"""Dense-array IO preparer — the inner loop of every snapshot.

TPU-native counterpart of /root/reference/torchsnapshot/io_preparers/tensor.py.
Where the reference stages with ``Tensor.to("cpu")`` in GIL-released
TorchScript (tensor.py:247-305,351-358), this preparer uses XLA's async
device→host DMA: ``jax.Array.copy_to_host_async()`` is enqueued at prepare
time so the DMA overlaps with scheduling, and the thread-pooled
``np.asarray`` in ``stage_buffer`` then finds the host copy ready (numpy
releases the GIL for the copy; the PJRT transfer releases it too).

Differences by design:
- JAX arrays are immutable, so the reference's in-place load
  (tensor.py:101,188-196) becomes: build a zero-copy numpy view over the
  read buffer and ``jax.device_put`` it with the restore target's
  sharding; for numpy targets we np.copyto in place.
- The async-snapshot defensive clone (tensor.py:281-305) is a host-side
  ``bytes()`` copy: on CPU backends ``np.asarray(jax_array)`` may alias
  the device buffer, which a donated update could overwrite.
"""

from __future__ import annotations

import asyncio
import math
from concurrent.futures import Executor
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..io_types import (
    BufferConsumer,
    BufferStager,
    BufferType,
    Future,
    ReadReq,
    WriteReq,
)
from ..manifest import TensorEntry
from ..serialization import (
    Serializer,
    array_as_memoryview,
    array_from_memoryview,
    dtype_to_string,
    tensor_nbytes,
)

ArrayLike = object  # jax.Array | np.ndarray


def array_nbytes(arr: ArrayLike) -> int:
    return int(np.prod(arr.shape)) * np.dtype(arr.dtype).itemsize if arr.shape else np.dtype(arr.dtype).itemsize


def is_supported_array_dtype(arr: ArrayLike) -> bool:
    try:
        dtype_to_string(arr.dtype)
        return True
    except ValueError:
        return False


def enqueue_dtoh(arr: ArrayLike) -> None:
    """Start the device→host DMA early (overlaps with scheduling).

    Host-offloaded arrays (host_offload.py, the UVM analog) skip the
    enqueue: their buffers already live in host memory, so staging is a
    plain view — the reference's uvm_to_cpu shortcut
    (io_preparers/tensor.py:257-259)."""
    from ..host_offload import is_host_resident

    if isinstance(arr, jax.Array) and not is_host_resident(arr):
        try:
            arr.copy_to_host_async()
        except Exception:
            pass  # some platforms/arrays don't support it; asarray will block


class ArrayBufferStager(BufferStager):
    def __init__(
        self,
        arr: ArrayLike,
        is_async_snapshot: bool = False,
        entry: Optional[TensorEntry] = None,
        array_prepare_func: Optional[Callable[[ArrayLike, bool], ArrayLike]] = None,
        dedup_entry: Optional[TensorEntry] = None,
        record_dedup_hashes: bool = False,
        compressible: bool = True,
    ) -> None:
        self.arr = arr
        self.is_async_snapshot = is_async_snapshot
        # Fused tile compression (tpusnap.compress): the take's policy
        # sets ``compress_codec`` on eligible stagers after batching;
        # staging then runs the fused shuffle+LZ4+dual-hash pass and
        # the staged buffer IS the compressed blob. ``compressible``
        # is construction-time eligibility: sharded shards opt out
        # (their restore path reads arbitrary overlap sub-ranges,
        # impossible at compressed-tile grain).
        self.compressible = compressible
        self.compress_codec: Optional[str] = None
        # Per-take clone-staging override, armed by the take after
        # batching (delta micro-commits force defensive clones: their
        # free-running captures cannot rendezvous with the training
        # thread, so COW's write-time verify would fail every commit).
        self.force_clone = False
        # Manifest entry to annotate with the stage-time checksum. The
        # manifest is gathered after staging completes, so the value lands
        # in the committed metadata.
        self.entry = entry
        # Incremental snapshots: the previous snapshot's entry for this
        # blob, locations already rewritten relative to the NEW snapshot
        # root. If the staged bytes hash to the same checksums, the write
        # is skipped and ``entry`` adopts the previous blob's location.
        self.dedup_entry = dedup_entry
        # Incremental takes record 64-bit per-tile dedup hashes so the
        # NEXT increment can make tile-grain skip decisions with more
        # than 32 bits of evidence (small tile-less blobs record theirs
        # eagerly on every take — see _record_checksums).
        self.record_dedup_hashes = record_dedup_hashes
        # Set by the take AFTER batching (non-incremental takes, any
        # world size): skip hashing at stage time; the write pipeline
        # calls late_checksum with the staged buffer instead — the hash
        # pass moves off the staging window async_take blocks training
        # on and overlaps other requests' disk time. Multi-process
        # manifests gather by value at staging-complete, so the late
        # values reach the commit via the barrier's KV store
        # (snapshot._LateChecksums). Incremental dedup needs hashes at
        # stage time and never defers.
        self.defer_checksums = False
        # Copy-on-write staging (TPUSNAP_ASYNC_COW, the default): set by
        # _stage_blocking when it returns the LIVE host bytes instead of
        # a defensive clone. The write pipeline then calls
        # verify_cow_after_write once the storage write completes; a
        # checksum mismatch (the caller mutated the array mid-take)
        # fails the take instead of committing torn data.
        self.cow_pending = False
        # User save-time transform (dtype cast / quantize-on-save),
        # applied to the ORIGINAL array at stage time with tracing=False
        # (reference io_preparers/tensor.py:231-241).
        self.array_prepare_func = array_prepare_func
        if array_prepare_func is None:
            # A transform usually changes the bytes; prefetching the
            # untransformed array's DtoH would be wasted DMA.
            enqueue_dtoh(arr)

    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        loop = asyncio.get_running_loop()
        if executor is not None:
            return await loop.run_in_executor(executor, self._stage_blocking)
        return self._stage_blocking()

    def _stage_blocking(self) -> BufferType:
        from ..knobs import is_checksum_disabled

        arr = self.arr
        if self.array_prepare_func is not None:
            arr = self.array_prepare_func(arr, False)  # tracing=False
            if self.entry is not None and (
                list(arr.shape) != list(self.entry.shape)
                or dtype_to_string(arr.dtype) != self.entry.dtype
            ):
                raise RuntimeError(
                    "_custom_array_prepare_func returned "
                    f"{arr.dtype}{list(arr.shape)} at stage time but "
                    f"{self.entry.dtype}{list(self.entry.shape)} was "
                    "recorded at prepare time — the transform must be "
                    "deterministic"
                )
        from .. import telemetry

        rec = telemetry.current()
        dtoh_t0 = (
            rec.now()
            if rec is not None and rec.enabled and not isinstance(arr, np.ndarray)
            else None
        )
        host = np.asarray(arr)  # DtoH (no-op if DMA already done)
        if dtoh_t0 is not None:
            rec.record_span("dtoh", dtoh_t0, rec.now() - dtoh_t0, bytes=host.nbytes)
        mv = array_as_memoryview(host)
        want_crc = self.entry is not None and not is_checksum_disabled()
        if self.compress_codec is not None and want_crc:
            # Fused tile compression: the staged buffer is the
            # compressed blob — fresh memory that never aliases the
            # live array, so async takes need neither the defensive
            # clone nor the COW write-time re-verify, and dedup (when
            # armed) compares hashes of the compressed bytes. Handles
            # its own dedup/skip decision.
            return self._stage_compressed(mv)
        if want_crc and self.dedup_entry is not None:
            # Incremental dedup: hash first (the expected outcome is
            # "unchanged", where no clone and no write happen at all).
            # A skip decision needs MORE than 32 bits of evidence per
            # unit of skipped data (ADVICE r4: tile CRCs alone leave a
            # single-CRC channel when the change is confined to one
            # tile), so the 64-bit lane rides the SAME fused memory
            # pass as the CRCs: record_dedup_hashes is always True when
            # dedup_entry is set (incremental takes force it,
            # snapshot.py), which is what arms the 64-bit side of the
            # match below. A base without recorded hashes
            # conservatively rewrites (dedup_entries_match).
            from ..io_types import SKIP_WRITE

            _record_checksums(self.entry, mv, self.record_dedup_hashes)
            if dedup_entries_match(self.entry, self.dedup_entry):
                self.entry.location = self.dedup_entry.location
                self.entry.byte_range = (
                    list(self.dedup_entry.byte_range)
                    if self.dedup_entry.byte_range is not None
                    else None
                )
                return SKIP_WRITE
            clone = self.is_async_snapshot and _may_alias_live_memory(
                self.arr, host
            )
            if clone:
                from ..knobs import is_async_cow_enabled

                if is_async_cow_enabled() and not self.force_clone:
                    # COW: checksums already recorded from the live
                    # bytes — skip the clone and verify at write time.
                    self.cow_pending = True
                    return mv
                from .. import _native
                from ..knobs import get_native_copy_threads

                out = _acquire_clone_buffer(mv.nbytes)
                # checksums already recorded
                _native.memcpy(out, mv, nthreads=get_native_copy_threads())
                return out
            return mv
        if self.is_async_snapshot and _may_alias_live_memory(self.arr, host):
            # Defensive clone: training resumes before I/O completes, and a
            # donated buffer could be overwritten under us. The native
            # memcpy releases the GIL (and parallelizes) for large clones
            # — and when checksums are on, the CRC is computed INSIDE the
            # clone pass (one read per byte instead of two), since the
            # clone is the async take's blocked time. In deferred mode
            # the clone is a plain memcpy and hashing happens on the
            # write path (late_checksum).
            from ..knobs import is_async_cow_enabled

            if want_crc and is_async_cow_enabled() and not self.force_clone:
                # COW (the default): no clone at all — record the fused hash
                # of the LIVE bytes now (overriding deferral: the
                # stage-time value is the mutation-detection reference)
                # and have the write pipeline re-verify after the
                # storage write. Frozen layers pay one read pass and
                # zero allocation inside the blocked window.
                _record_checksums(self.entry, mv, self.record_dedup_hashes)
                self.cow_pending = True
                return mv
            from .. import _native
            from ..knobs import get_native_copy_threads

            # Internal fan-out of each native pass is divided by the
            # executor thread count so the TOTAL copy-thread budget
            # stays constant (the ROADMAP 5 anomaly was this nesting).
            copy_threads = get_native_copy_threads()
            out = _acquire_clone_buffer(mv.nbytes)
            if want_crc and self.defer_checksums:
                _native.memcpy(out, mv, nthreads=copy_threads)
                return out
            if want_crc:
                tile_rows, row_nbytes = _tile_geometry(self.entry, mv.nbytes)
                want_dedup = _want_dedup_hashes(
                    self.record_dedup_hashes, tile_rows, mv.nbytes
                )
                if tile_rows:
                    if want_dedup:
                        crcs, xxhs = _native.memcpy_crc_xxh_tiles(
                            out, mv, tile_rows * row_nbytes,
                            nthreads=copy_threads,
                        )
                    else:
                        crcs = _native.memcpy_crc_tiles(
                            out, mv, tile_rows * row_nbytes,
                            nthreads=copy_threads,
                        )
                        xxhs = None
                    _annotate_checksums(
                        self.entry, crcs, tile_rows, row_nbytes, tile_xxhs=xxhs
                    )
                elif want_dedup:
                    # Tile-less blob needing the 64-bit dedup hash: XXH64
                    # has no combine, so the fused clone+hash runs as one
                    # tile (single-threaded copy; tile-less dedup-hashed
                    # blobs are small or rare (1, huge) shapes).
                    crcs, xxhs = _native.memcpy_crc_xxh_tiles(
                        out, mv, mv.nbytes
                    )
                    _annotate_checksums(
                        self.entry, crcs, 0, row_nbytes, whole_xxh=xxhs[0]
                    )
                else:
                    # Whole-blob checksum: still clone in internal
                    # sub-tiles so the copy parallelizes (a (1, huge)
                    # array maps to ONE checksum tile — without this the
                    # fused pass would run single-threaded), then fold
                    # the sub-tile values into the one recorded CRC.
                    sub = 16 << 20
                    crcs = _native.memcpy_crc_tiles(
                        out, mv, sub, nthreads=copy_threads
                    )
                    combined = _fold_crcs(
                        crcs, _tile_lengths(mv.nbytes, sub, len(crcs))
                    )
                    _annotate_checksums(
                        self.entry, [combined], 0, row_nbytes
                    )
            else:
                _native.memcpy(out, mv, nthreads=copy_threads)
            return out
        if want_crc and not self.defer_checksums:
            _record_checksums(self.entry, mv, self.record_dedup_hashes)
        return mv

    def late_checksum(self, buf) -> None:
        """Record checksums from the STAGED buffer — called by the write
        pipeline when ``defer_checksums`` is set (the buffer is stable:
        either the caller's own memory on a sync take or the defensive
        clone on an async one)."""
        from ..knobs import is_checksum_disabled

        if (
            self.entry is None
            or is_checksum_disabled()
            or self.entry.checksum is not None
        ):
            return
        _record_checksums(
            self.entry,
            memoryview(buf).cast("B"),
            self.record_dedup_hashes,
        )

    def verify_cow_after_write(self, buf) -> None:
        """COW staging: re-hash the live bytes AFTER the storage write
        and compare against the checksum recorded inside the blocked
        window. A mismatch means the caller mutated this array while
        the async take was in flight — the written blob may hold torn
        data, so the take fails here (the metadata is never committed)
        instead of silently snapshotting a state that never existed."""
        if self.entry is None or self.entry.checksum is None:
            return
        from .. import _native

        try:
            mv = memoryview(buf).cast("B")
            _native.verify_checksum(mv, self.entry.checksum, self.entry.location)
            self._verify_cow_xxh_lane(mv)
        except Exception as e:
            raise RuntimeError(
                f"async COW take detected a concurrent mutation of "
                f"{self.entry.location!r}: the array changed between "
                "staging and its storage write. Under TPUSNAP_ASYNC_COW "
                "the live bytes stay aliased until each blob's write "
                "completes — mutate state only after "
                "PendingSnapshot.wait_staged()/wait() returns (both are "
                "COW-aware and block until the writes drain), or unset "
                "TPUSNAP_ASYNC_COW to restore defensive cloning."
            ) from e

    def _verify_cow_xxh_lane(self, mv) -> None:
        """Re-verify the 64-bit XXH64 dedup lane too, when recorded
        (incremental takes, small eagerly-hashed blobs) — the CRC32C
        lane alone is 32 bits of mutation evidence; with the dedup lane
        the pair matches what dedup skips require. Lanes recorded by a
        different build's algorithm are skipped, mirroring
        verify_checksum's policy."""
        entry = self.entry
        from .. import _native

        dalgo = _native.dedup_hash_algorithm()
        if entry.dedup_hash is not None:
            algo, _, val = entry.dedup_hash.partition(":")
            if algo == dalgo and int(val, 16) != _native.xxh64(mv):
                raise _native.ChecksumError(
                    f"XXH64 lane mismatch for {entry.location!r}"
                )
            return
        if not entry.tile_dedup_hashes:
            return
        tile_rows, row_nbytes = _tile_geometry(entry, mv.nbytes)
        if not tile_rows:
            return
        tile_nbytes = tile_rows * row_nbytes
        for i, recorded in enumerate(entry.tile_dedup_hashes):
            algo, _, val = recorded.partition(":")
            if algo != dalgo:
                return
            tile = mv[i * tile_nbytes : (i + 1) * tile_nbytes]
            if int(val, 16) != _native.xxh64(tile):
                raise _native.ChecksumError(
                    f"XXH64 tile {i} mismatch for {entry.location!r}"
                )

    def _stage_compressed(self, mv: memoryview) -> BufferType:
        """Fused shuffle+LZ4+dual-hash staging pass: one read of the
        live bytes, compressed tiles + their checksums/dedup hashes out
        (all recorded over the STORED bytes — the journal/salvage/
        upload-journal evidence rule holds unchanged). The staged
        buffer is copied to a right-sized pool buffer so resident bytes
        match what the scheduler's budget credits back."""
        from .. import _native, telemetry
        from ..compress import codec_elem
        from ..knobs import get_native_copy_threads

        codec = self.compress_codec
        entry = self.entry
        tile_rows, row_nbytes = _tile_geometry(entry, mv.nbytes)
        tile_nbytes = tile_rows * row_nbytes if tile_rows else mv.nbytes
        want_dedup = _want_dedup_hashes(
            self.record_dedup_hashes, tile_rows, mv.nbytes
        ) or self.dedup_entry is not None
        rec = telemetry.current()
        # Raw-hash fast skip: an unchanged blob must cost a multi-GB/s
        # hash pass, not a codec pass (a mostly-frozen model streaming
        # micro-commits over a slow pipe would otherwise re-compress
        # the whole model per cadence interval to write ~zero bytes).
        # The codec is deterministic, so equal RAW bytes imply equal
        # stored bytes — the base's recorded dual raw hash (96 bits,
        # stronger than the 64-bit skip-evidence floor) licenses
        # adopting its stored blob and every recorded field wholesale.
        raw_hash = _raw_dual_hash(mv) if want_dedup else None
        prev = self.dedup_entry
        if (
            prev is not None
            and raw_hash is not None
            and getattr(prev, "uncompressed_dedup_hash", None) == raw_hash
            and getattr(prev, "codec", None) == codec
            and prev.checksum is not None
            and prev.dtype == entry.dtype
            and list(prev.shape) == list(entry.shape)
            and prev.serializer == entry.serializer
        ):
            from ..io_types import SKIP_WRITE

            entry.location = prev.location
            entry.byte_range = (
                list(prev.byte_range)
                if prev.byte_range is not None
                else None
            )
            _annotate_from_dedup_base(entry, prev)
            telemetry.incr("compress.raw_dedup_skips", rec=rec)
            return SKIP_WRITE
        t0 = rec.now() if rec is not None else 0.0
        out, comp_sizes, crcs, xxhs = _native.compress_tiles(
            mv,
            tile_nbytes,
            codec_elem(codec),
            want_dedup,
            nthreads=get_native_copy_threads(),
        )
        if rec is not None:
            rec.record_span(
                "compress",
                t0,
                rec.now() - t0,
                bytes=mv.nbytes,
                out_bytes=out.nbytes,
                codec=codec,
            )
        telemetry.incr("compress.bytes_in", mv.nbytes, rec=rec)
        telemetry.incr("compress.bytes_out", out.nbytes, rec=rec)
        telemetry.incr("compress.blobs", rec=rec)
        _annotate_compressed(
            entry, codec, mv.nbytes, comp_sizes, crcs, tile_rows, xxhs
        )
        if raw_hash is not None:
            # Write-skip evidence for the NEXT incremental take's
            # raw-hash fast path (see above) — never storage evidence.
            entry.uncompressed_dedup_hash = raw_hash
        if self.dedup_entry is not None and dedup_entries_match(
            entry, self.dedup_entry
        ):
            # Deterministic codec: unchanged input bytes yield identical
            # compressed bytes, so the compressed-hash comparison is as
            # strong as the uncompressed one (a base written by a
            # different codec/build conservatively rewrites — codec is
            # part of the match identity).
            from ..io_types import SKIP_WRITE

            entry.location = self.dedup_entry.location
            entry.byte_range = (
                list(self.dedup_entry.byte_range)
                if self.dedup_entry.byte_range is not None
                else None
            )
            return SKIP_WRITE
        # `out` slices a worst-case-bound allocation; re-home the
        # compressed bytes in a right-sized (aligned, O_DIRECT-ready)
        # pool buffer so the big bound buffer is not pinned until the
        # storage write drains.
        final = _acquire_clone_buffer(out.nbytes)
        _native.memcpy(final, out, nthreads=get_native_copy_threads())
        return final

    def get_staging_cost_bytes(self) -> int:
        n = self.get_planned_bytes()
        if self.compress_codec is not None:
            # Compressed staging transiently holds the worst-case-bound
            # output buffer plus the right-sized staged copy; 2x the
            # payload bounds both (and matches the async-clone model).
            return 2 * n
        if self.is_async_snapshot:
            from ..knobs import is_async_cow_enabled, is_checksum_disabled

            if (
                is_async_cow_enabled()
                and not self.force_clone
                and self.entry is not None
                and not is_checksum_disabled()
            ):
                # COW staging (same conditions as _stage_blocking's COW
                # branches): no second host copy is ever held — the
                # live bytes are written directly and verified by hash.
                return n
            # Defensive clone: a second host copy while in flight.
            return 2 * n
        return n

    def get_planned_bytes(self) -> int:
        """Payload bytes (the progress denominator) — never doubled by
        the async clone's staging-cost accounting."""
        if self.array_prepare_func is not None and self.entry is not None:
            # What will actually be staged is the transformed array.
            return tensor_nbytes(self.entry.dtype, self.entry.shape)
        return array_nbytes(self.arr)


# platform name -> does np.asarray of a device array ALIAS the XLA
# buffer (vs materializing a fresh host copy)? Probed empirically once
# per backend (VERDICT r4: a hardcoded platform assumption here decides
# whether every async take pays a full clone pass).
_ASARRAY_ALIASES_BY_PLATFORM: dict = {}


def _asarray_aliases_device_buffer(device) -> bool:
    """Probe whether ``np.asarray`` of an array on ``device`` returns a
    VIEW of the XLA buffer (CPU backends: zero-copy, so donation could
    overwrite it) or a fresh host copy (real TPU/GPU: DtoH materializes
    new host memory donation never touches). Compares the host array's
    data pointer against the device buffer's; platforms whose runtime
    can't report a buffer pointer (e.g. remote/proxied PJRT) fall back
    to the platform heuristic — only local "cpu" aliases."""
    platform = getattr(device, "platform", "unknown")
    cached = _ASARRAY_ALIASES_BY_PLATFORM.get(platform)
    if cached is not None:
        return cached
    try:
        probe = jax.device_put(np.arange(32, dtype=np.uint8), device)
        host = np.asarray(probe)
        aliases = bool(
            host.__array_interface__["data"][0]
            == probe.unsafe_buffer_pointer()
        )
    except Exception:
        aliases = platform == "cpu"
    _ASARRAY_ALIASES_BY_PLATFORM[platform] = aliases
    return aliases


def _may_alias_live_memory(arr: ArrayLike, host: np.ndarray) -> bool:
    """Whether the staged host buffer could alias memory the training
    loop may overwrite (donation) — if so, an async snapshot must clone
    it before returning control.

    On NON-CPU backends (TPU/GPU) the answer is no: ``np.asarray`` of a
    device array materializes a fresh host copy via DtoH — donation
    reuses device HBM, never that host buffer — so async takes on real
    accelerators skip the defensive clone entirely and their blocked
    time is DMA alone (non-incremental takes at any world size defer
    the hash to the write path; multi-process manifests receive the
    late values via the commit barrier's KV store — see
    snapshot._LateChecksums). Rather than
    trusting the platform name, the aliasing behavior is PROBED once
    per backend (``_asarray_aliases_device_buffer``). Host-resident
    (pinned_host, the UVM analog) arrays alias host memory on any
    backend, and numpy sources alias the caller's array by
    construction — those always clone."""
    if isinstance(arr, jax.Array):
        from ..host_offload import is_host_resident

        if is_host_resident(arr):
            return True
        try:
            return any(
                _asarray_aliases_device_buffer(d) for d in arr.devices()
            )
        except Exception:
            return True
    return True


def _acquire_clone_buffer(nbytes: int):
    """Aligned buffer for the async defensive clone, from the staging
    pool: steady-state checkpoint loops reuse warm pages instead of
    paying ~1 GB/s first-touch page zeroing per take (the dominant cost
    of the blocked window on CPU-backend hosts). The write pipeline
    returns it to the pool after the write."""
    from .._staging_pool import acquire

    return acquire(nbytes)


def writable_byte_view(
    arr: Optional[ArrayLike], dtype: str, shape: Sequence[int]
) -> Optional[memoryview]:
    """Flat writable byte view over ``arr`` when the stored blob's bytes
    may land there verbatim: numpy, writable, C-contiguous, exact
    dtype/shape match. Used as the destination of in-place reads — the
    storage plugin DMAs straight into the restore target and the
    deserialize+copy pass disappears."""
    if not isinstance(arr, np.ndarray):
        return None
    if not (arr.flags.writeable and arr.flags.c_contiguous):
        return None
    try:
        if dtype_to_string(arr.dtype) != dtype or list(arr.shape) != list(shape):
            return None
    except ValueError:
        return None
    try:
        mv = array_as_memoryview(arr)
    except ValueError:
        return None
    # array_as_memoryview copies non-contiguous inputs; contiguity was
    # checked above, so this view aliases arr's memory.
    return mv if not mv.readonly else None


def _want_crc(entry: TensorEntry) -> bool:
    from ..knobs import is_checksum_disabled

    return entry.checksum is not None and not is_checksum_disabled()


def dedup_entries_match(new: TensorEntry, prev: TensorEntry) -> bool:
    """True when the freshly staged blob (``new``, checksums recorded) is
    byte-identical to the previous snapshot's blob per its recorded
    checksums — same dtype/shape/serializer, same whole-blob CRC, and the
    same tile-grain CRCs (a changed tile-size knob between takes makes
    geometries differ and conservatively fails the match).

    Equality needs MORE than one 32-bit CRC of evidence per unit of
    skipped data (ADVICE r3/r4: a changed blob whose CRC collides with
    the base's silently restores stale data, a ~2^-32 channel per
    blob-take at fleet scale — and a change confined to ONE tile rests
    on that tile's single CRC, however many unchanged tiles also
    match): tiled blobs must carry matching 64-bit per-tile
    ``tile_dedup_hashes`` on BOTH sides, and tile-less blobs a matching
    64-bit ``dedup_hash`` on BOTH sides — a base without the hashes
    (older format, non-incremental take, or a blob above the eager-hash
    size) conservatively rewrites."""
    if not (
        prev.checksum is not None
        and new.checksum == prev.checksum
        and new.dtype == prev.dtype
        and list(new.shape) == list(prev.shape)
        and new.serializer == prev.serializer
        and new.tile_rows == prev.tile_rows
        and new.tile_checksums == prev.tile_checksums
        # Compressed blobs: hashes are over STORED bytes, so identity
        # includes the codec and the stored layout — a codec change
        # between takes (or compressed vs raw) conservatively rewrites.
        and getattr(new, "codec", None) == getattr(prev, "codec", None)
        and getattr(new, "comp_tile_sizes", None)
        == getattr(prev, "comp_tile_sizes", None)
    ):
        return False
    if new.tile_checksums:
        return bool(
            new.tile_dedup_hashes
            and prev.tile_dedup_hashes
            and new.tile_dedup_hashes == prev.tile_dedup_hashes
        )
    return (
        new.dedup_hash is not None
        and prev.dedup_hash is not None
        and new.dedup_hash == prev.dedup_hash
    )


def _tile_lengths(nbytes: int, tile_nbytes: int, n_tiles: int) -> List[int]:
    """Byte length of each of ``n_tiles`` consecutive tiles of
    ``tile_nbytes`` covering ``nbytes`` (last tile short)."""
    return [
        min((i + 1) * tile_nbytes, nbytes) - i * tile_nbytes
        for i in range(n_tiles)
    ]


def _fold_crcs(crcs: List[int], lengths: List[int]) -> int:
    """Combine per-tile seed-0 CRC values (with their byte lengths) into
    the CRC of the concatenation — the ONE fold used by every writer and
    verifier, so their boundary math cannot drift apart."""
    from .. import _native

    combined = crcs[0] & 0xFFFFFFFF
    for c, ln in zip(crcs[1:], lengths[1:]):
        combined = _native.crc_combine(combined, c & 0xFFFFFFFF, ln)
    return combined & 0xFFFFFFFF


def _tile_geometry(entry: TensorEntry, nbytes: int) -> Tuple[int, int]:
    """(tile_rows, row_nbytes) for tile-grain checksums of this entry's
    bytes, with tile_rows == 0 when the blob gets one whole-blob value.
    Shared by the sync hash pass and the async fused clone+hash pass so
    both record byte-identical manifests."""
    from ..knobs import get_tile_checksum_bytes

    shape = entry.shape
    n_rows = shape[0] if shape else 0
    row_nbytes = nbytes // n_rows if n_rows else 0
    tile_rows = (
        max(1, get_tile_checksum_bytes() // row_nbytes) if row_nbytes else 0
    )
    if n_rows > tile_rows >= 1:
        return tile_rows, row_nbytes
    return 0, row_nbytes


# Tile-less blobs at or below this size record their 64-bit dedup hash
# on EVERY take (cheap; lets the first increment against any base dedup
# them). Larger tile-less blobs — rare (1, huge)-shaped arrays whose
# hash pass is a real cost — record it only on incremental takes.
_DEDUP_HASH_EAGER_MAX = 64 << 20


def _want_dedup_hashes(record_flag: bool, tile_rows: int, nbytes: int) -> bool:
    if tile_rows:
        return record_flag
    return record_flag or nbytes <= _DEDUP_HASH_EAGER_MAX


def _annotate_checksums(
    entry: TensorEntry,
    tile_crcs: List[int],
    tile_rows: int,
    row_nbytes: int,
    tile_xxhs: Optional[List[int]] = None,
    whole_xxh: Optional[int] = None,
) -> None:
    """Record per-tile + combined whole-blob checksums into ``entry``
    from raw seed-0 CRC values (one per tile, or a single whole-blob
    value when ``tile_rows`` is 0), plus the optional 64-bit dedup
    hashes (per tile, or whole-blob)."""
    from .. import _native

    algo = _native.checksum_algorithm()
    if tile_rows:
        n_rows = entry.shape[0]
        combined = _fold_crcs(
            tile_crcs,
            _tile_lengths(
                n_rows * row_nbytes, tile_rows * row_nbytes, len(tile_crcs)
            ),
        )
        entry.tile_rows = tile_rows
        entry.tile_checksums = [
            f"{algo}:{crc & 0xFFFFFFFF:08x}" for crc in tile_crcs
        ]
        entry.checksum = f"{algo}:{combined:08x}"
        if tile_xxhs is not None:
            dalgo = _native.dedup_hash_algorithm()
            entry.tile_dedup_hashes = [
                f"{dalgo}:{x & _XXH_MASK:016x}" for x in tile_xxhs
            ]
    else:
        entry.checksum = f"{algo}:{tile_crcs[0] & 0xFFFFFFFF:08x}"
        if whole_xxh is not None:
            dalgo = _native.dedup_hash_algorithm()
            entry.dedup_hash = f"{dalgo}:{whole_xxh & _XXH_MASK:016x}"


_XXH_MASK = (1 << 64) - 1


def _annotate_compressed(
    entry: TensorEntry,
    codec: str,
    raw_nbytes: int,
    comp_sizes: List[int],
    tile_crcs: List[int],
    tile_rows: int,
    tile_xxhs: Optional[List[int]] = None,
) -> None:
    """Record the compressed-blob manifest fields: codec identity,
    logical size, per-tile stored sizes, and checksums/dedup hashes
    computed over the STORED (compressed) bytes — the whole-blob value
    is the CRC combine over the compressed tile lengths, so scrub, the
    journal's written-bytes evidence and restore verification all agree
    byte-for-byte with what is on disk."""
    from .. import _native

    algo = _native.checksum_algorithm()
    entry.codec = codec
    entry.uncompressed_nbytes = raw_nbytes
    entry.comp_tile_sizes = [int(s) for s in comp_sizes]
    if tile_rows:
        entry.tile_rows = tile_rows
        entry.tile_checksums = [
            f"{algo}:{crc & 0xFFFFFFFF:08x}" for crc in tile_crcs
        ]
        entry.checksum = (
            f"{algo}:{_fold_crcs(tile_crcs, entry.comp_tile_sizes):08x}"
        )
        if tile_xxhs is not None:
            dalgo = _native.dedup_hash_algorithm()
            entry.tile_dedup_hashes = [
                f"{dalgo}:{x & _XXH_MASK:016x}" for x in tile_xxhs
            ]
    else:
        entry.checksum = f"{algo}:{tile_crcs[0] & 0xFFFFFFFF:08x}"
        if tile_xxhs is not None:
            dalgo = _native.dedup_hash_algorithm()
            entry.dedup_hash = f"{dalgo}:{tile_xxhs[0] & _XXH_MASK:016x}"


def _raw_dual_hash(mv: memoryview) -> str:
    """Dual hash of a compressed stager's RAW payload bytes —
    ``uncompressed_dedup_hash`` write-skip evidence. One fused-speed
    read per algorithm; only computed on dedup-recording takes."""
    from .. import _native

    algo = _native.checksum_algorithm()
    crc = _native.crc32c(mv) & 0xFFFFFFFF
    xxh = _native.xxh64(mv) & _XXH_MASK
    return f"{algo}:{crc:08x}+xxh64:{xxh:016x}"


def _annotate_from_dedup_base(entry: TensorEntry, prev: TensorEntry) -> None:
    """A raw-hash fast skip never ran the codec, so the entry adopts
    the base's recorded representation wholesale — codec identity,
    stored layout and every stored-bytes integrity field. The codec is
    deterministic, so these are byte-identical to what re-compressing
    would have produced."""
    entry.codec = prev.codec
    entry.uncompressed_nbytes = prev.uncompressed_nbytes
    entry.comp_tile_sizes = (
        list(prev.comp_tile_sizes)
        if prev.comp_tile_sizes is not None
        else None
    )
    entry.tile_rows = prev.tile_rows
    entry.checksum = prev.checksum
    entry.tile_checksums = (
        list(prev.tile_checksums)
        if prev.tile_checksums is not None
        else None
    )
    entry.dedup_hash = prev.dedup_hash
    entry.tile_dedup_hashes = (
        list(prev.tile_dedup_hashes)
        if prev.tile_dedup_hashes is not None
        else None
    )
    entry.uncompressed_dedup_hash = prev.uncompressed_dedup_hash


def _record_checksums(
    entry: TensorEntry, mv: memoryview, record_dedup_hashes: bool = False
) -> None:
    """Record integrity checksums into ``entry`` at stage time.

    Blobs large enough to be read under a memory budget are hashed in
    row-tiles (``tile_rows``/``tile_checksums``) and the whole-blob value
    derived by CRC combine — one hash pass either way. Budget-tiled
    reads align to these boundaries and verify by combining the covered
    tiles' values (beyond the reference, which has no end-to-end
    integrity checking at all).

    ``record_dedup_hashes`` (incremental takes) additionally records the
    64-bit XXH64 dedup hashes — per tile, fused into the same memory
    pass — so the next increment's dedup decisions carry more than 32
    bits of evidence per skipped unit. Small tile-less blobs record
    theirs on every take (see _DEDUP_HASH_EAGER_MAX)."""
    from .. import telemetry

    with telemetry.span("checksum", bytes=mv.nbytes):
        _record_checksums_impl(entry, mv, record_dedup_hashes)


def _record_checksums_impl(
    entry: TensorEntry, mv: memoryview, record_dedup_hashes: bool
) -> None:
    from .. import _native
    from ..knobs import get_native_copy_threads

    tile_rows, row_nbytes = _tile_geometry(entry, mv.nbytes)
    want_dedup = _want_dedup_hashes(record_dedup_hashes, tile_rows, mv.nbytes)
    if tile_rows:
        n_rows = entry.shape[0]
        if want_dedup:
            # Tile boundaries are uniform except the last; the fused
            # native pass tiles by byte count, which matches exactly.
            # Internal fan-out divided by the stage-thread count — the
            # dedup hash pass is the hot pass of every delta-stream
            # micro-commit and must honor the same total-copy-thread
            # budget as the clone passes.
            crcs, xxhs = _native.crc_xxh_tiles(
                mv, tile_rows * row_nbytes,
                nthreads=get_native_copy_threads(),
            )
            _annotate_checksums(
                entry, crcs, tile_rows, row_nbytes, tile_xxhs=xxhs
            )
            return
        crcs = [
            _native.crc32c(
                mv[r0 * row_nbytes : min(r0 + tile_rows, n_rows) * row_nbytes]
            )
            for r0 in range(0, n_rows, tile_rows)
        ]
        _annotate_checksums(entry, crcs, tile_rows, row_nbytes)
        return
    if want_dedup:
        crcs, xxhs = _native.crc_xxh_tiles(
            mv, mv.nbytes, nthreads=get_native_copy_threads()
        )
        _annotate_checksums(entry, crcs, 0, row_nbytes, whole_xxh=xxhs[0])
        return
    _annotate_checksums(entry, [_native.crc32c(mv)], 0, row_nbytes)


def combined_tile_checksum(
    entry: TensorEntry, r0: int, r1: int, row_nbytes: int
) -> Optional[str]:
    """Expected checksum of rows [r0, r1) derived from recorded tile
    checksums, or None when the range is not verifiable (no tiles
    recorded, boundaries misaligned, or the snapshot was written by a
    build with a different checksum algorithm — combining with the wrong
    polynomial would manufacture false corruption reports)."""
    from .. import _native

    t = entry.tile_rows
    if not entry.tile_checksums or not t:
        return None
    n_rows = entry.shape[0]
    if r0 % t != 0 or (r1 != n_rows and r1 % t != 0):
        return None
    algo = _native.checksum_algorithm()
    crcs: List[int] = []
    lengths: List[int] = []
    for i in range(r0 // t, math.ceil(r1 / t)):
        tile = entry.tile_checksums[i]
        tile_algo, _, value = tile.partition(":")
        if tile_algo != algo:
            return None
        try:
            crcs.append(int(value, 16))
        except ValueError:
            return None
        tr1 = min((i + 1) * t, n_rows)
        lengths.append((tr1 - i * t) * row_nbytes)
    if not crcs:
        return None
    return f"{algo}:{_fold_crcs(crcs, lengths):08x}"


class ArrayBufferConsumer(BufferConsumer):
    """Deserializes into the restore target. For jax targets the result is
    device_put with the target's sharding; numpy targets are filled in
    place (the reference's in-place load, tensor.py:188-196) — and when
    the storage plugin supports it, the read lands in the target's own
    memory with the checksum computed inside the read (``consume_read_io``
    then verifies a 4-byte value and the consume stage does no data pass
    at all)."""

    def __init__(
        self,
        entry: TensorEntry,
        obj_out: Optional[ArrayLike],
        fut: Future,
        verify_location: str = "",
    ):
        self.entry = entry
        self.obj_out = obj_out
        self.fut = fut
        self.verify_location = verify_location or entry.location
        self.into_mv = writable_byte_view(obj_out, entry.dtype, entry.shape)

    async def consume_read_io(self, read_io, executor: Optional[Executor] = None) -> None:
        if read_io.in_place:
            self._finalize_in_place(read_io)
            return
        await self.consume_buffer(read_io.buf.getbuffer(), executor)

    def _finalize_in_place(self, read_io) -> None:
        # Bytes are already in obj_out's memory; only verify the read-time
        # checksum against the manifest (an int compare, no data pass).
        if self.entry.checksum is not None and read_io.crc32c is not None:
            from .. import _native

            _native.verify_checksum_value(
                read_io.crc32c,
                read_io.crc_algo,
                self.entry.checksum,
                self.verify_location,
            )
        self.fut.obj = self.obj_out

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        loop = asyncio.get_running_loop()
        if executor is not None:
            await loop.run_in_executor(executor, self._consume_blocking, buf)
        else:
            self._consume_blocking(buf)

    def _consume_blocking(self, buf: BufferType) -> None:
        _maybe_verify(buf, self.entry.checksum, self.verify_location)
        value = materialize_array(self.entry, buf, self.obj_out)
        self.fut.obj = value

    def get_consuming_cost_bytes(self) -> int:
        return tensor_nbytes(self.entry.dtype, self.entry.shape)


def _maybe_verify(buf: BufferType, checksum: Optional[str], location: str) -> None:
    """Verify a read buffer against a manifest checksum (knob-gated).
    Callers reading a sub-range of an entry's bytes pass the combined
    tile checksum for that range (``combined_tile_checksum``), or None
    when the range is not verifiable."""
    if checksum is None:
        return
    from ..knobs import is_checksum_disabled

    if is_checksum_disabled():
        return
    from .. import _native

    _native.verify_checksum(memoryview(buf).cast("B"), checksum, location)


def _owning_copy(src: np.ndarray) -> np.ndarray:
    """A copy of ``src`` that owns its memory, faulted as hugepages when
    large (np.copy would first-touch a multi-GB destination 4 KiB at a
    time, which on few-core hosts rivals the I/O cost)."""
    from .. import _native

    out = _native.empty_advised(src.shape, src.dtype)
    np.copyto(out, src)
    return out


def finalize_into_target(
    host: np.ndarray, obj_out: Optional[ArrayLike], owns_memory: bool
) -> ArrayLike:
    """Land a deserialized host array in the restore target — the ONE
    implementation of the cast-into-target semantics (the reference's
    tensor_copy, io_preparers/tensor.py:383-403) shared by the
    whole-blob and tiled/chunked paths:

    - same-shape writable numpy target: filled IN PLACE, cast to the
      target's dtype when it differs (a bf16-saved blob restores into an
      f32 training target upcast);
    - jax target: ``device_put`` with the target's sharding at the
      STORED dtype (half the HtoD bytes for reduced-precision saves),
      then cast ON DEVICE to the target's dtype when it differs;
    - otherwise: a host array owning its memory (``owns_memory`` says
      whether ``host`` already does, or aliases a transient read
      buffer)."""
    if isinstance(obj_out, np.ndarray):
        if obj_out.shape == host.shape and obj_out.flags.writeable:
            np.copyto(obj_out, host, casting="unsafe")
            return obj_out
        return host if owns_memory else _owning_copy(host)
    if isinstance(obj_out, jax.Array):
        # device_put is async; XLA overlaps the HtoD DMA with further
        # reads. The dtype cast (if any) runs on the accelerator with
        # the sharding preserved — not as a host pass that would double
        # the transfer volume.
        dev = jax.device_put(host, obj_out.sharding)
        if obj_out.dtype != dev.dtype and obj_out.shape == dev.shape:
            dev = dev.astype(obj_out.dtype)
        return dev
    return host if owns_memory else _owning_copy(host)


def materialize_array(
    entry: TensorEntry, buf: BufferType, obj_out: Optional[ArrayLike]
) -> ArrayLike:
    src = array_from_memoryview(memoryview(buf), entry.dtype, entry.shape)
    # `src` aliases the read buffer (about to be released) — any bare
    # return must copy.
    return finalize_into_target(src, obj_out, owns_memory=False)


def trace_array_prepare(
    arr: ArrayLike,
    array_prepare_func: Optional[Callable[[ArrayLike, bool], ArrayLike]],
) -> Tuple[str, List[int]]:
    """The (dtype, shape) the manifest must record for ``arr`` under an
    optional save-time transform — discovered WITHOUT computing the
    transform when possible: jax transforms are traced via
    ``jax.eval_shape`` (abstract evaluation, zero FLOPs — the TPU-first
    analog of the reference's tracing=True call on a real tensor,
    io_preparers/tensor.py:57-66); non-traceable transforms fall back to
    one real call whose result is discarded. Shape changes are rejected
    like the reference's."""
    if array_prepare_func is None:
        return dtype_to_string(arr.dtype), list(arr.shape)
    import functools

    try:
        traced = jax.eval_shape(
            functools.partial(array_prepare_func, tracing=True), arr
        )
    except Exception:
        traced = array_prepare_func(arr, True)  # tracing=True
    if list(traced.shape) != list(arr.shape):
        raise RuntimeError(
            "_custom_array_prepare_func must not change the array's "
            f"shape (changed from {list(arr.shape)} to {list(traced.shape)})"
        )
    return dtype_to_string(traced.dtype), list(traced.shape)


class ArrayIOPreparer:
    """prepare_write/prepare_read for dense (single-blob) arrays
    (reference TensorIOPreparer, io_preparers/tensor.py:47-222)."""

    @staticmethod
    def prepare_write(
        storage_path: str,
        arr: ArrayLike,
        replicated: bool = False,
        is_async_snapshot: bool = False,
        array_prepare_func: Optional[Callable[[ArrayLike, bool], ArrayLike]] = None,
        array_prepare_traced: Optional[Tuple[str, List[int]]] = None,
        prev_entry: Optional[object] = None,
        record_dedup_hashes: bool = False,
    ) -> Tuple[TensorEntry, List[WriteReq]]:
        if array_prepare_traced is not None:
            dtype, shape = array_prepare_traced[0], list(array_prepare_traced[1])
        else:
            dtype, shape = trace_array_prepare(arr, array_prepare_func)
        entry = TensorEntry(
            location=storage_path,
            serializer=Serializer.BUFFER_PROTOCOL.value,
            dtype=dtype,
            shape=shape,
            replicated=replicated,
        )
        write_reqs = [
            WriteReq(
                path=storage_path,
                buffer_stager=ArrayBufferStager(
                    arr,
                    is_async_snapshot,
                    entry=entry,
                    array_prepare_func=array_prepare_func,
                    dedup_entry=(
                        prev_entry
                        if isinstance(prev_entry, TensorEntry)
                        else None
                    ),
                    record_dedup_hashes=record_dedup_hashes,
                ),
            )
        ]
        return entry, write_reqs

    @staticmethod
    def prepare_read(
        entry: TensorEntry,
        obj_out: Optional[ArrayLike] = None,
        buffer_size_limit_bytes: Optional[int] = None,
        logical_path: str = "",
    ) -> Tuple[List[ReadReq], Future]:
        fut: Future = Future()
        if entry.codec:
            return ArrayIOPreparer._prepare_compressed_read(
                entry, obj_out, buffer_size_limit_bytes, fut, logical_path
            )
        nbytes = tensor_nbytes(entry.dtype, entry.shape)
        if (
            buffer_size_limit_bytes is not None
            and nbytes > buffer_size_limit_bytes
            and len(entry.shape) > 0
            and entry.shape[0] > 1
        ):
            return ArrayIOPreparer._prepare_tiled_read(
                entry, obj_out, buffer_size_limit_bytes, fut, logical_path
            )
        byte_range = tuple(entry.byte_range) if entry.byte_range is not None else None
        consumer = ArrayBufferConsumer(
            entry, obj_out, fut, verify_location=logical_path
        )
        read_reqs = [
            ReadReq(
                path=entry.location,
                byte_range=byte_range,
                buffer_consumer=consumer,
                into=consumer.into_mv,
                want_crc=consumer.into_mv is not None and _want_crc(entry),
                logical_path=logical_path,
            )
        ]
        return read_reqs, fut

    @staticmethod
    def _prepare_tiled_read(
        entry: TensorEntry,
        obj_out: Optional[ArrayLike],
        buffer_size_limit_bytes: int,
        fut: Future,
        logical_path: str = "",
    ) -> Tuple[List[ReadReq], Future]:
        """Split one tensor read into byte-ranged row tiles so peak host
        memory stays under the budget (reference tensor.py:126-179).

        The tiles are copied into one preallocated host array; the future
        resolves when the last tile lands. When the entry carries
        tile-grain checksums, read tiles are aligned to the recorded
        boundaries and each verified against the combined tile values —
        memory-budgeted reads detect corruption like whole-blob reads do.
        """
        shape = entry.shape
        row_nbytes = tensor_nbytes(entry.dtype, shape[1:]) if len(shape) > 1 else tensor_nbytes(entry.dtype, [1])
        rows_per_tile = max(1, buffer_size_limit_bytes // max(row_nbytes, 1))
        n_rows = shape[0]
        from ..knobs import is_checksum_disabled

        verify_tiles = (
            bool(entry.tile_checksums and entry.tile_rows)
            and not is_checksum_disabled()
        )
        if verify_tiles:
            if rows_per_tile >= entry.tile_rows:
                # Round down to a multiple of the checksum tile.
                rows_per_tile = (
                    rows_per_tile // entry.tile_rows
                ) * entry.tile_rows
            else:
                # Integrity over budget: the recorded tile is the minimum
                # verifiable read unit (16 MiB-class by default).
                rows_per_tile = entry.tile_rows

        # Preallocated host destination; tiles land in place.
        if isinstance(obj_out, np.ndarray) and (
            dtype_to_string(obj_out.dtype) == entry.dtype
            and list(obj_out.shape) == list(shape)
            and obj_out.flags.writeable
        ):
            host_out = obj_out
            in_place = True
        else:
            from ..serialization import string_to_dtype
            from .. import _native

            # Fresh multi-GB destination: fault as hugepages, not 4 KiB
            # pages — first-touch cost during the tile reads otherwise
            # rivals the I/O itself on few-core hosts.
            host_out = _native.empty_advised(shape, string_to_dtype(entry.dtype))
            in_place = False

        base_offset = entry.byte_range[0] if entry.byte_range is not None else 0
        n_tiles = math.ceil(n_rows / rows_per_tile)
        remaining = {"count": n_tiles}
        read_reqs = []
        for t in range(n_tiles):
            r0 = t * rows_per_tile
            r1 = min(r0 + rows_per_tile, n_rows)
            start = base_offset + r0 * row_nbytes
            end = base_offset + r1 * row_nbytes
            tile_checksum = (
                combined_tile_checksum(entry, r0, r1, row_nbytes)
                if verify_tiles
                else None
            )
            consumer = _TileConsumer(
                entry,
                host_out,
                r0,
                r1,
                remaining,
                fut,
                obj_out,
                in_place,
                blob_checksum=tile_checksum,
                blob_location=(
                    f"{logical_path or entry.location} (rows {r0}:{r1})"
                ),
            )
            read_reqs.append(
                ReadReq(
                    path=entry.location,
                    byte_range=(start, end),
                    buffer_consumer=consumer,
                    into=consumer.into_mv,
                    want_crc=consumer.into_mv is not None
                    and tile_checksum is not None,
                    logical_path=logical_path,
                )
            )
        return read_reqs, fut


    @staticmethod
    def _prepare_compressed_read(
        entry: TensorEntry,
        obj_out: Optional[ArrayLike],
        buffer_size_limit_bytes: Optional[int],
        fut: Future,
        logical_path: str = "",
    ) -> Tuple[List[ReadReq], Future]:
        """Read path for a codec entry: compressed tiles are read by
        byte range (grouped so each group's DECOMPRESSED bytes fit the
        memory budget — the stored tile is the random-access unit, so
        ``read_object`` and budget-tiled restores work at tile grain),
        verified against the combined compressed-tile checksum, then
        fused-decompressed (LZ4 + unshuffle, parallel across tiles)
        straight into the destination rows."""
        shape = entry.shape
        raw_nbytes = entry.uncompressed_nbytes or tensor_nbytes(
            entry.dtype, shape
        )
        sizes = [int(s) for s in (entry.comp_tile_sizes or [])]
        tile_rows = entry.tile_rows or 0
        n_rows = shape[0] if shape else 0
        row_nbytes = raw_nbytes // n_rows if n_rows else 0
        tile_raw = tile_rows * row_nbytes if tile_rows else raw_nbytes
        n_tiles = max(len(sizes), 1)
        if not sizes:
            raise IOError(
                f"compressed entry {entry.location!r} records no "
                "comp_tile_sizes — the snapshot metadata is inconsistent"
            )
        # The tile list must COVER the payload: each group below only
        # verifies its own range, so a truncated comp_tile_sizes (buggy
        # external rewriter) would otherwise "restore" with the tail of
        # the destination never written — every per-group checksum
        # green, result garbage.
        from ..compress import check_tile_coverage

        check_tile_coverage(entry.location, len(sizes), raw_nbytes, tile_raw)
        if isinstance(obj_out, np.ndarray) and (
            dtype_to_string(obj_out.dtype) == entry.dtype
            and list(obj_out.shape) == list(shape)
            and obj_out.flags.writeable
        ):
            host_out = obj_out
            in_place = True
        else:
            from .. import _native
            from ..serialization import string_to_dtype

            host_out = _native.empty_advised(
                shape, string_to_dtype(entry.dtype)
            )
            in_place = False
        dest_mv = array_as_memoryview(host_out)
        if dest_mv.readonly:  # zero-size arrays come back read-only
            dest_mv = None
        base = entry.byte_range[0] if entry.byte_range is not None else 0
        from ..compress import comp_tile_offsets

        offsets = comp_tile_offsets(sizes)
        # Group consecutive tiles while the group's decompressed bytes
        # fit the budget (>= 1 tile per group: the stored tile is the
        # minimum readable unit, integrity over budget — same policy as
        # the uncompressed tiled read).
        groups: List[Tuple[int, int]] = []
        t0 = 0
        while t0 < n_tiles:
            t1 = t0 + 1
            if buffer_size_limit_bytes is not None:
                while (
                    t1 < n_tiles
                    and (t1 + 1 - t0) * tile_raw <= buffer_size_limit_bytes
                ):
                    t1 += 1
            else:
                t1 = n_tiles
            groups.append((t0, t1))
            t0 = t1
        remaining = {"count": len(groups)}
        from ..compress import combined_comp_checksum
        from ..knobs import is_checksum_disabled

        verify = not is_checksum_disabled()
        read_reqs: List[ReadReq] = []
        for g0, g1 in groups:
            comp_start = base + offsets[g0]
            comp_end = base + offsets[g1 - 1] + sizes[g1 - 1]
            expected = (
                combined_comp_checksum(entry, g0, g1) if verify else None
            )
            raw_start = g0 * tile_raw
            raw_end = min(g1 * tile_raw, raw_nbytes)
            consumer = _CompressedConsumer(
                entry=entry,
                dest_slice=(
                    dest_mv[raw_start:raw_end] if dest_mv is not None else None
                ),
                comp_sizes=sizes[g0:g1],
                tile_raw=tile_raw,
                raw_len=raw_end - raw_start,
                remaining=remaining,
                fut=fut,
                host_out=host_out,
                obj_out=obj_out,
                in_place=in_place,
                expected_checksum=expected,
                location=(
                    f"{logical_path or entry.location} "
                    f"(comp tiles {g0}:{g1})"
                ),
            )
            read_reqs.append(
                ReadReq(
                    path=entry.location,
                    byte_range=(comp_start, comp_end),
                    buffer_consumer=consumer,
                    want_crc=expected is not None,
                    logical_path=logical_path,
                )
            )
        return read_reqs, fut


class _CompressedConsumer(BufferConsumer):
    """Consumes one group of compressed tiles: verify the CRC of the
    stored bytes (the fused read-time value when the plugin computed
    one, else one hash pass), then fused-decompress into the
    destination rows. Completion bookkeeping mirrors _TileConsumer."""

    def __init__(
        self,
        entry: TensorEntry,
        dest_slice: Optional[memoryview],
        comp_sizes: List[int],
        tile_raw: int,
        raw_len: int,
        remaining: dict,
        fut: Future,
        host_out,
        obj_out,
        in_place: bool,
        expected_checksum: Optional[str],
        location: str,
    ) -> None:
        self.entry = entry
        self.dest_slice = dest_slice
        self.comp_sizes = comp_sizes
        self.tile_raw = tile_raw
        self.raw_len = raw_len
        self.remaining = remaining
        self.fut = fut
        self.host_out = host_out
        self.obj_out = obj_out
        self.in_place = in_place
        self.expected_checksum = expected_checksum
        self.location = location
        self.comp_nbytes = sum(comp_sizes)
        # Decode-lane attribution: capture the restore's recorder at
        # construction (prepare_read runs under the restore's telemetry
        # overlay); _consume_blocking later runs on a consume-executor
        # thread, where the thread-local overlay is invisible.
        # record_span is lock-guarded, so recording from that thread is
        # safe.
        from .. import telemetry as _telemetry

        self._tele = _telemetry.current()

    async def consume_read_io(self, read_io, executor: Optional[Executor] = None) -> None:
        buf = read_io.buf.getbuffer()
        loop = asyncio.get_running_loop()
        if executor is not None:
            await loop.run_in_executor(
                executor,
                self._consume_blocking,
                buf,
                read_io.crc32c,
                read_io.crc_algo,
            )
        else:
            self._consume_blocking(buf, read_io.crc32c, read_io.crc_algo)
        await self._after_consume(executor)

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        loop = asyncio.get_running_loop()
        if executor is not None:
            await loop.run_in_executor(
                executor, self._consume_blocking, buf, None, None
            )
        else:
            self._consume_blocking(buf, None, None)
        await self._after_consume(executor)

    def _consume_blocking(self, buf: BufferType, crc, crc_algo) -> None:
        from .. import _native
        from ..knobs import get_native_copy_threads

        mv = memoryview(buf).cast("B")
        if self.expected_checksum is not None:
            if crc is not None and crc_algo:
                # Fused read-time hash: verify a 4-byte value, no
                # second pass over the compressed bytes.
                _native.verify_checksum_value(
                    crc, crc_algo, self.expected_checksum, self.location
                )
            else:
                _native.verify_checksum(
                    mv, self.expected_checksum, self.location
                )
        if mv.nbytes != self.comp_nbytes:
            raise IOError(
                f"short read: got {mv.nbytes} of {self.comp_nbytes} "
                f"compressed bytes for {self.location} — the blob is "
                "truncated"
            )
        if self.dest_slice is None:
            return  # zero-size destination: nothing to decode
        from ..compress import codec_elem

        tele = self._tele
        start = tele.now() if tele is not None else 0.0
        try:
            # One span site covers native decode AND the Python
            # fallback — the fallback lives inside decompress_tiles.
            _native.decompress_tiles(
                mv,
                self.comp_sizes,
                self.tile_raw,
                self.raw_len,
                codec_elem(self.entry.codec),
                self.dest_slice,
                nthreads=get_native_copy_threads(),
            )
            if tele is not None:
                tele.record_span(
                    "restore.decode",
                    start,
                    tele.now() - start,
                    path=self.location,
                    bytes=self.comp_nbytes,
                    raw_bytes=self.raw_len,
                )
        except _native.CompressionError as e:
            raise _native.CompressionError(
                f"{self.location}: {e} (stored checksum verified — the "
                "blob was written malformed, not corrupted in transit)"
            ) from e

    async def _after_consume(self, executor: Optional[Executor] = None) -> None:
        self.remaining["count"] -= 1
        if self.remaining["count"] != 0:
            return
        if self.in_place:
            self.fut.obj = self.host_out
            return
        if executor is not None:
            loop = asyncio.get_running_loop()
            self.fut.obj = await loop.run_in_executor(
                executor,
                finalize_into_target,
                self.host_out,
                self.obj_out,
                True,
            )
        else:
            self.fut.obj = finalize_into_target(
                self.host_out, self.obj_out, True
            )

    def get_consuming_cost_bytes(self) -> int:
        return self.raw_len + self.comp_nbytes


class _TileConsumer(BufferConsumer):
    def __init__(
        self,
        entry,
        host_out,
        r0,
        r1,
        remaining,
        fut,
        obj_out,
        in_place,
        blob_checksum=None,
        blob_location="",
    ):
        self.entry = entry
        self.host_out = host_out
        self.r0, self.r1 = r0, r1
        self.remaining = remaining
        self.fut = fut
        self.obj_out = obj_out
        self.in_place = in_place
        # The checksum this read range is verifiable against: the chunk's
        # whole-blob value for chunked reads, or the combined tile value
        # for budget tiles aligned to recorded checksum-tile boundaries
        # (None when the range is unverifiable or verification is off).
        self.blob_checksum = blob_checksum
        self.blob_location = blob_location
        # The tile's destination rows are contiguous in host_out, so the
        # read may land there directly (host_out is freshly allocated or
        # already validated as an exact-match target).
        row_slice = host_out[self.r0 : self.r1]
        mv = (
            array_as_memoryview(row_slice)
            if row_slice.flags.c_contiguous and row_slice.flags.writeable
            else None
        )
        # Zero-byte slices come back as a read-only memoryview(b"").
        self.into_mv = mv if mv is not None and not mv.readonly else None

    async def consume_read_io(self, read_io, executor: Optional[Executor] = None) -> None:
        if read_io.in_place:
            if self.blob_checksum is not None and read_io.crc32c is not None:
                from .. import _native

                _native.verify_checksum_value(
                    read_io.crc32c,
                    read_io.crc_algo,
                    self.blob_checksum,
                    self.blob_location,
                )
        else:
            await self.consume_buffer(read_io.buf.getbuffer(), executor)
            return
        await self._after_consume(executor)

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        loop = asyncio.get_running_loop()
        if executor is not None:
            await loop.run_in_executor(executor, self._consume_blocking, buf)
        else:
            self._consume_blocking(buf)
        await self._after_consume(executor)

    async def _after_consume(self, executor: Optional[Executor] = None) -> None:
        # Completion bookkeeping stays on the event-loop thread — the
        # executor runs up to 4 consumers concurrently and a bare
        # read-modify-write there can lose decrements.
        self.remaining["count"] -= 1
        if self.remaining["count"] != 0:
            return
        if self.in_place:
            # host_out IS the caller's target; bytes already landed.
            self.fut.obj = self.host_out
            return
        # Finalization may be a full data pass (cast into a
        # mismatched-dtype target) — run it in the executor so the
        # event loop keeps dispatching other entries' reads.
        if executor is not None:
            loop = asyncio.get_running_loop()
            self.fut.obj = await loop.run_in_executor(
                executor,
                finalize_into_target,
                self.host_out,
                self.obj_out,
                True,
            )
        else:
            self.fut.obj = finalize_into_target(
                self.host_out, self.obj_out, True
            )

    def _consume_blocking(self, buf: BufferType) -> None:
        _maybe_verify(buf, self.blob_checksum, self.blob_location)
        tile_shape = [self.r1 - self.r0] + list(self.entry.shape[1:])
        src = array_from_memoryview(memoryview(buf), self.entry.dtype, tile_shape)
        np.copyto(self.host_out[self.r0 : self.r1], src)

    def get_consuming_cost_bytes(self) -> int:
        return tensor_nbytes(
            self.entry.dtype, [self.r1 - self.r0] + list(self.entry.shape[1:])
        )
