"""Fallback preparer for arbitrary picklable objects.

Counterpart of /root/reference/torchsnapshot/io_preparers/object.py
(which uses torch.save — also pickle underneath). Costs are approximated
with sys.getsizeof before serialization, as in the reference (:76-78).
"""

from __future__ import annotations

import asyncio
import sys
from concurrent.futures import Executor
from typing import Any, List, Optional, Tuple

from ..io_types import (
    BufferConsumer,
    BufferStager,
    BufferType,
    Future,
    ReadReq,
    WriteReq,
)
from ..manifest import ObjectEntry
from ..serialization import Serializer, pickle_as_bytes, pickle_from_bytes


class ObjectBufferStager(BufferStager):
    def __init__(self, obj: Any) -> None:
        self.obj = obj

    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        loop = asyncio.get_running_loop()
        if executor is not None:
            return await loop.run_in_executor(executor, pickle_as_bytes, self.obj)
        return pickle_as_bytes(self.obj)

    def get_staging_cost_bytes(self) -> int:
        return sys.getsizeof(self.obj)


class ObjectBufferConsumer(BufferConsumer):
    def __init__(self, fut: Future) -> None:
        self.fut = fut
        self._estimated_cost = 0

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        loop = asyncio.get_running_loop()
        if executor is not None:
            self.fut.obj = await loop.run_in_executor(
                executor, pickle_from_bytes, bytes(buf)
            )
        else:
            self.fut.obj = pickle_from_bytes(bytes(buf))

    def get_consuming_cost_bytes(self) -> int:
        return max(self._estimated_cost, 1)


class ObjectIOPreparer:
    @staticmethod
    def prepare_write(
        storage_path: str, obj: Any, replicated: bool = False
    ) -> Tuple[ObjectEntry, List[WriteReq]]:
        entry = ObjectEntry(
            location=storage_path,
            serializer=Serializer.PICKLE.value,
            obj_type=type(obj).__name__,
            replicated=replicated,
        )
        return entry, [
            WriteReq(path=storage_path, buffer_stager=ObjectBufferStager(obj))
        ]

    @staticmethod
    def prepare_read(entry: ObjectEntry) -> Tuple[List[ReadReq], Future]:
        fut: Future = Future()
        return [
            ReadReq(path=entry.location, buffer_consumer=ObjectBufferConsumer(fut))
        ], fut
