"""Fallback preparer for arbitrary picklable objects.

Counterpart of /root/reference/torchsnapshot/io_preparers/object.py
(which uses torch.save — also pickle underneath). Unlike the reference
(which estimates costs with sys.getsizeof, :76-78), objects are pickled
eagerly at prepare time: they are small in practice (configs, schedules,
metrics), this freezes their content for async snapshots, and it makes
both the staging cost and the manifest ``nbytes`` exact — which the read
scheduler's memory budget relies on.
"""

from __future__ import annotations

import asyncio
from concurrent.futures import Executor
from typing import Any, List, Optional, Tuple

from ..io_types import (
    BufferConsumer,
    BufferStager,
    BufferType,
    Future,
    ReadReq,
    WriteReq,
)
from ..manifest import ObjectEntry
from ..serialization import Serializer, pickle_as_bytes, pickle_from_bytes


class ObjectBufferStager(BufferStager):
    def __init__(self, buf: bytes) -> None:
        self.buf = buf

    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        return self.buf

    def get_staging_cost_bytes(self) -> int:
        return len(self.buf)


class ObjectBufferConsumer(BufferConsumer):
    def __init__(
        self,
        fut: Future,
        nbytes: int,
        checksum: Optional[str] = None,
        location: str = "",
    ) -> None:
        self.fut = fut
        self.nbytes = nbytes
        self.checksum = checksum
        self.location = location

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        from .array import _maybe_verify

        _maybe_verify(buf, self.checksum, self.location)
        if executor is not None:
            loop = asyncio.get_running_loop()
            self.fut.obj = await loop.run_in_executor(
                executor, pickle_from_bytes, bytes(buf)
            )
        else:
            self.fut.obj = pickle_from_bytes(bytes(buf))

    def get_consuming_cost_bytes(self) -> int:
        return max(self.nbytes, 1)


class ObjectIOPreparer:
    @staticmethod
    def prepare_write(
        storage_path: str,
        obj: Any,
        replicated: bool = False,
        prev_entry: Any = None,
    ) -> Tuple[ObjectEntry, List[WriteReq]]:
        buf = pickle_as_bytes(obj)
        from ..knobs import is_checksum_disabled

        checksum = None
        dedup_hash = None
        if not is_checksum_disabled():
            from .. import _native

            checksum = _native.checksum_string(buf)
            # Objects are small; always carry the 64-bit dedup hash so
            # dedup never rests on a single 32-bit CRC (ADVICE r3).
            dedup_hash = _native.dedup_hash_string(buf)
        entry = ObjectEntry(
            location=storage_path,
            serializer=Serializer.PICKLE.value,
            obj_type=type(obj).__name__,
            replicated=replicated,
            nbytes=len(buf),
            checksum=checksum,
            dedup_hash=dedup_hash,
        )
        # Incremental dedup: objects pickle + hash eagerly at prepare
        # time, so an unchanged object needs no write request at all.
        # Requires the 96 bits of combined evidence on both sides; a
        # base written before dedup hashes existed conservatively
        # rewrites.
        if (
            isinstance(prev_entry, ObjectEntry)
            and checksum is not None
            and prev_entry.checksum == checksum
            and dedup_hash is not None
            and prev_entry.dedup_hash == dedup_hash
            and prev_entry.nbytes == len(buf)
            and prev_entry.serializer == entry.serializer
        ):
            entry.location = prev_entry.location
            return entry, []
        return entry, [WriteReq(path=storage_path, buffer_stager=ObjectBufferStager(buf))]

    @staticmethod
    def prepare_read(
        entry: ObjectEntry, logical_path: str = ""
    ) -> Tuple[List[ReadReq], Future]:
        fut: Future = Future()
        consumer = ObjectBufferConsumer(
            fut,
            nbytes=entry.nbytes or 0,
            checksum=entry.checksum,
            location=logical_path or entry.location,
        )
        return [
            ReadReq(
                path=entry.location,
                buffer_consumer=consumer,
                logical_path=logical_path,
            )
        ], fut
