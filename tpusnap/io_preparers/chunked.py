"""Chunked-array preparer: arrays larger than max_chunk_size are split
along dim 0 into independently staged/written chunks, enabling pipelined
DtoH/IO and per-chunk write-load partitioning.

Counterpart of /root/reference/torchsnapshot/io_preparers/chunked_tensor.py.
Chunk slicing of a jax.Array is a device-side slice (an XLA computation
producing a chunk-sized buffer), so only one chunk of extra HBM is live at
a time; host memory is bounded by the scheduler's budget as usual.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

import jax
import numpy as np

from ..io_types import Future, ReadReq, WriteReq
from ..knobs import get_max_chunk_size_bytes
from ..manifest import Chunk, ChunkedTensorEntry, TensorEntry
from ..serialization import Serializer, dtype_to_string, string_to_dtype, tensor_nbytes
from .array import (
    ArrayBufferStager,
    ArrayIOPreparer,
    _TileConsumer,
    _want_crc,
    array_nbytes,
)


def should_chunk(arr) -> bool:
    return (
        len(arr.shape) > 0
        and arr.shape[0] > 1
        and array_nbytes(arr) > get_max_chunk_size_bytes()
    )


def chunk_row_ranges(shape: List[int], dtype: str, max_chunk_bytes: int) -> List[Tuple[int, int]]:
    row_nbytes = max(tensor_nbytes(dtype, shape[1:]), 1)
    rows_per_chunk = max(1, max_chunk_bytes // row_nbytes)
    n_rows = shape[0]
    return [
        (r0, min(r0 + rows_per_chunk, n_rows))
        for r0 in range(0, n_rows, rows_per_chunk)
    ]


class ChunkedArrayIOPreparer:
    @staticmethod
    def prepare_write(
        storage_path: str,
        arr,
        replicated: bool = False,
        is_async_snapshot: bool = False,
        array_prepare_func=None,
        array_prepare_traced=None,
        prev_entry=None,
    ) -> Tuple[ChunkedTensorEntry, List[WriteReq]]:
        from .array import trace_array_prepare

        # Chunk geometry follows the TRANSFORMED dtype (a cast-on-save
        # changes bytes-per-row); the transform itself is applied
        # per-chunk at stage time (reference chunked_tensor.py:82-94).
        if array_prepare_traced is not None:
            dtype, shape = array_prepare_traced[0], list(array_prepare_traced[1])
        else:
            dtype, shape = trace_array_prepare(arr, array_prepare_func)
        # Incremental dedup: match chunks of the previous snapshot's entry
        # by (offsets, sizes) — a changed chunk-size knob between takes
        # shifts boundaries and conservatively misses.
        prev_chunks = {}
        if isinstance(prev_entry, ChunkedTensorEntry):
            prev_chunks = {
                (tuple(c.offsets), tuple(c.sizes)): c.tensor
                for c in prev_entry.chunks
            }
        ranges = chunk_row_ranges(shape, dtype, get_max_chunk_size_bytes())
        chunks: List[Chunk] = []
        write_reqs: List[WriteReq] = []
        ndim = len(shape)
        for r0, r1 in ranges:
            # Lazy device-side slice; DtoH happens at staging time.
            sub = arr[r0:r1]
            location = f"{storage_path}_{r0}_0"
            offsets = [r0] + [0] * (ndim - 1)
            sizes = [r1 - r0] + shape[1:]
            tensor_entry = TensorEntry(
                location=location,
                serializer=Serializer.BUFFER_PROTOCOL.value,
                dtype=dtype,
                shape=[r1 - r0] + shape[1:],
                replicated=replicated,
            )
            chunks.append(
                Chunk(offsets=offsets, sizes=sizes, tensor=tensor_entry)
            )
            write_reqs.append(
                WriteReq(
                    path=location,
                    buffer_stager=ArrayBufferStager(
                        sub,
                        is_async_snapshot,
                        entry=tensor_entry,
                        array_prepare_func=array_prepare_func,
                        dedup_entry=prev_chunks.get(
                            (tuple(offsets), tuple(sizes))
                        ),
                    ),
                )
            )
        entry = ChunkedTensorEntry(
            dtype=dtype, shape=shape, chunks=chunks, replicated=replicated
        )
        return entry, write_reqs

    @staticmethod
    def prepare_read(
        entry: ChunkedTensorEntry,
        obj_out=None,
        buffer_size_limit_bytes: Optional[int] = None,
        logical_path: str = "",
    ) -> Tuple[List[ReadReq], Future]:
        """Chunks land in one preallocated host array via narrow views
        (reference chunked_tensor.py:65-126)."""
        fut: Future = Future()
        shape = entry.shape
        if isinstance(obj_out, np.ndarray) and (
            dtype_to_string(obj_out.dtype) == entry.dtype
            and list(obj_out.shape) == list(shape)
            and obj_out.flags.writeable
        ):
            host_out = obj_out
            in_place = True
        else:
            from .. import _native

            # Chunked entries are >512 MB by construction: fault the fresh
            # destination as hugepages (see _native.advise_hugepages).
            host_out = _native.empty_advised(shape, string_to_dtype(entry.dtype))
            in_place = False

        remaining = {"count": len(entry.chunks)}
        read_reqs: List[ReadReq] = []
        for chunk in entry.chunks:
            r0 = chunk.offsets[0]
            r1 = r0 + chunk.sizes[0]
            tensor_entry = chunk.tensor
            byte_range = (
                tuple(tensor_entry.byte_range)
                if tensor_entry.byte_range is not None
                else None
            )
            consumer = _TileConsumer(
                # _TileConsumer tiles over rows of `shape`; a chunk is
                # exactly a row range, so it is reused as-is.
                _chunk_as_full_entry(entry, chunk),
                host_out,
                r0,
                r1,
                remaining,
                fut,
                obj_out,
                in_place,
                # Each chunk read covers one complete stored blob,
                # so the chunk's whole-blob checksum is verifiable.
                blob_checksum=tensor_entry.checksum,
                blob_location=(
                    f"{logical_path or tensor_entry.location} "
                    f"(chunk @ row {r0})"
                ),
            )
            read_reqs.append(
                ReadReq(
                    path=tensor_entry.location,
                    byte_range=byte_range,
                    buffer_consumer=consumer,
                    into=consumer.into_mv,
                    want_crc=consumer.into_mv is not None
                    and _want_crc(tensor_entry),
                )
            )
        return read_reqs, fut


def _chunk_as_full_entry(entry: ChunkedTensorEntry, chunk: Chunk) -> TensorEntry:
    return TensorEntry(
        location=chunk.tensor.location,
        serializer=chunk.tensor.serializer,
        dtype=entry.dtype,
        shape=entry.shape,
        replicated=entry.replicated,
        byte_range=chunk.tensor.byte_range,
    )
