"""Chunked-array preparer: arrays larger than max_chunk_size are split
along dim 0 into independently staged/written chunks, enabling pipelined
DtoH/IO and per-chunk write-load partitioning.

Counterpart of /root/reference/torchsnapshot/io_preparers/chunked_tensor.py.
Chunk slicing of a jax.Array is a device-side slice (an XLA computation
producing a chunk-sized buffer), so only one chunk of extra HBM is live at
a time; host memory is bounded by the scheduler's budget as usual.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

import jax
import numpy as np

from ..io_types import Future, ReadReq, WriteReq
from ..knobs import get_max_chunk_size_bytes
from ..manifest import Chunk, ChunkedTensorEntry, TensorEntry
from ..serialization import Serializer, dtype_to_string, string_to_dtype, tensor_nbytes
from .array import (
    ArrayBufferStager,
    ArrayIOPreparer,
    _TileConsumer,
    _want_crc,
    array_nbytes,
)


def should_chunk(arr) -> bool:
    return (
        len(arr.shape) > 0
        and arr.shape[0] > 1
        and array_nbytes(arr) > get_max_chunk_size_bytes()
    )


def chunk_row_ranges(shape: List[int], dtype: str, max_chunk_bytes: int) -> List[Tuple[int, int]]:
    row_nbytes = max(tensor_nbytes(dtype, shape[1:]), 1)
    rows_per_chunk = max(1, max_chunk_bytes // row_nbytes)
    n_rows = shape[0]
    return [
        (r0, min(r0 + rows_per_chunk, n_rows))
        for r0 in range(0, n_rows, rows_per_chunk)
    ]


class ChunkedArrayIOPreparer:
    @staticmethod
    def prepare_write(
        storage_path: str,
        arr,
        replicated: bool = False,
        is_async_snapshot: bool = False,
        array_prepare_func=None,
        array_prepare_traced=None,
        prev_entry=None,
        record_dedup_hashes: bool = False,
        chunk_rows: Optional[int] = None,
        prev_chunks: Optional[dict] = None,
    ) -> Tuple[ChunkedTensorEntry, List[WriteReq]]:
        """``chunk_rows``/``prev_chunks`` are set by the tile-grain
        incremental route (io_preparer.prepare_write): chunks follow the
        previous snapshot's checksum-tile grid instead of the chunk-size
        knob, and each chunk dedups against the synthesized per-tile
        entry for its row range — so only changed tiles are written."""
        from .array import trace_array_prepare

        # Chunk geometry follows the TRANSFORMED dtype (a cast-on-save
        # changes bytes-per-row); the transform itself is applied
        # per-chunk at stage time (reference chunked_tensor.py:82-94).
        if array_prepare_traced is not None:
            dtype, shape = array_prepare_traced[0], list(array_prepare_traced[1])
        else:
            dtype, shape = trace_array_prepare(arr, array_prepare_func)
        # Incremental dedup: match chunks of the previous snapshot's entry
        # by (offsets, sizes) — a changed chunk-size knob between takes
        # shifts boundaries and conservatively misses.
        if prev_chunks is None:
            prev_chunks = {}
            if isinstance(prev_entry, ChunkedTensorEntry):
                prev_chunks = {
                    (tuple(c.offsets), tuple(c.sizes)): c.tensor
                    for c in prev_entry.chunks
                }
        if chunk_rows is not None:
            n_rows = shape[0]
            ranges = [
                (r0, min(r0 + chunk_rows, n_rows))
                for r0 in range(0, n_rows, chunk_rows)
            ]
        else:
            ranges = chunk_row_ranges(shape, dtype, get_max_chunk_size_bytes())
        chunks: List[Chunk] = []
        write_reqs: List[WriteReq] = []
        ndim = len(shape)
        for r0, r1 in ranges:
            # Lazy device-side slice; DtoH happens at staging time.
            sub = arr[r0:r1]
            location = f"{storage_path}_{r0}_0"
            offsets = [r0] + [0] * (ndim - 1)
            sizes = [r1 - r0] + shape[1:]
            tensor_entry = TensorEntry(
                location=location,
                serializer=Serializer.BUFFER_PROTOCOL.value,
                dtype=dtype,
                shape=[r1 - r0] + shape[1:],
                replicated=replicated,
            )
            chunks.append(
                Chunk(offsets=offsets, sizes=sizes, tensor=tensor_entry)
            )
            write_reqs.append(
                WriteReq(
                    path=location,
                    buffer_stager=ArrayBufferStager(
                        sub,
                        is_async_snapshot,
                        entry=tensor_entry,
                        array_prepare_func=array_prepare_func,
                        dedup_entry=prev_chunks.get(
                            (tuple(offsets), tuple(sizes))
                        ),
                        record_dedup_hashes=record_dedup_hashes,
                    ),
                )
            )
        entry = ChunkedTensorEntry(
            dtype=dtype, shape=shape, chunks=chunks, replicated=replicated
        )
        return entry, write_reqs

    @staticmethod
    def prepare_read(
        entry: ChunkedTensorEntry,
        obj_out=None,
        buffer_size_limit_bytes: Optional[int] = None,
        logical_path: str = "",
    ) -> Tuple[List[ReadReq], Future]:
        """Chunks land in one preallocated host array via narrow views
        (reference chunked_tensor.py:65-126)."""
        fut: Future = Future()
        shape = entry.shape
        if isinstance(obj_out, np.ndarray) and (
            dtype_to_string(obj_out.dtype) == entry.dtype
            and list(obj_out.shape) == list(shape)
            and obj_out.flags.writeable
        ):
            host_out = obj_out
            in_place = True
        else:
            from .. import _native

            # Chunked entries are >512 MB by construction: fault the fresh
            # destination as hugepages (see _native.advise_hugepages).
            host_out = _native.empty_advised(shape, string_to_dtype(entry.dtype))
            in_place = False

        remaining = {"count": len(entry.chunks)}
        read_reqs: List[ReadReq] = []
        for chunk in entry.chunks:
            r0 = chunk.offsets[0]
            r1 = r0 + chunk.sizes[0]
            tensor_entry = chunk.tensor
            if tensor_entry.codec:
                # Compressed chunk: its own standalone compressed blob
                # — read the stored tiles, verify the chunk checksum
                # (over the stored bytes), fused-decompress into the
                # chunk's rows. Shares the array-wide remaining/fut
                # bookkeeping with the plain-chunk consumers.
                read_reqs.append(
                    _compressed_chunk_read_req(
                        tensor_entry,
                        host_out,
                        r0,
                        r1,
                        remaining,
                        fut,
                        obj_out,
                        in_place,
                        logical_path,
                    )
                )
                continue
            byte_range = (
                tuple(tensor_entry.byte_range)
                if tensor_entry.byte_range is not None
                else None
            )
            consumer = _TileConsumer(
                # _TileConsumer tiles over rows of `shape`; a chunk is
                # exactly a row range, so it is reused as-is.
                _chunk_as_full_entry(entry, chunk),
                host_out,
                r0,
                r1,
                remaining,
                fut,
                obj_out,
                in_place,
                # Each chunk read covers one complete stored blob,
                # so the chunk's whole-blob checksum is verifiable.
                blob_checksum=tensor_entry.checksum,
                blob_location=(
                    f"{logical_path or tensor_entry.location} "
                    f"(chunk @ row {r0})"
                ),
            )
            read_reqs.append(
                ReadReq(
                    path=tensor_entry.location,
                    byte_range=byte_range,
                    buffer_consumer=consumer,
                    into=consumer.into_mv,
                    want_crc=consumer.into_mv is not None
                    and _want_crc(tensor_entry),
                    logical_path=logical_path,
                )
            )
        return read_reqs, fut


def _compressed_chunk_read_req(
    tensor_entry: TensorEntry,
    host_out,
    r0: int,
    r1: int,
    remaining: dict,
    fut,
    obj_out,
    in_place: bool,
    logical_path: str,
) -> ReadReq:
    from ..knobs import is_checksum_disabled
    from .array import _CompressedConsumer, array_as_memoryview

    sizes = [int(s) for s in (tensor_entry.comp_tile_sizes or [])]
    raw_nbytes = tensor_entry.uncompressed_nbytes or tensor_nbytes(
        tensor_entry.dtype, tensor_entry.shape
    )
    n_rows = tensor_entry.shape[0] if tensor_entry.shape else 0
    row_nbytes = raw_nbytes // n_rows if n_rows else 0
    tile_raw = (
        (tensor_entry.tile_rows or 0) * row_nbytes
        if tensor_entry.tile_rows
        else raw_nbytes
    )
    from ..compress import check_tile_coverage

    check_tile_coverage(
        tensor_entry.location, len(sizes), raw_nbytes, tile_raw
    )
    row_slice = host_out[r0:r1]
    dest_mv = array_as_memoryview(row_slice)
    expected = (
        tensor_entry.checksum if not is_checksum_disabled() else None
    )
    consumer = _CompressedConsumer(
        entry=tensor_entry,
        dest_slice=dest_mv if not dest_mv.readonly else None,
        comp_sizes=sizes,
        tile_raw=tile_raw,
        raw_len=raw_nbytes,
        remaining=remaining,
        fut=fut,
        host_out=host_out,
        obj_out=obj_out,
        in_place=in_place,
        expected_checksum=expected,
        location=(
            f"{logical_path or tensor_entry.location} (chunk @ row {r0})"
        ),
    )
    return ReadReq(
        path=tensor_entry.location,
        byte_range=(0, sum(sizes)),
        buffer_consumer=consumer,
        want_crc=expected is not None,
        logical_path=logical_path,
    )


def tile_prev_map(
    prev_entry, dtype: str, shape: List[int]
) -> Optional[Tuple[int, dict]]:
    """Per-tile view of a previous snapshot's entry for tile-grain
    incremental dedup: ``(grid_rows, {(offsets, sizes): TensorEntry})``
    with one synthesized entry per checksum tile — its byte range within
    the previous blob, its recorded tile CRC, and its 64-bit tile dedup
    hash — or None when tile-grain dedup is not possible (mismatched
    identity, no tile checksums, no dedup hashes, or an irregular grid).

    Accepts a dense ``TensorEntry`` carrying ``tile_checksums`` +
    ``tile_dedup_hashes``, or a ``ChunkedTensorEntry`` produced by a
    previous tile-grain take (uniform tile-sized chunks, each carrying
    its own checksum + dedup_hash) — so incremental chains keep
    dedup'ing tile-grain after the first increment changes the entry's
    geometry. Every skip decision this map backs compares BOTH a 32-bit
    CRC and a 64-bit hash per tile (see dedup_entries_match)."""
    serializer = Serializer.BUFFER_PROTOCOL.value
    if (
        isinstance(prev_entry, TensorEntry)
        and prev_entry.serializer == serializer
        and prev_entry.dtype == dtype
        and list(prev_entry.shape) == list(shape)
        and prev_entry.tile_rows
        and prev_entry.tile_checksums
        and prev_entry.tile_dedup_hashes
        and len(prev_entry.tile_checksums) == len(prev_entry.tile_dedup_hashes)
        # Compressed bases: tile hashes are over STORED bytes at
        # compressed offsets — per-tile byte_range references into the
        # raw layout would be wrong. Dedup against a compressed base
        # stays whole-blob (dedup_entries_match compares codec+layout).
        and not prev_entry.codec
    ):
        t = prev_entry.tile_rows
        n_rows = shape[0]
        row_nbytes = tensor_nbytes(dtype, shape[1:]) if len(shape) > 1 else tensor_nbytes(dtype, [1])
        base = prev_entry.byte_range[0] if prev_entry.byte_range else 0
        ndim = len(shape)
        out = {}
        for i, (crc, dh) in enumerate(
            zip(prev_entry.tile_checksums, prev_entry.tile_dedup_hashes)
        ):
            r0, r1 = i * t, min((i + 1) * t, n_rows)
            offsets = tuple([r0] + [0] * (ndim - 1))
            sizes = tuple([r1 - r0] + list(shape[1:]))
            out[(offsets, sizes)] = TensorEntry(
                location=prev_entry.location,
                serializer=serializer,
                dtype=dtype,
                shape=list(sizes),
                replicated=False,
                byte_range=[base + r0 * row_nbytes, base + r1 * row_nbytes],
                checksum=crc,
                dedup_hash=dh,
            )
        return t, out
    if (
        isinstance(prev_entry, ChunkedTensorEntry)
        and prev_entry.dtype == dtype
        and list(prev_entry.shape) == list(shape)
        and prev_entry.chunks
    ):
        chunks = sorted(prev_entry.chunks, key=lambda c: c.offsets[0])
        t = chunks[0].sizes[0]
        n_rows = shape[0]
        out = {}
        expect_r0 = 0
        for i, c in enumerate(chunks):
            r0 = c.offsets[0]
            r1 = r0 + c.sizes[0]
            last = i == len(chunks) - 1
            if (
                r0 != expect_r0
                or (not last and c.sizes[0] != t)
                or (last and r1 != n_rows)
                or any(o != 0 for o in c.offsets[1:])
                or list(c.sizes[1:]) != list(shape[1:])
                or c.tensor.serializer != serializer
                or c.tensor.checksum is None
                or c.tensor.dedup_hash is None
                or c.tensor.tile_rows  # oversized chunk: grid not tile-sized
                or c.tensor.codec  # compressed chunk: blob-grain dedup only
            ):
                return None
            out[(tuple(c.offsets), tuple(c.sizes))] = c.tensor
            expect_r0 = r1
        if expect_r0 != n_rows or len(out) < 2:
            return None
        return t, out
    return None


def _chunk_as_full_entry(entry: ChunkedTensorEntry, chunk: Chunk) -> TensorEntry:
    return TensorEntry(
        location=chunk.tensor.location,
        serializer=chunk.tensor.serializer,
        dtype=entry.dtype,
        shape=entry.shape,
        replicated=entry.replicated,
        byte_range=chunk.tensor.byte_range,
    )
