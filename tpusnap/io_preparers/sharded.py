"""Sharded-array preparer: save/restore of jax.Arrays partitioned over a
device mesh, with automatic resharding on load.

TPU-native counterpart of
/root/reference/torchsnapshot/io_preparers/sharded_tensor.py — but where
the reference handles torch ShardedTensor sharding specs, here ONE
preparer covers DP/FSDP/TP/SP/EP uniformly: any
``jax.sharding.NamedSharding`` (or other sharding) reduces to per-shard
offsets/sizes in the global shape via ``jax.Array.addressable_shards``.

Save (reference :127-170): each process writes its addressable shards
with ``replica_id == 0`` — exactly one device globally owns each distinct
piece, so replicated axes (DP) are written once without any collective.
Shards larger than max_shard_size are subdivided along their largest dim
(reference ``subdivide_shard``, :47-76).

Restore/reshard (reference :78-125, 227-268): compute overlap regions
between saved shards and the pieces needed by the *target* sharding, read
each overlapping saved shard once, scatter into per-piece host buffers via
numpy views, then ``device_put`` each piece to its device(s) and assemble
with ``jax.make_array_from_single_device_arrays``. The target may also be
a plain numpy array or None (treated as one full-size piece —
reference :211-221), which is how sharded→dense ``read_object`` works.
"""

from __future__ import annotations

import asyncio
import math
import threading
from concurrent.futures import Executor
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..io_types import (
    BufferConsumer,
    BufferStager,
    BufferType,
    Future,
    ReadReq,
    WriteReq,
)
from ..knobs import get_max_shard_size_bytes
from ..manifest import Shard as ShardMeta
from ..manifest import ShardedEntry, TensorEntry
from ..serialization import (
    Serializer,
    array_from_memoryview,
    dtype_to_string,
    string_to_dtype,
    tensor_nbytes,
)
from .array import ArrayBufferStager, trace_array_prepare


def is_sharded(arr: Any) -> bool:
    """True if the array is partitioned (not fully replicated) over >1
    device, or spans processes — i.e. no single host holds it densely."""
    if not isinstance(arr, jax.Array):
        return False
    if not arr.is_fully_addressable:
        return True
    return len(arr.sharding.device_set) > 1 and not arr.is_fully_replicated


def _index_to_box(
    index: Tuple[slice, ...], global_shape: Sequence[int]
) -> Tuple[List[int], List[int]]:
    """jax shard index (tuple of slices) → (offsets, sizes)."""
    offsets, sizes = [], []
    for dim, slc in enumerate(index):
        start = slc.start if slc.start is not None else 0
        stop = slc.stop if slc.stop is not None else global_shape[dim]
        offsets.append(start)
        sizes.append(stop - start)
    if len(index) == 0:  # 0-d array
        return [], []
    return offsets, sizes


def _subdivide(
    offsets: List[int], sizes: List[int], itemsize: int, max_bytes: int
) -> List[Tuple[List[int], List[int], Tuple[int, int], int]]:
    """Split a box into sub-boxes ≤ max_bytes along its largest dim.
    Returns [(sub_offsets, sub_sizes, (r0, r1), dim)] where r0:r1 is the
    slice of the shard-local data along ``dim``."""
    nbytes = itemsize * math.prod(sizes) if sizes else itemsize
    if nbytes <= max_bytes or not sizes:
        return [(list(offsets), list(sizes), (0, sizes[0] if sizes else 1), 0)]
    dim = max(range(len(sizes)), key=lambda d: sizes[d])
    if sizes[dim] <= 1:
        return [(list(offsets), list(sizes), (0, sizes[dim]), dim)]
    row_bytes = nbytes // sizes[dim]
    rows_per = max(1, max_bytes // max(row_bytes, 1))
    out = []
    for r0 in range(0, sizes[dim], rows_per):
        r1 = min(r0 + rows_per, sizes[dim])
        sub_off = list(offsets)
        sub_off[dim] += r0
        sub_sz = list(sizes)
        sub_sz[dim] = r1 - r0
        out.append((sub_off, sub_sz, (r0, r1), dim))
    return out


def _location(base: str, offsets: Sequence[int]) -> str:
    suffix = "_".join(str(o) for o in offsets) if len(offsets) else "scalar"
    return f"{base}.{suffix}"


class ShardedArrayIOPreparer:
    @staticmethod
    def prepare_write(
        storage_path: str,
        arr: jax.Array,
        is_async_snapshot: bool = False,
        array_prepare_func=None,
        array_prepare_traced: Optional[Tuple[str, List[int]]] = None,
        prev_entry=None,
        record_dedup_hashes: bool = False,
    ) -> Tuple[ShardedEntry, List[WriteReq]]:
        """``array_prepare_func(arr, tracing)`` is the user save-time
        transform, applied PER LOCAL SHARD at stage time (the reference
        threads its tensor_prepare_func into the sharded preparer the
        same way, sharded_tensor.py:133,159) — on TPU essentially all
        interesting training state is NamedSharding-sharded, so this is
        the transform's primary audience. The stored dtype is discovered
        abstractly (``jax.eval_shape`` on the global array, zero FLOPs);
        subdivision uses the STORED itemsize so blobs honor
        max_shard_size at their written width."""
        if array_prepare_traced is not None:
            dtype_str = array_prepare_traced[0]
        else:
            dtype_str, _ = trace_array_prepare(arr, array_prepare_func)
        itemsize = string_to_dtype(dtype_str).itemsize
        max_bytes = get_max_shard_size_bytes()
        global_shape = list(arr.shape)

        # Incremental dedup: the previous snapshot's (merged, all-rank)
        # entry's shards keyed by box — a resharded array's boxes differ
        # and conservatively miss.
        prev_shards = {}
        if isinstance(prev_entry, ShardedEntry):
            prev_shards = {
                (tuple(s.offsets), tuple(s.sizes)): s.tensor
                for s in prev_entry.shards
            }

        shards_meta: List[ShardMeta] = []
        write_reqs: List[WriteReq] = []
        for shard in arr.addressable_shards:
            if shard.replica_id != 0:
                continue  # exactly one device globally owns each piece
            offsets, sizes = _index_to_box(shard.index, global_shape)
            for sub_off, sub_sz, (r0, r1), dim in _subdivide(
                offsets, sizes, itemsize, max_bytes
            ):
                if (r0, r1) == (0, sizes[dim] if sizes else 1):
                    data = shard.data
                else:
                    slices = [slice(None)] * len(sizes)
                    slices[dim] = slice(r0, r1)
                    data = shard.data[tuple(slices)]  # device-side slice
                loc = _location(storage_path, sub_off)
                tensor_entry = TensorEntry(
                    location=loc,
                    serializer=Serializer.BUFFER_PROTOCOL.value,
                    dtype=dtype_str,
                    shape=list(sub_sz),
                    replicated=False,
                )
                shards_meta.append(
                    ShardMeta(offsets=sub_off, sizes=sub_sz, tensor=tensor_entry)
                )
                write_reqs.append(
                    WriteReq(
                        path=loc,
                        buffer_stager=ArrayBufferStager(
                            data,
                            is_async_snapshot,
                            entry=tensor_entry,
                            array_prepare_func=array_prepare_func,
                            dedup_entry=prev_shards.get(
                                (tuple(sub_off), tuple(sub_sz))
                            ),
                            record_dedup_hashes=record_dedup_hashes,
                            # Shard restores read arbitrary overlap
                            # sub-ranges (resharding) — impossible at
                            # compressed-tile grain, so shards bypass
                            # the codec by construction.
                            compressible=False,
                        ),
                    )
                )
        entry = ShardedEntry(
            shards=shards_meta, dtype=dtype_str, shape=global_shape
        )
        return entry, write_reqs

    @staticmethod
    def prepare_read(
        entry: ShardedEntry,
        obj_out: Any = None,
        buffer_size_limit_bytes: Optional[int] = None,
        logical_path: str = "",
    ) -> Tuple[List[ReadReq], Future]:
        fut: Future = Future()
        global_shape = list(entry.shape)
        np_dtype = string_to_dtype(entry.dtype)

        # The pieces this process must materialize, each a host buffer.
        assembler = _Assembler(entry, obj_out, fut)

        # Map every saved shard to the target pieces it overlaps; one read
        # per overlapping saved shard, scattered into all destinations.
        read_reqs: List[ReadReq] = []
        for saved in entry.shards:
            overlaps = []
            for piece in assembler.pieces:
                region = _overlap(
                    saved.offsets, saved.sizes, piece.offsets, piece.sizes
                )
                if region is not None:
                    overlaps.append((piece, region))
            if not overlaps:
                continue
            byte_range = (
                tuple(saved.tensor.byte_range)
                if saved.tensor.byte_range is not None
                else None
            )
            from .array import _want_crc

            read_reqs.append(
                ReadReq(
                    path=saved.tensor.location,
                    byte_range=byte_range,
                    buffer_consumer=_ScatterConsumer(
                        saved,
                        overlaps,
                        assembler,
                        verify_location=(
                            f"{logical_path or saved.tensor.location} "
                            f"(shard @ {saved.offsets})"
                        ),
                    ),
                    # Checksum computed inside the storage plugin's read
                    # (fused on the read thread); the consumer verifies
                    # the value without re-reading the buffer.
                    want_crc=_want_crc(saved.tensor),
                    logical_path=logical_path,
                )
            )
        assembler.total_reads = len(read_reqs)
        if not read_reqs:  # nothing overlaps (e.g. empty target) — finish now
            assembler.finish()
        return read_reqs, fut


def _overlap(
    off_a: Sequence[int],
    sz_a: Sequence[int],
    off_b: Sequence[int],
    sz_b: Sequence[int],
) -> Optional[Tuple[List[int], List[int]]]:
    """Intersection box of two (offsets, sizes) boxes, or None."""
    offsets, sizes = [], []
    for d in range(len(off_a)):
        start = max(off_a[d], off_b[d])
        stop = min(off_a[d] + sz_a[d], off_b[d] + sz_b[d])
        if stop <= start:
            return None
        offsets.append(start)
        sizes.append(stop - start)
    return offsets, sizes


class _Piece:
    """One distinct piece of the restore target (a shard index of the
    target sharding, or the whole array for dense targets).

    The backing buffer is lazy: when a saved shard exactly matches this
    piece (same-sharding restore — the common production case) the read
    buffer is *adopted* zero-copy via ``adopt`` and no allocation or
    scatter copy happens at all. Saved shards are disjoint, so an exact
    match is the piece's sole writer.

    Allocation/adoption is guarded by a lock: ``_scatter`` runs on a
    multi-worker executor, and when a piece overlaps several saved shards
    (resharding restores) two threads may race to allocate. Concurrent
    scatters into an allocated buffer are safe without the lock — saved
    shards are disjoint, so the written regions never overlap."""

    def __init__(self, offsets: List[int], sizes: List[int], np_dtype) -> None:
        self.offsets = offsets
        self.sizes = sizes
        self._np_dtype = np_dtype
        self._buf: Optional[np.ndarray] = None
        self._alloc_lock = threading.Lock()

    @property
    def buf(self) -> np.ndarray:
        buf = self._buf
        if buf is None:
            with self._alloc_lock:
                if self._buf is None:
                    from .. import _native

                    self._buf = _native.empty_advised(self.sizes, self._np_dtype)
                buf = self._buf
        return buf

    def adopt(self, arr: np.ndarray) -> bool:
        with self._alloc_lock:
            if self._buf is None:
                self._buf = arr
                return True
            return False


class _Assembler:
    """Collects scattered regions into per-piece host buffers; when every
    read has landed, assembles the final restored object."""

    def __init__(self, entry: ShardedEntry, obj_out: Any, fut: Future) -> None:
        self.entry = entry
        self.obj_out = obj_out
        self.fut = fut
        self.total_reads = 0
        self._done_reads = 0
        self._lock = asyncio.Lock()
        np_dtype = string_to_dtype(entry.dtype)
        global_shape = list(entry.shape)

        self.pieces: List[_Piece] = []
        self._piece_by_key: Dict[Tuple, _Piece] = {}
        if isinstance(obj_out, jax.Array):
            for shard in obj_out.addressable_shards:
                offsets, sizes = _index_to_box(shard.index, global_shape)
                key = tuple(offsets) + tuple(sizes)
                if key not in self._piece_by_key:
                    piece = _Piece(offsets, sizes, np_dtype)
                    self._piece_by_key[key] = piece
                    self.pieces.append(piece)
        else:
            piece = _Piece(
                [0] * len(global_shape), global_shape, np_dtype
            )
            self.pieces.append(piece)
            self._piece_by_key[tuple(piece.offsets) + tuple(piece.sizes)] = piece

    def read_landed(self) -> None:
        self._done_reads += 1
        if self.total_reads and self._done_reads == self.total_reads:
            self.finish()

    def finish(self) -> None:
        obj_out = self.obj_out
        if isinstance(obj_out, jax.Array):
            global_shape = tuple(self.entry.shape)
            bufs, dsts = [], []
            # Preserve the target's memory kind: a host-offloaded (UVM
            # analog) target must get pinned_host buffers, not HBM ones.
            memory_kind = getattr(obj_out.sharding, "memory_kind", None)
            for shard in obj_out.addressable_shards:
                offsets, sizes = _index_to_box(shard.index, list(global_shape))
                piece = self._piece_by_key[tuple(offsets) + tuple(sizes)]
                bufs.append(piece.buf)
                dsts.append(
                    jax.sharding.SingleDeviceSharding(
                        shard.device, memory_kind=memory_kind
                    )
                    if memory_kind is not None
                    else shard.device
                )
            # One batched transfer for all of this array's shards (a
            # per-shard loop pays jax dispatch overhead per piece).
            per_device = jax.device_put(bufs, dsts)
            if obj_out.dtype != per_device[0].dtype:
                # Reduced-precision save restoring into a full-precision
                # target: transfer at the STORED width (half the HtoD
                # bytes), cast on device per single-device piece — the
                # sharded analog of finalize_into_target's device cast.
                per_device = [a.astype(obj_out.dtype) for a in per_device]
            self.fut.obj = jax.make_array_from_single_device_arrays(
                global_shape, obj_out.sharding, per_device
            )
        elif isinstance(obj_out, np.ndarray):
            piece = self.pieces[0]
            if (
                obj_out.shape == piece.buf.shape
                and obj_out.flags.writeable
            ):
                # Cast into a mismatched-dtype dense target in place
                # (reference tensor_copy semantics).
                np.copyto(obj_out, piece.buf, casting="unsafe")
                self.fut.obj = obj_out
            else:
                self.fut.obj = piece.buf
        else:
            self.fut.obj = self.pieces[0].buf


class _ScatterConsumer(BufferConsumer):
    """Reads one saved shard and scatters it into every overlapping target
    piece (reference ShardedTensorBufferConsumer, sharded_tensor.py:249-268)."""

    def __init__(
        self,
        saved: ShardMeta,
        overlaps: List[Tuple[_Piece, Tuple[List[int], List[int]]]],
        assembler: _Assembler,
        verify_location: str = "",
    ) -> None:
        self.saved = saved
        self.overlaps = overlaps
        self.assembler = assembler
        self.verify_location = verify_location or saved.tensor.location
        self._verified = False

    async def consume_read_io(self, read_io, executor: Optional[Executor] = None) -> None:
        if read_io.crc32c is not None and self.saved.tensor.checksum is not None:
            # The storage plugin hashed the bytes during the read; verify
            # the 4-byte value here and skip the re-hash pass below.
            from .. import _native

            _native.verify_checksum_value(
                read_io.crc32c,
                read_io.crc_algo,
                self.saved.tensor.checksum,
                self.verify_location,
            )
            self._verified = True
        await self.consume_buffer(read_io.buf.getbuffer(), executor)

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        loop = asyncio.get_running_loop()
        if executor is not None:
            await loop.run_in_executor(executor, self._scatter, buf)
        else:
            self._scatter(buf)
        # Assembly bookkeeping stays on the event-loop thread: no races.
        self.assembler.read_landed()

    def _scatter(self, buf: BufferType) -> None:
        from .array import _maybe_verify

        if not self._verified:
            _maybe_verify(buf, self.saved.tensor.checksum, self.verify_location)
        saved_arr = array_from_memoryview(
            memoryview(buf), self.saved.tensor.dtype, self.saved.sizes
        )
        for piece, (off, sz) in self.overlaps:
            if (
                list(off) == list(self.saved.offsets)
                and list(sz) == list(self.saved.sizes)
                and list(off) == list(piece.offsets)
                and list(sz) == list(piece.sizes)
                and piece.adopt(saved_arr)
            ):
                continue  # exact match: zero-copy, no scatter
            src_slices = tuple(
                slice(off[d] - self.saved.offsets[d], off[d] - self.saved.offsets[d] + sz[d])
                for d in range(len(off))
            )
            dst_slices = tuple(
                slice(off[d] - piece.offsets[d], off[d] - piece.offsets[d] + sz[d])
                for d in range(len(off))
            )
            np.copyto(piece.buf[dst_slices], saved_arr[src_slices])

    def get_consuming_cost_bytes(self) -> int:
        return tensor_nbytes(self.saved.tensor.dtype, self.saved.sizes)
