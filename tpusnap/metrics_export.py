"""Fleet metrics export: production :class:`~tpusnap.telemetry.MetricsSink`s.

PR 2's ``MetricsSink`` interface made external collection *possible*;
this module makes it *deployed*: two always-safe sinks any fleet can
turn on with one env var, no code.

- :class:`PrometheusTextfileSink` — atomic rewrite of a per-rank
  ``.prom`` textfile (Prometheus exposition format, ``# HELP``/``# TYPE``
  per metric) on every take/restore summary, suitable for
  node-exporter's textfile collector. Counters come from the
  PROCESS-GLOBAL telemetry counters, so they are monotonic across
  takes — exactly what Prometheus ``rate()`` needs. A textfile, not an
  HTTP endpoint, on purpose: checkpoint ranks are short-lived batch
  processes behind schedulers and NATs; a scrape port per rank is a
  service-discovery problem, a file under the node collector is not
  (see docs/design.md "Fleet observability").
- :class:`JsonlEventSink` — one structured JSON line per take/restore
  summary (rank-tagged, rotation-bounded): the raw-event feed for
  fleet log pipelines (Vector/fluentd -> wherever), carrying the same
  compact event shape the cross-run history records.

Both are registered automatically when ``TPUSNAP_METRICS_EXPORT``
names them (``prom``, ``jsonl``, or ``prom,jsonl``; files land under
``TPUSNAP_METRICS_DIR``, default the telemetry dir) —
:func:`install_env_sinks` runs at every take/restore begin and
reconciles registration against the current env, so tests and
long-lived processes can flip the knobs between takes. They can also
be registered explicitly like any sink (``tpusnap.metrics_sink(
PrometheusTextfileSink(dir))``). Sink failures never fail a take
(swallowed + rate-limited WARNING, telemetry.py).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import telemetry
from .knobs import get_job_id, get_metrics_dir, get_metrics_export

logger = logging.getLogger(__name__)

JSONL_FILENAME = "events.jsonl"
_DEFAULT_JSONL_MAX_BYTES = 16 * 1024 * 1024

# Wall-clock seam (timestamps only; durations ride the summaries).
_wall = time.time


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 2**53:
        return str(int(f))
    return repr(f)


class PrometheusTextfileSink(telemetry.MetricsSink):
    """Atomic ``.prom`` textfile per rank, rewritten on every
    take/restore summary (never per counter — the textfile collector
    scrapes on its own cadence; rewriting per hot-path increment would
    be pure churn). The default filename carries the job id
    (``tpusnap_<job>_rank<k>.prom``) and every sample a ``job`` label
    (``TPUSNAP_JOB_ID``, host-pid default), so concurrent jobs sharing
    one metrics directory stay attributable instead of clobbering.

    Exported series (``rank`` and ``job`` labels on all):

    - ``tpusnap_take_seconds`` / ``tpusnap_restore_seconds`` — gauges,
      last completed take/restore wall-clock.
    - ``tpusnap_takes_total`` / ``tpusnap_restores_total`` — summaries
      exported since process start.
    - ``tpusnap_bytes_written_total`` / ``tpusnap_bytes_read_total`` —
      process-global byte counters (monotonic).
    - ``tpusnap_retry_attempts_total``, and
      ``tpusnap_retry_total{classification="transient.<op>.<Exc>"}`` —
      one series per observed retry classification.
    - ``tpusnap_stall_episodes_total`` — stall-watchdog episodes.
    - ``tpusnap_salvage_bytes_total``, ``tpusnap_dedup_skips_total``.
    - ``tpusnap_compress_bytes_in_total`` /
      ``tpusnap_compress_bytes_out_total`` — fused tile codec volume
      (ratio = in/out; equal ⇒ the auto policy is bypassing).
    - ``tpusnap_budget_high_water_bytes``,
      ``tpusnap_peak_rss_delta_bytes`` — gauges from the last summary.
    - ``tpusnap_storage_write_seconds`` /
      ``tpusnap_storage_read_seconds`` — summary-typed latency
      quantiles (``quantile="0.5|0.95|0.99"``, ``plugin=<class>``) from
      the process-global log2 histograms recorded at the
      storage-plugin boundary.
    - ``tpusnap_rpo_seconds``, ``tpusnap_data_at_risk_bytes``,
      ``tpusnap_estimated_rto_seconds``,
      ``tpusnap_commit_interval_seconds`` — checkpoint-SLO gauges
      (:mod:`tpusnap.slo`), refreshed at heartbeat cadence while a
      take runs and at every commit; rank 0 of a multi-process take
      additionally exports the fleet worst-case as ``scope="fleet"``
      samples. ``tpusnap_slo_breach`` is 1 while a set
      ``TPUSNAP_SLO_RPO_S``/``TPUSNAP_SLO_RTO_S`` threshold is
      crossed (``objective`` label).
    - ``tpusnap_last_summary_timestamp_seconds`` — staleness probe.
    """

    def __init__(
        self, directory: Optional[str] = None, filename: Optional[str] = None
    ) -> None:
        self._directory = directory
        self._filename = filename
        self._lock = threading.Lock()
        self._last_wall: Dict[str, float] = {}
        self._summary_counts: Dict[str, int] = {}
        self._last_gauges: Dict[str, float] = {}
        self._slo_state: Optional[Dict[str, Any]] = None
        self._tier_state: Optional[Dict[str, Any]] = None
        self._rank: Optional[int] = None

    # --- MetricsSink ----------------------------------------------------

    def on_take_summary(self, summary: Dict[str, Any]) -> None:
        self._absorb(summary.get("kind") or "take", summary)

    def on_restore_summary(self, summary: Dict[str, Any]) -> None:
        self._absorb("restore", summary)

    def on_slo_update(self, state: Dict[str, Any]) -> None:
        # Same locked write+rename discipline as _absorb: the SLO
        # publisher runs on the heartbeat pump thread while summaries
        # publish from commit threads; the per-pid temp name is shared.
        with self._lock:
            self._slo_state = dict(state)
            self._rank = state.get("rank", self._rank or 0)
            self._rewrite_locked()

    def on_tier_update(self, state: Dict[str, Any]) -> None:
        # Write-back tier status (tpusnap.tiering): the uploader's
        # drain thread publishes on every transition/blob completion.
        with self._lock:
            self._tier_state = dict(state)
            self._rewrite_locked()

    # --- internals ------------------------------------------------------

    def path(self, rank: int) -> str:
        d = self._directory or get_metrics_dir()
        # The job id is in the default filename so two jobs sharing one
        # TPUSNAP_METRICS_DIR (a node collector's textfile directory)
        # can never silently overwrite each other's samples.
        name = self._filename or f"tpusnap_{get_job_id()}_rank{rank}.prom"
        return os.path.join(d, name)

    def _absorb(self, kind: str, summary: Dict[str, Any]) -> None:
        # The write+rename stays INSIDE the lock: an async take's commit
        # publishes from its background thread while a restore publishes
        # from the main thread, and the per-pid temp name is shared
        # across threads — unlocked, the two rewrites would interleave
        # into a torn .prom.
        with self._lock:
            self._rank = summary.get("rank", self._rank or 0)
            if summary.get("completed"):
                # Aborted takes/failed restores publish summaries too;
                # the "last completed" gauges and "completed ... total"
                # counters must not absorb them (the file still
                # rewrites below: the global counters advanced).
                self._last_wall[kind] = float(summary.get("take_wall_s") or 0.0)
                self._summary_counts[kind] = (
                    self._summary_counts.get(kind, 0) + 1
                )
            for g in ("scheduler.budget_used_bytes", "peak_rss_delta_bytes"):
                v = (summary.get("gauges") or {}).get(g)
                if v is not None:
                    self._last_gauges[g] = float(v)
            # Probe-derived roofline fractions ride as top-level summary
            # fields, not gauges: write lane from takes, read lane from
            # restores (TPUSNAP_PROBE=1 only — absent otherwise).
            for f in ("roofline_fraction", "restore_roofline_fraction"):
                v = summary.get(f)
                if isinstance(v, (int, float)):
                    self._last_gauges[f] = float(v)
            self._rewrite_locked()

    def _rewrite_locked(self) -> None:
        text = self.render()
        path = self.path(self._rank if self._rank is not None else 0)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)

    def render(self) -> str:
        """The full exposition text from current state (process-global
        counters + last summary). Callable without a write for tests."""
        rank = str(self._rank if self._rank is not None else 0)
        job = get_job_id()
        counters = telemetry.global_counters_snapshot()
        out: List[str] = []

        def metric(
            name: str,
            mtype: str,
            help_: str,
            samples: List[Tuple[Dict[str, str], float]],
        ) -> None:
            out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} {mtype}")
            for labels, value in samples:
                all_labels = dict(labels)
                all_labels["rank"] = rank
                all_labels["job"] = job
                out.append(f"{name}{_fmt_labels(all_labels)} {_fmt_value(value)}")

        for kind, mname in (("take", "tpusnap_take_seconds"),
                            ("restore", "tpusnap_restore_seconds")):
            if kind in self._last_wall:
                metric(
                    mname,
                    "gauge",
                    f"Wall-clock seconds of the last completed {kind}.",
                    [({}, self._last_wall[kind])],
                )
        for kind, mname in (("take", "tpusnap_takes_total"),
                            ("restore", "tpusnap_restores_total")):
            metric(
                mname,
                "counter",
                f"Completed {kind} summaries exported since process start.",
                [({}, self._summary_counts.get(kind, 0))],
            )
        metric(
            "tpusnap_bytes_written_total",
            "counter",
            "Snapshot bytes written to storage (process lifetime).",
            [({}, counters.get("storage.bytes_written", 0))],
        )
        metric(
            "tpusnap_bytes_read_total",
            "counter",
            "Snapshot bytes read from storage (process lifetime).",
            [({}, counters.get("storage.bytes_read", 0))],
        )
        metric(
            "tpusnap_retry_attempts_total",
            "counter",
            "Storage retry attempts (process lifetime).",
            [({}, counters.get("retry.attempts", 0))],
        )
        retry_series: List[Tuple[Dict[str, str], float]] = [
            ({"classification": name[len("retry."):]}, v)
            for name, v in sorted(counters.items())
            if name.startswith("retry.transient.")
            or name.startswith("retry.fatal.")
            or name.startswith("retry.exhausted.")
        ]
        metric(
            "tpusnap_retry_total",
            "counter",
            "Storage retries by classification (transient/fatal, op, "
            "exception type).",
            retry_series or [({"classification": "none"}, 0)],
        )
        metric(
            "tpusnap_stall_episodes_total",
            "counter",
            "Stall-watchdog episodes (no forward progress past the "
            "deadline inside a named op).",
            [({}, counters.get("progress.stall_episodes", 0))],
        )
        metric(
            "tpusnap_salvage_bytes_total",
            "counter",
            "Bytes salvaged from torn takes instead of rewritten.",
            [({}, counters.get("salvage.bytes_salvaged", 0))],
        )
        metric(
            "tpusnap_dedup_skips_total",
            "counter",
            "Incremental-dedup skipped blob writes.",
            [({}, counters.get("scheduler.dedup_skipped", 0))],
        )
        # Fused tile compression: input (logical) vs output (stored)
        # bytes through the codec — the fleet-level compression ratio is
        # rate(in)/rate(out), and a sustained in==out says the auto
        # policy is bypassing (fast local disk) as designed.
        metric(
            "tpusnap_compress_bytes_in_total",
            "counter",
            "Logical bytes fed through the fused tile codec.",
            [({}, counters.get("compress.bytes_in", 0))],
        )
        metric(
            "tpusnap_compress_bytes_out_total",
            "counter",
            "Stored (compressed) bytes produced by the fused tile codec.",
            [({}, counters.get("compress.bytes_out", 0))],
        )
        # Storage-boundary latency quantiles from the PROCESS-GLOBAL
        # log2 histograms (one summary-typed family per op, labeled by
        # plugin class): the tail the whole-op gauges average away.
        # Quantiles are point-in-time values, not counters — the
        # monotonic-domain rule applies to the *_total families only.
        io_hist = telemetry.global_io_histograms_snapshot()
        for op in ("write", "read"):
            samples: List[Tuple[Dict[str, str], float]] = []
            for key, st in io_hist.items():
                key_op, _, plugin = key.partition(".")
                if key_op != op or not st.get("count"):
                    continue
                for qname, qkey in (
                    ("0.5", "p50_s"),
                    ("0.95", "p95_s"),
                    ("0.99", "p99_s"),
                ):
                    v = st.get(qkey)
                    if v is not None:
                        samples.append(
                            ({"plugin": plugin, "quantile": qname}, v)
                        )
            if samples:
                metric(
                    f"tpusnap_storage_{op}_seconds",
                    "summary",
                    f"Storage-plugin {op} latency quantiles "
                    "(process-lifetime log2 histograms, per plugin class).",
                    samples,
                )
        if "scheduler.budget_used_bytes" in self._last_gauges:
            metric(
                "tpusnap_budget_high_water_bytes",
                "gauge",
                "Scheduler memory-budget high-water mark of the last "
                "take/restore.",
                [({}, self._last_gauges["scheduler.budget_used_bytes"])],
            )
        if "peak_rss_delta_bytes" in self._last_gauges:
            metric(
                "tpusnap_peak_rss_delta_bytes",
                "gauge",
                "Peak RSS delta sampled over the last take/restore.",
                [({}, self._last_gauges["peak_rss_delta_bytes"])],
            )
        if "roofline_fraction" in self._last_gauges:
            metric(
                "tpusnap_roofline_fraction",
                "gauge",
                "Last take's payload throughput as a fraction of the "
                "in-take probe WRITE ceiling (TPUSNAP_PROBE=1).",
                [({}, self._last_gauges["roofline_fraction"])],
            )
        if "restore_roofline_fraction" in self._last_gauges:
            metric(
                "tpusnap_restore_roofline_fraction",
                "gauge",
                "Last restore's payload throughput as a fraction of "
                "the in-restore probe READ ceiling (TPUSNAP_PROBE=1).",
                [({}, self._last_gauges["restore_roofline_fraction"])],
            )
        # Checkpoint-SLO gauges (tpusnap.slo): the per-rank view, plus
        # rank 0's fleet worst-case fold as scope="fleet" samples.
        slo = self._slo_state
        if slo is not None:
            fleet = slo.get("fleet") or {}

            def slo_samples(key: str) -> List[Tuple[Dict[str, str], float]]:
                samples: List[Tuple[Dict[str, str], float]] = []
                v = slo.get(key)
                if isinstance(v, (int, float)):
                    samples.append(({}, float(v)))
                fv = fleet.get(key)
                if isinstance(fv, (int, float)):
                    samples.append(({"scope": "fleet"}, float(fv)))
                return samples

            for key, mname, help_ in (
                (
                    "rpo_s",
                    "tpusnap_rpo_seconds",
                    "Seconds since the last committed take (recovery-"
                    "point exposure; fleet scope = worst rank).",
                ),
                (
                    "data_at_risk_bytes",
                    "tpusnap_data_at_risk_bytes",
                    "Bytes mutated since the last committed take (best "
                    "evidence tier: explicit steps / incremental change "
                    "stats / planned payload).",
                ),
                (
                    "estimated_rto_s",
                    "tpusnap_estimated_rto_seconds",
                    "History-derived estimated restore wall-clock of "
                    "the last committed snapshot.",
                ),
                (
                    "commit_interval_s",
                    "tpusnap_commit_interval_seconds",
                    "Monotonic interval between the last two commits "
                    "(the realized RPO of the closed interval).",
                ),
            ):
                samples = slo_samples(key)
                if samples:
                    metric(mname, "gauge", help_, samples)
            breach = slo.get("breach") or {}
            metric(
                "tpusnap_slo_breach",
                "gauge",
                "1 while a set TPUSNAP_SLO_RPO_S/RTO_S threshold is "
                "crossed, by objective.",
                [
                    ({"objective": k}, 1.0 if breach.get(k) else 0.0)
                    for k in ("rpo", "rto")
                ],
            )
        # Write-back tier gauges (tpusnap.tiering): the at-risk window
        # between local commit and cloud durability, live through
        # outages (lag rises while degraded, falls as the drain
        # recovers), plus the circuit-breaker state itself.
        tier = self._tier_state
        if tier is not None:
            metric(
                "tpusnap_upload_lag_bytes",
                "gauge",
                "Local-committed bytes not yet proven remote by the "
                "write-back uploader's journal.",
                [({}, float(tier.get("lag_bytes") or 0))],
            )
            metric(
                "tpusnap_upload_lag_seconds",
                "gauge",
                "Age of the oldest local commit still awaiting remote "
                "durability.",
                [({}, float(tier.get("lag_seconds") or 0.0))],
            )
            metric(
                "tpusnap_tier_degraded",
                "gauge",
                "1 while the uploader's outage circuit is open (remote "
                "unavailable; takes keep committing locally).",
                [({}, 1.0 if tier.get("degraded") else 0.0)],
            )
        metric(
            "tpusnap_last_summary_timestamp_seconds",
            "gauge",
            "Unix time this file was last rewritten (staleness probe).",
            [({}, _wall())],
        )
        return "\n".join(out) + "\n"


class JsonlEventSink(telemetry.MetricsSink):
    """One JSON line per take/restore summary, appended (O_APPEND, one
    write syscall — concurrent ranks interleave whole lines) to
    ``<metrics_dir>/events.jsonl``. Rotation-bounded: when the file
    exceeds ``max_bytes`` it is renamed to ``events.jsonl.1`` (replacing
    the previous rotation) and a fresh file starts — bounded worst-case
    footprint of 2x ``max_bytes``."""

    def __init__(
        self,
        directory: Optional[str] = None,
        max_bytes: int = _DEFAULT_JSONL_MAX_BYTES,
    ) -> None:
        self._directory = directory
        self.max_bytes = max(4096, int(max_bytes))
        self._lock = threading.Lock()

    def path(self) -> str:
        return os.path.join(self._directory or get_metrics_dir(), JSONL_FILENAME)

    def on_take_summary(self, summary: Dict[str, Any]) -> None:
        self._append(summary.get("kind") or "take", summary)

    def on_restore_summary(self, summary: Dict[str, Any]) -> None:
        self._append("restore", summary)

    def _append(self, kind: str, summary: Dict[str, Any]) -> None:
        from .history import append_jsonl_line, event_from_summary

        event = event_from_summary(kind, summary)
        event["completed"] = bool(summary.get("completed"))
        line = json.dumps(event, separators=(",", ":")) + "\n"
        path = self.path()
        with self._lock:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            try:
                if os.path.getsize(path) + len(line) > self.max_bytes:
                    os.replace(path, path + ".1")
            except OSError:
                pass
            append_jsonl_line(path, line)


# -------------------------------------------------- env-driven installing

_env_lock = threading.Lock()
_env_spec: Optional[Tuple[Tuple[str, ...], str]] = None
_env_sinks: List[telemetry.MetricsSink] = []


def install_env_sinks() -> None:
    """Reconcile registered export sinks against
    ``TPUSNAP_METRICS_EXPORT`` / ``TPUSNAP_METRICS_DIR``. Idempotent
    per spec (same env -> no-op); a changed spec unregisters the old
    env-installed sinks and registers the new set. Called at every
    take/restore begin; never raises to the caller."""
    spec = (get_metrics_export(), get_metrics_dir())
    with _env_lock:
        global _env_spec
        if spec == _env_spec:
            return
        for sink in _env_sinks:
            telemetry.unregister_metrics_sink(sink)
        _env_sinks.clear()
        formats, directory = spec
        for fmt in formats:
            sink: telemetry.MetricsSink
            if fmt == "prom":
                sink = PrometheusTextfileSink(directory)
            else:
                sink = JsonlEventSink(directory)
            telemetry.register_metrics_sink(sink)
            _env_sinks.append(sink)
        _env_spec = spec


# --------------------------------------------------- format self-checking


def parse_prometheus_textfile(text: str) -> Dict[str, Dict[str, Any]]:
    """Strict parse of the exposition text this module writes:
    ``{metric_name: {"type", "help", "samples": {label_string: value}}}``.
    Raises ``ValueError`` on any malformed line, a sample without a
    preceding ``# TYPE``, or a ``# TYPE``/``# HELP`` pair missing for a
    sampled metric — the acceptance-criteria format self-check, also
    usable against any collector-side copy of the file."""
    metrics: Dict[str, Dict[str, Any]] = {}
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_ = rest.partition(" ")
            if not name:
                raise ValueError(f"line {lineno}: HELP without a metric name")
            metrics.setdefault(name, {"samples": {}})["help"] = help_
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):]
            name, _, mtype = rest.partition(" ")
            if mtype not in ("counter", "gauge", "histogram", "summary", "untyped"):
                raise ValueError(
                    f"line {lineno}: bad metric type {mtype!r} for {name!r}"
                )
            metrics.setdefault(name, {"samples": {}})["type"] = mtype
            continue
        if line.startswith("#"):
            continue  # comment
        # Sample: name[{labels}] value
        brace = line.find("{")
        if brace != -1:
            close = line.rfind("}")
            if close == -1 or close < brace:
                raise ValueError(f"line {lineno}: unbalanced label braces")
            name = line[:brace]
            labels = line[brace : close + 1]
            value_part = line[close + 1 :].strip()
        else:
            name, _, value_part = line.partition(" ")
            labels = ""
        if not name or not value_part:
            raise ValueError(f"line {lineno}: malformed sample {line!r}")
        try:
            value = float(value_part.split()[0])
        except ValueError:
            raise ValueError(
                f"line {lineno}: non-numeric value {value_part!r}"
            ) from None
        meta = metrics.get(name)
        if meta is None or "type" not in meta:
            raise ValueError(
                f"line {lineno}: sample for {name!r} without a # TYPE line"
            )
        meta["samples"][labels] = value
    for name, meta in metrics.items():
        if meta["samples"] and ("help" not in meta or "type" not in meta):
            raise ValueError(f"metric {name!r} missing # HELP or # TYPE")
    return metrics
