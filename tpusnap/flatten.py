"""Reversible flattening of nested state into ``{logical_path: leaf}``.

TPU-native counterpart of the reference's flatten/inflate
(/root/reference/torchsnapshot/flatten.py:18-224), extended with tuple
support because JAX state (optax optimizer states, flax TrainState) is
tuple/NamedTuple-heavy.

Semantics preserved from the reference:
- path components are percent-escaped so ``/`` and ``%`` in keys round-trip
  (flatten.py:213-224);
- dicts with non-str/int keys, or keys that collide after ``str()``
  conversion, are NOT flattened — the whole dict becomes a single leaf
  (flatten.py:142-154);
- ``inflate`` rebuilds the original containers from the container manifest,
  skipping leaf paths absent from ``flattened`` (flatten.py:176-199).
"""

from collections import OrderedDict
from typing import Any, Dict, List, Tuple, Union
from urllib.parse import quote, unquote

from .manifest import (
    DictEntry,
    Entry,
    ListEntry,
    Manifest,
    OrderedDictEntry,
    TupleEntry,
    is_container_entry,
)

Flattened = Dict[str, Any]


def _encode(key: Union[str, int]) -> str:
    # An empty-string key would produce an empty path component; encode it as
    # a bare "%" (percent-quoting always emits two hex digits after "%", so
    # this cannot collide with any quoted key).
    encoded = quote(str(key), safe="")
    return encoded if encoded else "%"


def _decode(component: str) -> str:
    if component == "%":
        return ""
    return unquote(component)


def _dict_is_flattenable(obj: Dict[Any, Any]) -> bool:
    keys = list(obj.keys())
    if not all(isinstance(k, (str, int)) and not isinstance(k, bool) for k in keys):
        return False
    # Refuse if two keys collide after str() conversion (e.g. 1 vs "1").
    return len({str(k) for k in keys}) == len(keys)


def _join(prefix: str, component: str) -> str:
    return f"{prefix}/{component}" if prefix else component


def flatten(obj: Any, prefix: str = "") -> Tuple[Manifest, Flattened]:
    """Flatten nested containers into (container manifest, {path: leaf})."""
    manifest: Manifest = {}
    flattened: Flattened = {}
    _flatten(obj, prefix, manifest, flattened)
    return manifest, flattened


def _flatten(obj: Any, path: str, manifest: Manifest, flattened: Flattened) -> None:
    if isinstance(obj, OrderedDict) and _dict_is_flattenable(obj):
        manifest[path] = OrderedDictEntry(keys=list(obj.keys()))
        for key, val in obj.items():
            _flatten(val, _join(path, _encode(key)), manifest, flattened)
    elif isinstance(obj, dict) and _dict_is_flattenable(obj):
        manifest[path] = DictEntry(keys=list(obj.keys()))
        for key, val in obj.items():
            _flatten(val, _join(path, _encode(key)), manifest, flattened)
    elif isinstance(obj, list):
        manifest[path] = ListEntry()
        for idx, val in enumerate(obj):
            _flatten(val, _join(path, str(idx)), manifest, flattened)
    elif isinstance(obj, tuple):
        # Covers NamedTuples too; they inflate to plain tuples — callers that
        # need the exact pytree structure (PytreeState) re-apply the treedef.
        manifest[path] = TupleEntry()
        for idx, val in enumerate(obj):
            _flatten(val, _join(path, str(idx)), manifest, flattened)
    else:
        flattened[path] = obj


_MISSING = object()


def inflate(manifest: Manifest, flattened: Flattened, prefix: str = "") -> Any:
    """Rebuild the nested object flattened under ``prefix``.

    Leaf paths present in ``manifest``'s container skeleton but absent from
    ``flattened`` are dropped (reference flatten.py:176-199 semantics) —
    dict entries lose the key, list/tuple entries compact.
    """
    entries: Dict[str, Entry] = {}
    for path, entry in manifest.items():
        rel = _strip_prefix(path, prefix)
        if rel is not None:
            entries[rel] = entry
    leaves: Dict[str, Any] = {}
    for path, value in flattened.items():
        rel = _strip_prefix(path, prefix)
        if rel is not None:
            leaves[rel] = value

    if "" not in entries:
        # The root itself is a leaf (not a container).
        if "" in leaves:
            return leaves[""]
        raise ValueError(f"No root found under prefix {prefix!r}")

    children: Dict[str, List[str]] = {}
    for rel in list(entries.keys()) + list(leaves.keys()):
        if rel == "":
            continue
        parent = rel.rsplit("/", 1)[0] if "/" in rel else ""
        children.setdefault(parent, []).append(rel)

    def build(rel: str) -> Any:
        if rel not in entries:
            return leaves.get(rel, _MISSING)
        entry = entries[rel]
        if not is_container_entry(entry):
            raise ValueError(f"Non-container entry in container manifest at {rel!r}")
        kids = children.get(rel, [])
        components = {k: (k.rsplit("/", 1)[-1] if "/" in k else k) for k in kids}
        if isinstance(entry, (ListEntry, TupleEntry)):
            built = [
                build(k) for k in sorted(kids, key=lambda k: int(components[k]))
            ]
            built = [v for v in built if v is not _MISSING]
            return tuple(built) if isinstance(entry, TupleEntry) else built
        # dict/OrderedDict: original key list preserves both order and the
        # str-vs-int type of each key.
        key_by_str = {str(orig): orig for orig in entry.keys}
        order = {str(orig): i for i, orig in enumerate(entry.keys)}
        out = OrderedDict() if isinstance(entry, OrderedDictEntry) else {}
        for k in sorted(
            kids, key=lambda k: order.get(_decode(components[k]), len(order))
        ):
            decoded = _decode(components[k])
            if decoded not in key_by_str:
                # The container entry is the source of truth for membership
                # (reference flatten.py:176-199); stray leaves are dropped.
                continue
            value = build(k)
            if value is _MISSING:
                continue
            out[key_by_str[decoded]] = value
        return out

    return build("")


def _strip_prefix(path: str, prefix: str) -> Union[str, None]:
    if prefix == "":
        return path
    if path == prefix:
        return ""
    if path.startswith(prefix + "/"):
        return path[len(prefix) + 1 :]
    return None
