"""Metadata collectives over the JAX distributed coordination service.

TPU-native counterpart of /root/reference/torchsnapshot/pg_wrapper.py.
The reference funnels small-object collectives (all_gather_object,
broadcast_object_list, barrier) through torch.distributed (gloo/NCCL).
tpusnap instead rides the **coordination-service KV store** that
``jax.distributed.initialize`` brings up over DCN:

- it exists on every multi-host TPU deployment (no extra rendezvous);
- it is usable from background threads, where device collectives are
  forbidden (same constraint as the reference, snapshot.py:902);
- manifests/globs/write-loads are KB-scale — device collectives over ICI
  would be overkill (SURVEY.md §5).

Like the reference's PGWrapper (pg_wrapper.py:15-30), construction
auto-detects the environment: single process → no-op collectives; a live
``jax.distributed`` coordination client with >1 process → KV-store-backed
collectives. Detection reads the coordination state directly so that
checkpointing host-resident state never initializes a device backend.

Sequencing: keys are namespaced per Communicator *instance* (assigned
lazily at the first collective from a process-global counter — ranks
issue their first collective on instances in the same order under SPMD,
while collective-free construction on rank subsets stays free) and
sequenced per instance, so two interleaved Communicator instances can
never cross-wire keys. Within one instance, ranks must execute the same
collectives in the same order — the same contract as any collective
backend.

Scalability: ``all_gather_object`` is one KV set + one barrier + one
``key_value_dir_get`` per rank — O(1) RPCs regardless of world size
(the reference pays one torch.dist gather; the naive KV port paid
world_size serial gets). ``broadcast_object`` is one set / one blocking
get with NO barrier. Consumed keys are garbage-collected lazily: rank 0
deletes a collective's prefix only after a later barrier proves every
rank has moved past it.
"""

from __future__ import annotations

import base64
import logging
import pickle
import threading
from typing import Any, List, Optional

logger = logging.getLogger(__name__)


def _default_timeout_ms() -> int:
    # Historically a 600_000 literal (mirroring reference
    # dist_store.py:17); now routed through the one knob that bounds
    # every blocking collective wait. Resolved per-instance so test
    # overrides apply without reimports.
    from .knobs import get_barrier_timeout_s

    return int(get_barrier_timeout_s() * 1000.0)


class Communicator:
    """Uniform interface; base class doubles as the single-process no-op
    implementation (reference pg_wrapper.py single-process path)."""

    @property
    def rank(self) -> int:
        return 0

    @property
    def world_size(self) -> int:
        return 1

    def barrier(self) -> None:
        return None

    def all_gather_object(self, obj: Any) -> List[Any]:
        return [obj]

    def broadcast_object(self, obj: Any, src: int = 0) -> Any:
        return obj

    def gc_epoch(self) -> int:
        """Marker for ``gc_consumed_keys``: keys pending GC as of now."""
        return 0

    def gc_consumed_keys(self, epoch: Optional[int] = None) -> None:
        """Release KV keys of fully-consumed collectives — the first
        ``epoch`` pending ones (from a prior ``gc_epoch()`` call), or
        all when ``epoch`` is None. Callers must hold external proof
        that EVERY rank consumed those keys (e.g. all ranks departed a
        LinearBarrier issued after the collective) — async_take's
        background commit uses this, since it never issues another
        barrier on the communicator. The epoch bound keeps a background
        flush from deleting keys of collectives the main thread started
        AFTER the proof point. Pure KV deletes: safe from any thread."""
        return None

    def set_wait_watcher(self, watcher) -> None:
        """Install a callable run periodically inside every collective
        wait; it may raise to abort the wait early (take-abort
        propagation). While installed, barriers and blocking gets switch
        from the coordination service's native blocking RPCs (which
        cannot be interrupted before their timeout) to KV polling. ALL
        ranks must install/clear at the same point in their collective
        program — the polling barrier only interoperates with itself.
        No-op on the single-process communicator."""
        return None

    def clear_wait_watcher(self) -> None:
        return None

    def barrier_missing_ranks(self) -> Optional[List[int]]:
        """While this process is blocked inside a POLLING barrier (the
        abort-aware mode every multi-process take runs in), the sorted
        rank ids whose arrive keys are absent — the stall watchdog's
        straggler attribution. None when not waiting in a barrier, or
        when the wait mode cannot be introspected (native
        wait_at_barrier). Called from the watchdog thread: pure KV
        reads, safe concurrently with the waiting thread's polling."""
        return None


_instance_count = 0


def _next_instance() -> int:
    global _instance_count
    _instance_count += 1
    return _instance_count


class JaxCoordinationComm(Communicator):
    """KV-store-backed collectives for multi-process jobs."""

    def __init__(
        self,
        timeout_ms: Optional[int] = None,
        namespace: Optional[str] = None,
    ) -> None:
        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "jax.distributed is not initialized; call "
                "jax.distributed.initialize() before using tpusnap across "
                "processes"
            )
        self._client = client
        # Read rank/world from the coordination state, not
        # jax.process_index()/process_count() — those initialize the device
        # backend, which checkpointing of host state must never require.
        self._rank = distributed.global_state.process_id
        self._world_size = distributed.global_state.num_processes
        self._timeout_ms = (
            timeout_ms if timeout_ms is not None else _default_timeout_ms()
        )
        # Keys are namespaced per instance so interleaved use of two
        # Communicator objects cannot cross-wire. Auto namespaces are
        # assigned LAZILY at the first collective — constructing a
        # communicator for collective-free work (restore, read_object)
        # on a subset of ranks must not desync the counter that makes
        # namespaces agree across ranks. Ranks must issue their FIRST
        # collective on instances in the same order (SPMD); pass
        # ``namespace`` explicitly when that order may diverge.
        # Explicit namespaces live under "u/" with unsafe characters
        # escaped, so they can never collide with an auto namespace
        # ("i<N>") nor map onto another namespace's barrier ids.
        self._ns: Optional[str] = (
            f"tpusnap/u/{_sanitize_ns(namespace)}"
            if namespace is not None
            else None
        )
        self._seq = 0
        # Prefixes fully consumed on this rank, deletable (by rank 0)
        # once a later barrier proves every rank has moved past them.
        # Guarded by a lock: the async-commit background thread flushes
        # while the main thread may be appending for a newer take.
        self._gc_pending: List[str] = []
        self._gc_lock = threading.Lock()
        # Optional abort watcher (see Communicator.set_wait_watcher).
        self._wait_watcher = None
        # ("barrier", prefix) while blocked in a polling barrier — read
        # by barrier_missing_ranks() from the watchdog thread. A plain
        # attribute write (GIL-atomic); staleness across the hand-off is
        # tolerable for a best-effort diagnostic.
        self._live_wait: Optional[tuple] = None

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def world_size(self) -> int:
        return self._world_size

    def _namespace(self) -> str:
        if self._ns is None:
            self._ns = f"tpusnap/i{_next_instance()}"
        return self._ns

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _flush_gc(self, upto: Optional[int] = None) -> None:
        """Delete pending prefixes whose consumption has been proved
        global — the first ``upto`` of them, or all when None. Called
        right after a successful wait_at_barrier (all pending), or from
        the async commit with an epoch captured at its proof point."""
        with self._gc_lock:
            if upto is None:
                flush, self._gc_pending = self._gc_pending, []
            else:
                flush = self._gc_pending[:upto]
                self._gc_pending = self._gc_pending[upto:]
        if self._rank != 0:
            return
        for prefix in flush:
            try:
                self._client.key_value_delete(prefix)
            except Exception:
                # Best-effort gc of proved-consumed KV prefixes; a leaked
                # key costs service memory, not correctness.
                logger.debug(
                    "coordination-KV gc delete failed for %r", prefix,
                    exc_info=True,
                )

    def set_wait_watcher(self, watcher) -> None:
        self._wait_watcher = watcher

    def clear_wait_watcher(self) -> None:
        self._wait_watcher = None

    def barrier(self) -> None:
        from . import telemetry

        with telemetry.span("comm.barrier"):
            self._barrier_impl()

    def _barrier_impl(self) -> None:
        from . import flight

        seq = self._next_seq()
        # Flight-recorder anchor: every rank logs the SAME anchor string
        # for the same barrier, and the exit event fires at (nearly) the
        # same instant on all ranks — the cross-rank clock-skew
        # alignment `tpusnap timeline` runs on.
        anchor = f"{self._namespace()}/b{seq}"
        flight.record("barrier_enter", op=anchor)
        if self._wait_watcher is not None:
            # Abort-aware mode: the native wait_at_barrier blocks inside
            # the coordination client until its timeout and cannot
            # observe an abort record. Substitute a KV polling barrier
            # (arrive keys + a depart key, LinearBarrier-style) that
            # runs the watcher every poll. All ranks take this branch
            # for the same seq because watcher installation is a fixed
            # point in the take's SPMD program.
            prefix = self._polling_barrier(seq)
            flight.record("barrier_exit", op=anchor)
            # Flush BEFORE registering this barrier's own prefix: the
            # flush must never delete the depart key a slow rank is
            # still polling — this prefix is only provably consumed
            # after the NEXT barrier.
            self._flush_gc()
            with self._gc_lock:
                self._gc_pending.append(prefix + "/")
            return
        # Namespace components contain no "." (auto ids are digits,
        # explicit ones are sanitized), so this mapping is injective —
        # distinct namespaces can never satisfy each other's barriers.
        self._client.wait_at_barrier(
            anchor.replace("/", "."),
            timeout_in_ms=self._timeout_ms,
        )
        flight.record("barrier_exit", op=anchor)
        self._flush_gc()

    def _watched_wait_key(self, key: str, deadline: float):
        """Poll ``key`` until present (returning its value), running the
        wait watcher (which may raise) every iteration."""
        import time

        from .dist_store import _client_try_get

        while True:
            watcher = self._wait_watcher
            if watcher is not None:
                watcher()
            # The probe blocks up to its own 50ms timeout on older
            # clients without key_value_try_get, doubling as the poll
            # interval there.
            value = _client_try_get(self._client, key)
            if value is not None:
                return value
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"Timed out waiting for coordination key {key!r}"
                )
            time.sleep(0.05)

    def _polling_barrier(self, seq: int) -> str:
        """KV-polling two-phase barrier, interoperable only with itself:
        every rank sets an arrive key; rank 0 collects them and sets the
        depart key; non-leaders wait for depart. Returns the key prefix;
        the caller registers it for GC after a LATER barrier proves
        every rank has passed this one (the same lazy proof as
        collective payload keys — deleting the depart key any earlier
        could strand a slow rank).

        Deliberately NOT dist_store.LinearBarrier: that rides
        CoordinationKVStore, whose keys live under its own store prefix
        — outside this communicator's namespace, invisible to the
        _gc_pending raw-client deletes that keep per-take keys from
        accumulating in the coordination service for the job's
        lifetime. Keeping the barrier on raw client keys inside
        ``{ns}/`` makes the existing GC proof cover it for free."""
        import time

        prefix = f"{self._namespace()}/pb{seq}"
        deadline = time.monotonic() + self._timeout_ms / 1000.0
        self._client.key_value_set(f"{prefix}/a/{self._rank}", "1")
        self._live_wait = ("barrier", prefix)
        try:
            if self._rank == 0:
                for r in range(1, self._world_size):
                    self._watched_wait_key(f"{prefix}/a/{r}", deadline)
                self._client.key_value_set(f"{prefix}/d", "1")
            else:
                self._watched_wait_key(f"{prefix}/d", deadline)
        finally:
            self._live_wait = None
        return prefix

    def barrier_missing_ranks(self) -> Optional[List[int]]:
        live = self._live_wait
        if live is None or live[0] != "barrier":
            return None
        try:
            entries = self._client.key_value_dir_get(f"{live[1]}/a")
        except Exception:
            return None
        arrived = set()
        for key, _value in entries:
            try:
                arrived.add(int(key.rsplit("/", 1)[-1]))
            except ValueError:
                continue
        missing = sorted(set(range(self._world_size)) - arrived)
        if not missing:
            # Everyone arrived but we are still waiting: a non-leader is
            # blocked on the depart key, which rank 0 owns — attribute
            # the stall to the leader (mirrors LinearBarrier).
            return [0] if self._rank != 0 else None
        return missing

    def gc_epoch(self) -> int:
        with self._gc_lock:
            return len(self._gc_pending)

    def gc_consumed_keys(self, epoch: Optional[int] = None) -> None:
        self._flush_gc(upto=epoch)

    def all_gather_object(self, obj: Any) -> List[Any]:
        """One KV set + one barrier + ONE dir-get — O(1) RPCs per rank
        regardless of world size (the per-rank serial gets of the naive
        port serialized take/restore at scale)."""
        from . import telemetry

        with telemetry.span("comm.all_gather"):
            return self._all_gather_object_impl(obj)

    def _all_gather_object_impl(self, obj: Any) -> List[Any]:
        seq = self._next_seq()
        prefix = f"{self._namespace()}/ag{seq}"
        self._client.key_value_set(f"{prefix}/{self._rank}", _encode(obj))
        # The barrier guarantees every rank's key is written (and lets
        # rank 0 GC prefixes from earlier collectives).
        self.barrier()
        entries = self._client.key_value_dir_get(prefix)
        by_rank = {}
        for key, raw in entries:
            by_rank[int(key.rsplit("/", 1)[-1])] = raw
        if len(by_rank) != self._world_size:
            raise RuntimeError(
                f"all_gather {prefix!r}: expected {self._world_size} "
                f"entries, got {sorted(by_rank)}"
            )
        with self._gc_lock:
            self._gc_pending.append(prefix + "/")
        return [_decode(by_rank[r]) for r in range(self._world_size)]

    def broadcast_object(self, obj: Any, src: int = 0) -> Any:
        """One set (src) / one blocking get (others); no barrier. The key
        is GC'd after a later barrier proves global consumption."""
        from . import telemetry

        with telemetry.span("comm.broadcast"):
            return self._broadcast_object_impl(obj, src)

    def _broadcast_object_impl(self, obj: Any, src: int = 0) -> Any:
        seq = self._next_seq()
        key = f"{self._namespace()}/bc{seq}"
        if self._rank == src:
            self._client.key_value_set(key, _encode(obj))
            result = obj
        elif self._wait_watcher is not None:
            # Abort-aware wait: the native blocking get cannot be
            # interrupted before its timeout; poll instead, running the
            # watcher (which may raise) each iteration.
            import time

            result = _decode(
                self._watched_wait_key(
                    key, time.monotonic() + self._timeout_ms / 1000.0
                )
            )
        else:
            result = _decode(
                self._client.blocking_key_value_get(key, self._timeout_ms)
            )
        if self._rank == 0:
            with self._gc_lock:
                self._gc_pending.append(key)
        return result


class SubsetComm(JaxCoordinationComm):
    """Collectives over a SUBSET of the jax.distributed world — the
    communicator elastic delta streams run their per-epoch captures on
    (:mod:`tpusnap.delta`): after a rank dies or leaves, the survivors
    keep taking real multi-rank snapshots without it, and a joiner is
    folded in at the next epoch simply by listing it as a member.

    The subset is expressed by RE-RANKING: ``rank``/``world_size``
    report this process's position within ``members`` (sorted global
    process ids), so every loop the parent class runs over
    ``range(world_size)`` — arrive keys, gather slots, leader checks —
    stays correct verbatim. The GLOBAL identity survives as
    ``global_rank``/``global_ranks`` for rendering and forensics (take
    internals — leases, journals, manifests — speak virtual ranks; the
    epoch metadata maps them back).

    Two contract changes against the parent:

    - the namespace is REQUIRED and must be identical (and unique per
      epoch) on every member — the lazy auto-counter cannot agree
      across processes that construct different numbers of
      communicators once the world diverges;
    - barriers always use the KV polling path: the coordination
      service's native ``wait_at_barrier`` counts every process in the
      job, which would park a subset barrier until the full-world
      timeout.
    """

    def __init__(
        self,
        members: List[int],
        namespace: str,
        timeout_ms: Optional[int] = None,
    ) -> None:
        super().__init__(timeout_ms=timeout_ms, namespace=namespace)
        self.global_rank = self._rank
        self.global_ranks = sorted(int(m) for m in members)
        if len(set(self.global_ranks)) != len(self.global_ranks):
            raise ValueError(f"duplicate members: {members}")
        if self.global_rank not in self.global_ranks:
            raise ValueError(
                f"process {self.global_rank} is not a member of {members}"
            )
        self._rank = self.global_ranks.index(self.global_rank)
        self._world_size = len(self.global_ranks)

    def _barrier_impl(self) -> None:
        from . import flight

        seq = self._next_seq()
        anchor = f"{self._namespace()}/b{seq}"
        flight.record("barrier_enter", op=anchor)
        prefix = self._polling_barrier(seq)
        flight.record("barrier_exit", op=anchor)
        # Same GC ordering as the parent's watched branch: flush proved
        # prefixes BEFORE registering this barrier's own.
        self._flush_gc()
        with self._gc_lock:
            self._gc_pending.append(prefix + "/")


def _sanitize_ns(ns: str) -> str:
    """Escape everything outside [A-Za-z0-9_-]: keeps user namespaces
    from colliding with each other or with key/barrier separators."""
    import re

    return re.sub(
        r"[^A-Za-z0-9_-]", lambda m: f"%{ord(m.group(0)):02x}", ns
    )


def _encode(obj: Any) -> str:
    return base64.b64encode(pickle.dumps(obj)).decode("ascii")


def _decode(raw) -> Any:
    if isinstance(raw, bytes):
        raw = raw.decode("ascii")
    return pickle.loads(base64.b64decode(raw))


def get_communicator(comm: Optional[Communicator] = None) -> Communicator:
    """Auto-detect (reference pg_wrapper.py:15-30): explicit comm wins; a
    live multi-process jax.distributed runtime selects the KV-backed
    implementation; otherwise single-process no-op.

    Detection deliberately reads ``jax.distributed``'s coordination state
    instead of calling ``jax.process_count()``: the latter initializes the
    device backend, which is slow (and can block on flaky hardware links)
    — and a snapshot of host-resident state must not require a device at
    all. Multi-process JAX always goes through
    ``jax.distributed.initialize``, so the coordination client is the
    authoritative signal."""
    if comm is not None:
        return comm
    try:
        from jax._src import distributed as _jd

        client = _jd.global_state.client
        nproc = _jd.global_state.num_processes or 1
    except Exception:
        # The private coordination-state API moved (JAX internals carry no
        # stability guarantee). JaxCoordinationComm needs that API too, so
        # there is no degraded mode — but silently treating a multi-host
        # job as single-process would corrupt snapshots, so probe the
        # public API (slower: initializes the device backend) and fail
        # loudly if this really is a multi-process job.
        import jax

        if jax.process_count() > 1:
            raise RuntimeError(
                "tpusnap cannot reach JAX's distributed coordination "
                "client on this JAX version (jax._src.distributed moved); "
                "multi-process snapshots would be corrupted. Pass an "
                "explicit `comm` or update tpusnap."
            )
        return Communicator()

    if client is not None and nproc > 1:
        return JaxCoordinationComm()

    if client is None and _backend_initialized() is not False:
        # Some multi-host deployments (libtpu auto-bootstrap on TPU pods)
        # never call jax.distributed.initialize, so there is no
        # coordination client to ride. A device backend is already live
        # (device-array snapshots imply it is), so probing process_count
        # costs no new backend init — and a >1 answer with no client means
        # snapshots would collide: fail loudly. With no backend
        # initialized we stay backend-free and treat the process as
        # single-process. "Unknown" (the probe itself broke) must run the
        # loud check too: assuming single-process here is the silent
        # snapshot-collision corruption mode.
        import jax

        if jax.process_count() > 1:
            raise RuntimeError(
                "This looks like a multi-host JAX job without "
                "jax.distributed.initialize(); tpusnap needs the "
                "coordination service for cross-host snapshot "
                "consistency. Call jax.distributed.initialize() at "
                "startup or pass an explicit `comm`."
            )
    return Communicator()


def _backend_initialized() -> Optional[bool]:
    """Whether some XLA backend is already live in this process, checked
    without triggering initialization. Returns None when the private probe
    is unavailable (jax._src.xla_bridge moved): the caller must then fall
    back to the loud public-API check instead of assuming single-process —
    a silent False here is exactly the multi-host snapshot-collision mode
    this module is designed to fail loudly on."""
    try:
        from jax._src import xla_bridge as _xb
    except Exception:
        logger.warning(
            "tpusnap cannot probe jax._src.xla_bridge on this JAX version; "
            "falling back to jax.process_count() to rule out an "
            "uncoordinated multi-host job (this may initialize the device "
            "backend)."
        )
        return None
    try:
        return bool(getattr(_xb, "_backends", None))
    except Exception:
        return None
