"""Metadata collectives over the JAX distributed coordination service.

TPU-native counterpart of /root/reference/torchsnapshot/pg_wrapper.py.
The reference funnels small-object collectives (all_gather_object,
broadcast_object_list, barrier) through torch.distributed (gloo/NCCL).
tpusnap instead rides the **coordination-service KV store** that
``jax.distributed.initialize`` brings up over DCN:

- it exists on every multi-host TPU deployment (no extra rendezvous);
- it is usable from background threads, where device collectives are
  forbidden (same constraint as the reference, snapshot.py:902);
- manifests/globs/write-loads are KB-scale — device collectives over ICI
  would be overkill (SURVEY.md §5).

Like the reference's PGWrapper (pg_wrapper.py:15-30), construction
auto-detects the environment: single process → no-op collectives;
``jax.process_count() > 1`` → KV-store-backed collectives.

Sequencing: every collective bumps a process-global sequence number.
Ranks execute the same collectives in the same order (SPMD), so the
sequence numbers agree across ranks and key collisions are impossible;
keys are deleted after a trailing barrier.
"""

from __future__ import annotations

import base64
import logging
import pickle
from typing import Any, List, Optional

logger = logging.getLogger(__name__)

_DEFAULT_TIMEOUT_MS = 600_000  # mirrors reference dist_store.py:17 (600s)


class Communicator:
    """Uniform interface; base class doubles as the single-process no-op
    implementation (reference pg_wrapper.py single-process path)."""

    @property
    def rank(self) -> int:
        return 0

    @property
    def world_size(self) -> int:
        return 1

    def barrier(self) -> None:
        return None

    def all_gather_object(self, obj: Any) -> List[Any]:
        return [obj]

    def broadcast_object(self, obj: Any, src: int = 0) -> Any:
        return obj


_seq = 0


def _next_seq() -> int:
    global _seq
    _seq += 1
    return _seq


class JaxCoordinationComm(Communicator):
    """KV-store-backed collectives for multi-process jobs."""

    def __init__(self, timeout_ms: int = _DEFAULT_TIMEOUT_MS) -> None:
        import jax

        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "jax.distributed is not initialized; call "
                "jax.distributed.initialize() before using tpusnap across "
                "processes"
            )
        self._client = client
        self._rank = jax.process_index()
        self._world_size = jax.process_count()
        self._timeout_ms = timeout_ms

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def world_size(self) -> int:
        return self._world_size

    def barrier(self) -> None:
        seq = _next_seq()
        self._client.wait_at_barrier(f"tpusnap_b{seq}", timeout_in_ms=self._timeout_ms)

    def all_gather_object(self, obj: Any) -> List[Any]:
        seq = _next_seq()
        prefix = f"tpusnap/ag{seq}"
        self._client.key_value_set(f"{prefix}/{self._rank}", _encode(obj))
        out = []
        for r in range(self._world_size):
            raw = self._client.blocking_key_value_get(
                f"{prefix}/{r}", self._timeout_ms
            )
            out.append(_decode(raw))
        # Everyone has read every key; rank 0 garbage-collects the prefix.
        self.barrier()
        if self._rank == 0:
            try:
                self._client.key_value_delete(prefix + "/")
            except Exception:
                pass
        return out

    def broadcast_object(self, obj: Any, src: int = 0) -> Any:
        seq = _next_seq()
        key = f"tpusnap/bc{seq}"
        if self._rank == src:
            self._client.key_value_set(key, _encode(obj))
            result = obj
        else:
            result = _decode(
                self._client.blocking_key_value_get(key, self._timeout_ms)
            )
        self.barrier()
        if self._rank == src:
            try:
                self._client.key_value_delete(key)
            except Exception:
                pass
        return result


def _encode(obj: Any) -> str:
    return base64.b64encode(pickle.dumps(obj)).decode("ascii")


def _decode(raw) -> Any:
    if isinstance(raw, bytes):
        raw = raw.decode("ascii")
    return pickle.loads(base64.b64decode(raw))


def get_communicator(comm: Optional[Communicator] = None) -> Communicator:
    """Auto-detect (reference pg_wrapper.py:15-30): explicit comm wins; a
    live multi-process jax.distributed runtime selects the KV-backed
    implementation; otherwise single-process no-op."""
    if comm is not None:
        return comm
    try:
        import jax

        if jax.process_count() > 1:
            return JaxCoordinationComm()
    except Exception:
        pass
    return Communicator()
