"""Crash-safe snapshot lifecycle: take journal, fsck/GC, salvage-resume.

The two-phase commit proves ``metadata exists ⟺ snapshot restores
bit-exact`` — but says nothing about the OTHER side of a crash. This
module closes that gap:

- **Take journal** (``.tpusnap/journal``): rank 0 writes a record (take
  id, world size, incremental base, started-at) through the take's own
  storage plugin BEFORE any blob write and clears it only after the
  metadata commit, so a directory holding a SIGKILLed take is
  distinguishable from a committed snapshot, an empty path, or foreign
  files. While writes run, every rank journals per-blob completion
  records (``.tpusnap/journal.d/rank_<k>``: location → size + CRC32C +
  XXH64 of the exact bytes written) — the salvage evidence.
- **fsck** classifies a directory (committed / torn / empty /
  corrupt-metadata / foreign) and, on backends that can list, enumerates
  orphan blobs unreferenced by the manifest. **gc** reclaims them —
  dry-run by default, and safe to run concurrently with readers because
  orphan blobs are never referenced by any committed manifest.
- **Salvage-resume**: a take to a path holding a torn take loads the
  journal's completion records; any staged blob whose freshly computed
  CRC32C+XXH64 pair (the SAME dual-hash evidence rule incremental dedup
  uses — one 32-bit CRC leaves a ~2^-32 silent-collision channel)
  matches the record for its target location skips its storage write:
  the bytes are already on disk. A crash at 90% of a multi-TB take costs
  ~10% of the bytes on retry. Slab blobs carry fresh uuid locations each
  take and are simply rewritten (their members are small by
  construction).

Trust model: a completion record is written only AFTER the storage op
returned success, so record ⟹ the blob held exactly those bytes. This is
process-crash-grade evidence (SIGKILL, OOM-kill, preemption — the page
cache survives); power-loss-grade salvage additionally needs
``TPUSNAP_DURABLE_COMMIT=1`` at the torn take (each blob fsync'd before
its record). Post-salvage integrity is independently provable either
way: the committed manifest records stage-time checksums, so
``python -m tpusnap verify`` re-reads every salvaged byte.
"""

from __future__ import annotations

import asyncio
import json
import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import _native, flight, telemetry
from .io_types import (
    CAS_REFS_DIR,
    FLIGHT_DIR,
    JOURNAL_PATH,
    JOURNAL_RECORDS_DIR,
    PROBE_DIR,
    PROGRESS_DIR,
    SIDECAR_PREFIX,
    TELEMETRY_DIR,
    UPLOAD_JOURNAL_PATH,
    ReadIO,
    StoragePlugin,
    WriteIO,
)
from .manifest import MetadataError, SnapshotMetadata, decode_metadata

logger = logging.getLogger(__name__)

__all__ = [
    "FsckReport",
    "GCReport",
    "TakeJournal",
    "fsck_snapshot",
    "gc_snapshot",
]

# Canonical sidecar paths live in io_types; the historical local name
# is kept for external callers (tests import JOURNAL_FNAME from here).
JOURNAL_FNAME = JOURNAL_PATH
_SIDECAR_PREFIX = SIDECAR_PREFIX  # canonical definition: io_types
# Heartbeat records (tpusnap.progress): observability-only — ignored by
# fsck's empty/foreign decision, legit in committed snapshots.
_PROGRESS_SIDECAR_PREFIX = PROGRESS_DIR + "/"
# Roofline probe streams (scheduler._ProbeRunner, TPUSNAP_PROBE=1):
# transient; ignored by the empty/foreign decision (a stranded stream
# must not make an aborted dir unreusable) but NOT legit post-commit —
# in a committed snapshot a leftover is an orphan gc reclaims.
_PROBE_SIDECAR_PREFIX = PROBE_DIR + "/"
# Flight-recorder event logs (tpusnap.flight): observability-only, the
# same class as heartbeats — legit in committed snapshots (the black
# box of the take that produced them) and exempt from the empty/foreign
# decision (an aborted/killed take's forensic breadcrumb is the whole
# point; it must not lock the path out of reuse).
_FLIGHT_SIDECAR_PREFIX = FLIGHT_DIR + "/"


def journal_rank_path(rank: int) -> str:
    return f"{JOURNAL_RECORDS_DIR}/rank_{rank}"


def dual_hash_evidence(buf) -> Tuple[int, str, str]:
    """The dual-hash evidence triple of a buffer —
    ``(nbytes, "<algo>:<8-hex>", "<algo>:<16-hex>")`` from ONE fused
    CRC32C+XXH64 pass. The one evidence rule shared by incremental
    dedup, salvage-resume and the write-back upload journal
    (:mod:`tpusnap.tiering`): a 32-bit CRC alone leaves a ~2^-32
    silent-collision channel, the 64-bit lane closes it."""
    from .knobs import get_native_copy_threads

    mv = memoryview(buf).cast("B")
    crcs, xxhs = _native.crc_xxh_tiles(mv, 0, nthreads=get_native_copy_threads())
    return (
        mv.nbytes,
        f"{_native.checksum_algorithm()}:{crcs[0] & 0xFFFFFFFF:08x}",
        f"{_native.dedup_hash_algorithm()}:{xxhs[0] & ((1 << 64) - 1):016x}",
    )


def is_journal_path(path: str) -> bool:
    """True for the journal marker and its per-rank record files (the
    fault layer groups ops on these under the ``journal`` chaos kind)."""
    return path == JOURNAL_FNAME or path.startswith(JOURNAL_RECORDS_DIR + "/")


# ------------------------------------------------------------------ journal


@dataclass
class TakeJournal:
    """The ``.tpusnap/journal`` record: present ⟺ a take started here and
    its metadata commit has not completed (modulo the post-commit clear,
    which fsck treats as stale when valid metadata exists)."""

    take_id: str
    world_size: int
    started_at: float
    incremental_from: Optional[str] = None
    version: str = ""
    # Delta-chain membership (tpusnap.delta): the stream id, this
    # micro-commit's sequence number and its parent member name — what
    # lets fsck/timeline name the in-flight delta state of a take that
    # never committed (a committed member carries the same fields in
    # its metadata ``extras["delta"]`` instead).
    stream: Optional[Dict[str, Any]] = None

    def to_json(self) -> str:
        d = {
            "take_id": self.take_id,
            "world_size": self.world_size,
            "started_at": self.started_at,
            "incremental_from": self.incremental_from,
            "version": self.version,
        }
        if self.stream:
            d["stream"] = self.stream
        return json.dumps(d)

    @classmethod
    def from_json(cls, s: str) -> "TakeJournal":
        d = json.loads(s)
        stream = d.get("stream")
        return cls(
            take_id=d["take_id"],
            world_size=int(d["world_size"]),
            started_at=float(d.get("started_at", 0.0)),
            incremental_from=d.get("incremental_from"),
            version=d.get("version", ""),
            stream=stream if isinstance(stream, dict) else None,
        )


def write_journal(
    storage: StoragePlugin,
    event_loop: asyncio.AbstractEventLoop,
    journal: TakeJournal,
) -> None:
    """Rank 0, before any blob write. Atomic so a crash mid-write never
    leaves a torn journal masquerading as one."""
    storage.sync_write_atomic(
        WriteIO(path=JOURNAL_FNAME, buf=journal.to_json().encode("utf-8")),
        event_loop,
    )
    flight.record(
        "journal", op="marker_written", take_id=journal.take_id[:8]
    )


def read_journal(
    storage: StoragePlugin, event_loop: asyncio.AbstractEventLoop
) -> Optional[TakeJournal]:
    """The journal at this path, or None (absent/unreadable/corrupt —
    corrupt is logged and treated as absent: the journal is advisory
    metadata, never load-bearing for restore correctness)."""
    read_io = ReadIO(path=JOURNAL_FNAME)
    try:
        storage.sync_read(read_io, event_loop)
    except Exception:
        return None
    try:
        return TakeJournal.from_json(read_io.buf.getvalue().decode("utf-8"))
    except Exception:
        logger.warning("Unparseable take journal at %r; ignoring", JOURNAL_FNAME)
        return None


def clear_journal(
    storage: StoragePlugin,
    event_loop: asyncio.AbstractEventLoop,
    world_size: int,
) -> None:
    """Post-commit (rank 0) / abort cleanup: best-effort removal of the
    per-rank completion records, then the journal marker LAST — at no
    point does a record file outlive the marker's promise, and a crash
    mid-clear leaves a stale-but-classifiable state (valid metadata +
    journal = committed; fsck flags the leftovers as orphans).

    ``world_size`` must cover every rank that may have written a record
    — a salvage-retake over a torn take with a LARGER world size passes
    the max of the two (see ``_take_impl``), which is what lets this
    stay a fixed set of deletes instead of a full storage listing on
    every take's commit path. Flush-tmp debris (``rank_k.tmp.<pid>``)
    from a SIGKILLed flush is not covered; it is fsck-visible and gc
    reclaims it."""
    for r in range(world_size):
        try:
            storage.sync_delete(journal_rank_path(r), event_loop)
        except Exception:
            # A surviving record file under a cleared marker is inert
            # (fsck flags it as an orphan); the take is committed.
            logger.debug(
                "journal record delete failed (rank %d)", r, exc_info=True
            )
    try:
        storage.sync_delete(JOURNAL_FNAME, event_loop)
        flight.record("journal", op="marker_cleared")
    except Exception:
        # Marker outliving the commit keeps the dir classifiable
        # (valid metadata + journal = committed); not worth failing a
        # finished take over, but worth a trace.
        logger.debug("journal marker delete failed", exc_info=True)


def load_salvage_records(
    storage: StoragePlugin,
    event_loop: asyncio.AbstractEventLoop,
    world_size: int,
    files: Optional[Dict[str, int]] = None,
) -> Dict[str, Tuple[int, str, str]]:
    """Every rank's completion records from a torn take, merged:
    ``{location: (nbytes, "<algo>:<8-hex>", "<algo>:<16-hex>")}``. Any
    rank may reuse any rank's blob — the write-load partition of the
    retake need not match the torn take's.

    REQUIRES a listing (``files``, or the backend's
    ``list_with_sizes``): record files are discovered by listing
    ``journal.d/`` (robust to the torn take having had a different world
    size than the journal a concurrent retake may already have
    overwritten — benign either way: the evidence rule compares staged
    bytes against the record, so a stale or racing record can only cause
    a rewrite, never a wrong skip), and every record is cross-checked
    against the files actually present (existence + exact size). That
    cross-check is LOAD-BEARING, not an optimization: a record whose
    blob is gone — e.g. a record file that outlived an abort's blob
    cleanup by one SIGKILL — must never license a write skip, or the
    retake commits a manifest referencing a missing blob. Backends that
    cannot list therefore get NO salvage (empty dict; the journal still
    classifies their crashes). ``world_size`` is unused when a listing
    exists and kept for the signature's documentation value."""
    if files is None:
        try:
            files = storage.sync_list_with_sizes(event_loop)
        except Exception:
            files = None
    if files is None:
        logger.info(
            "Salvage-resume disabled: this storage backend cannot list, "
            "so completion records cannot be cross-checked against the "
            "blobs actually present"
        )
        return {}
    out: Dict[str, Tuple[int, str, str]] = {}
    for rec_path in sorted(
        p for p in files if p.startswith(JOURNAL_RECORDS_DIR + "/")
    ):
        read_io = ReadIO(path=rec_path)
        try:
            storage.sync_read(read_io, event_loop)
            recs = json.loads(read_io.buf.getvalue().decode("utf-8"))
        except Exception:
            continue  # flush-tmp debris, or a torn record flush
        if not isinstance(recs, dict):
            continue
        for loc, rec in recs.items():
            try:
                out[loc] = (int(rec[0]), str(rec[1]), str(rec[2]))
            except (IndexError, TypeError, ValueError):
                continue
    return {loc: rec for loc, rec in out.items() if files.get(loc) == rec[0]}


class JournalingStoragePlugin(StoragePlugin):
    """Wraps a take's (fully middleware-composed) storage plugin:

    - every successful blob ``write`` appends a completion record
      (location → nbytes + CRC32C + XXH64 of the written bytes, both
      lanes from ONE fused pass) and flushes this rank's record file
      atomically — the salvage evidence a retake consumes;
    - when salvage records from a torn take are loaded, a ``write``
      whose buffer's dual hash matches the record for its target
      location is SKIPPED (the bytes are already on disk), counted in
      the ``salvage.bytes_salvaged`` / ``salvage.blobs_salvaged``
      telemetry counters, and re-recorded so a second crash still finds
      its evidence.

    Sidecar writes (``.tpusnap/``, the metadata file) are never
    journaled. Scheduling-transparent like the retry/chaos wrappers.
    With checksums disabled (``TPUSNAP_DISABLE_CHECKSUM=1``) neither
    recording nor salvage runs — there is no evidence rule to apply —
    but the journal marker itself still makes the take classifiable."""

    def __init__(
        self,
        inner: StoragePlugin,
        rank: int,
        salvage_records: Optional[Dict[str, Tuple[int, str, str]]] = None,
    ) -> None:
        self.inner = inner
        self.rank = rank
        self.salvage_records = salvage_records or {}
        from .knobs import is_checksum_disabled, is_journal_disabled

        self._hashing = not is_checksum_disabled() and not is_journal_disabled()
        # How many ranks' record files a commit/abort clear must cover —
        # widened by the take when a prior (torn) journal had a larger
        # world size. The take sets it after construction.
        self.clear_world_size = 1
        # Seeded with the loaded salvage records: every record flush
        # (including the take-start eager one) re-persists the torn
        # take's evidence, so a SECOND crash early in a salvage-retake
        # still finds records for the not-yet-reprocessed blobs. Safe —
        # a stale entry can only cause a rewrite, never a wrong skip
        # (the existence/size cross-check and dual-hash rule gate every
        # skip).
        self._records: Dict[str, List[Any]] = {
            loc: list(rec) for loc, rec in (salvage_records or {}).items()
        }
        # Single-loop coroutines: plain flags serialize the flusher.
        self._dirty = False
        self._flushing = False
        self._executor = None

    def sync_seed_record_file(
        self, event_loop: asyncio.AbstractEventLoop
    ) -> None:
        """Take-start eager write of this rank's record file: proves a
        take started here (the journal-family evidence fsck classifies
        on) WITHOUT losing loaded salvage records — the seeded content
        is written, not an empty map."""
        self.inner.sync_write_atomic(
            WriteIO(
                path=journal_rank_path(self.rank),
                buf=json.dumps(self._records).encode("utf-8"),
            ),
            event_loop,
        )

    def _get_executor(self):
        # The fused hash pass runs GIL-released in native code on a
        # worker thread — blocking the event loop for a multi-hundred-MB
        # pass would stall every concurrent I/O dispatch.
        if self._executor is None:
            from concurrent.futures import ThreadPoolExecutor

            self._executor = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="tpusnap-journal"
            )
        return self._executor

    # --- scheduling transparency -----------------------------------------

    @property
    def supports_in_place_reads(self) -> bool:  # type: ignore[override]
        return self.inner.supports_in_place_reads

    def in_place_read_overhead_bytes(self, nbytes: int) -> int:
        return self.inner.in_place_read_overhead_bytes(nbytes)

    def drain_in_flight(self) -> None:
        self.inner.drain_in_flight()

    def classify_transient(self, exc: BaseException) -> bool:
        from .retry import default_classify_transient

        return getattr(
            self.inner, "classify_transient", default_classify_transient
        )(exc)

    # --- journaling core --------------------------------------------------

    def _hash_pair(self, buf) -> Tuple[int, str, str]:
        # One fused pass, honoring the total copy-thread budget (the
        # journal hash runs concurrently with the staging executor).
        return dual_hash_evidence(buf)

    async def _record(self, path: str, triple: Tuple[int, str, str]) -> None:
        self._records[path] = list(triple)
        self._dirty = True
        if self._flushing:
            return  # the in-progress flusher will pick this record up
        self._flushing = True
        try:
            while self._dirty:
                self._dirty = False
                payload = json.dumps(self._records).encode("utf-8")
                await self.inner.write_atomic(
                    WriteIO(path=journal_rank_path(self.rank), buf=payload)
                )
        except Exception:
            # Best-effort evidence: a lost flush only shrinks what a
            # future salvage can reuse — never fails the take.
            logger.warning(
                "journal record flush failed (non-fatal)", exc_info=True
            )
        finally:
            self._flushing = False

    # --- plugin interface -------------------------------------------------

    async def write(self, write_io: WriteIO) -> None:
        if (
            not self._hashing
            or write_io.path.startswith(_SIDECAR_PREFIX)
            # Slab objects are uuid-named per take: a retake can never
            # reuse one, so journaling them is pure cost (their members
            # are small by construction — the slab threshold).
            or write_io.path.startswith("batched/")
        ):
            await self.inner.write(write_io)
            return
        loop = asyncio.get_running_loop()
        triple = await loop.run_in_executor(
            self._get_executor(), self._hash_pair, write_io.buf
        )
        prior = self.salvage_records.get(write_io.path)
        if prior is not None and tuple(prior) == triple and triple[0] > 0:
            # Dual-hash evidence matched: the torn take already persisted
            # exactly these bytes at exactly this location — skip the
            # write. (Zero-byte blobs are rewritten: trivial, and it
            # keeps "skipped" synonymous with "bytes salvaged".)
            telemetry.incr("salvage.blobs_salvaged")
            telemetry.incr("salvage.bytes_salvaged", triple[0])
            telemetry.event(
                "salvaged_blob", path=write_io.path, bytes=triple[0]
            )
            flight.record(
                "blob_salvaged", op=write_io.path, bytes=triple[0]
            )
            await self._record(write_io.path, triple)
            return
        # Hand the fused-pass evidence down the chain: a CAS layer below
        # keys the shared-store blob on exactly this triple instead of
        # paying a second hash pass over the same bytes.
        write_io.dedup_triple = triple
        await self.inner.write(write_io)
        # Completion evidence exists the moment the record lands; the
        # flight event mirrors it so the post-mortem timeline shows
        # which blobs PROVABLY finished before the lights went out.
        flight.record("blob_complete", op=write_io.path, bytes=triple[0])
        await self._record(write_io.path, triple)

    async def write_atomic(self, write_io: WriteIO, durable: bool = False) -> None:
        await self.inner.write_atomic(write_io, durable=durable)

    async def read(self, read_io: ReadIO) -> None:
        await self.inner.read(read_io)

    async def delete(self, path: str) -> None:
        await self.inner.delete(path)

    async def list_with_sizes(self):
        return await self.inner.list_with_sizes()

    async def flush_created_dirs(self) -> None:
        await self.inner.flush_created_dirs()

    async def close(self) -> None:
        if self._executor is not None:
            # Finalizer-safe join policy (io_types): explicit closes
            # join the hash worker (quiescence), GC-finalizer closes
            # must not (the PR 6 Thread._set_tstate_lock self-deadlock).
            from .io_types import shutdown_plugin_executor

            shutdown_plugin_executor(self._executor)
            self._executor = None
        await self.inner.close()


# --------------------------------------------------------------------- fsck


#: fsck states. "foreign" = files present but neither metadata nor
#: journal — this directory was not produced by a tpusnap take.
FSCK_STATES = ("committed", "torn", "empty", "corrupt-metadata", "foreign")


@dataclass
class FsckReport:
    """Outcome of classifying one snapshot directory."""

    path: str
    state: str  # one of FSCK_STATES
    detail: str = ""
    journal: Optional[TakeJournal] = None
    metadata: Optional[SnapshotMetadata] = None
    listing_supported: bool = True
    # committed: files not referenced by the manifest and not legitimate
    # sidecars (stale journals, torn-take leftovers, *.tmp.* debris).
    orphans: Dict[str, int] = field(default_factory=dict)
    # torn: completion-record evidence a salvage-resume will actually
    # use (already cross-checked against the listing: records whose blob
    # is gone or resized are excluded).
    salvage_records: int = 0
    salvage_bytes_present: int = 0
    # committed: dangling external (../) base references, present-but-
    # unverifiable only when the backend cannot list — counted here only
    # for this snapshot's own files.
    referenced_files: int = 0
    missing_referenced: List[str] = field(default_factory=list)
    # Write-back tiering (tpusnap.tiering): the two-state durability
    # ladder when this directory is a tiered snapshot's LOCAL tier —
    # "local-committed" (upload journal present, drain pending) or
    # "remote-durable" (the durable marker was written after the last
    # remote blob + remote metadata verify). None = not tiered.
    durability: Optional[str] = None
    tier_remote: Optional[str] = None
    # remote-durable only: referenced blobs absent LOCALLY because gc
    # evicted them past the durable marker — restorable through the
    # tier's remote fallback, so they are NOT counted as missing.
    evicted: List[str] = field(default_factory=list)
    # Delta-chain membership of this directory, when it is (or was
    # becoming) a micro-commit of a delta stream: {"stream", "seq",
    # "parent"} from the committed metadata's extras (committed) or
    # the take journal (torn) — what makes a torn tail explainable as
    # "micro-commit N over member X" instead of an anonymous torn take.
    delta: Optional[Dict[str, Any]] = None
    # Content-addressed store (tpusnap.cas): ref records this snapshot
    # holds instead of private payload copies. ``cas_resolved`` are
    # referenced locations whose shared blob the store verifiably holds
    # (they are NOT missing even though the snapshot dir has no such
    # file); ``cas_dangling`` are refs whose blob the store has LOST —
    # restore-breaking, the one CAS state that exits nonzero (4).
    cas_store: Optional[str] = None
    cas_refs: int = 0
    cas_dedup_bytes: int = 0
    cas_resolved: List[str] = field(default_factory=list)
    cas_dangling: List[str] = field(default_factory=list)
    # The listing this classification was computed from (None when the
    # backend cannot list) — reused by gc so one fsck+gc pays one walk.
    files: Optional[Dict[str, int]] = field(default=None, repr=False)

    def summary(self) -> str:
        s = f"{self.path}: {self.state}"
        if self.detail:
            s += f" ({self.detail})"
        if self.state == "committed":
            s += (
                f" — {self.referenced_files} referenced file(s)"
                + (
                    f", {len(self.missing_referenced)} MISSING"
                    if self.missing_referenced
                    else ""
                )
                + (
                    f", {len(self.orphans)} orphan(s) / "
                    f"{sum(self.orphans.values())} bytes reclaimable"
                    if self.orphans
                    else ", no orphans"
                    if self.listing_supported
                    else ", orphan scan unsupported on this backend"
                )
            )
            deg = (getattr(self.metadata, "extras", None) or {}).get(
                "degraded"
            )
            if isinstance(deg, dict) and deg.get("dead_ranks"):
                s += (
                    f" [DEGRADED commit: rank(s) {deg['dead_ranks']} died "
                    "mid-take; their replicated writes were adopted by "
                    "the survivors]"
                )
            if self.cas_refs:
                s += (
                    f" [CAS: {self.cas_refs} ref(s) into "
                    f"{self.cas_store or 'unknown store'}, "
                    f"{self.cas_dedup_bytes} bytes deduplicated"
                    + (
                        f"; {len(self.cas_dangling)} DANGLING ref(s) — "
                        "the store lost blob(s) this snapshot needs"
                        if self.cas_dangling
                        else ""
                    )
                    + "]"
                )
            if self.durability is not None:
                s += f" [{self.durability}"
                if self.durability == "local-committed" and self.tier_remote:
                    s += f" — cloud drain to {self.tier_remote} pending"
                elif self.tier_remote:
                    s += f" at {self.tier_remote}"
                if self.evicted:
                    s += (
                        f"; {len(self.evicted)} local blob(s) evicted, "
                        "restorable from the remote tier"
                    )
                s += "]"
        elif self.state == "torn":
            s += (
                f" — take {self.journal.take_id[:8]} world_size="
                f"{self.journal.world_size}; {self.salvage_records} "
                f"salvageable blob record(s), {self.salvage_bytes_present} "
                "bytes intact on disk (salvage-resume will reuse matching "
                "blobs)"
                if self.journal is not None
                else ""
            )
        if self.delta:
            seq = self.delta.get("seq")
            parent = self.delta.get("parent")
            if self.state == "torn":
                s += (
                    f" [torn delta micro-commit seq {seq}"
                    + (f" over {parent!r}" if parent else "")
                    + " — recovery lands on the last committed increment;"
                    " retake/gc like any torn take]"
                )
            else:
                s += (
                    f" [delta increment seq {seq}"
                    + (f", parent {parent!r}" if parent else "")
                    + "]"
                )
        return s


def _referenced_locations(metadata: SnapshotMetadata) -> set:
    """Every LOCAL file a committed manifest references (external ``../``
    locations live in base snapshots and are not this directory's)."""
    from .inspect import _entry_tensors

    out = set()
    for entry in metadata.manifest.values():
        for t in _entry_tensors(entry):
            if not t.location.startswith("../"):
                out.add(t.location)
    return out


def _is_legit_sidecar(path: str) -> bool:
    """Sidecars a committed snapshot legitimately carries: telemetry
    traces, the final heartbeat records, the flight-recorder event
    logs, and the write-back upload journal (it IS the post-commit
    durability state — clearing it would forget what is proven remote).
    The take-journal family is NOT legit post-commit (the commit clears
    it), and ``.tmp.<pid>`` debris anywhere — including a SIGKILLed
    journal/telemetry/heartbeat atomic write — is reclaimable, so both
    count as orphans. CAS ref records (``.tpusnap/cas_refs/``) are the
    committed snapshot's claim on its shared-store blobs — deleting one
    would hand the blob to the store's next sweep."""
    if path == UPLOAD_JOURNAL_PATH:
        return True
    return (
        path.startswith(
            (
                TELEMETRY_DIR + "/",
                _PROGRESS_SIDECAR_PREFIX,
                _FLIGHT_SIDECAR_PREFIX,
                CAS_REFS_DIR + "/",
            )
        )
        and ".tmp." not in path.rsplit("/", 1)[-1]
    )


def fsck_snapshot(
    path: str,
    storage_options: Optional[Dict[str, Any]] = None,
    resources: Optional[
        Tuple[asyncio.AbstractEventLoop, StoragePlugin]
    ] = None,
) -> FsckReport:
    """Classify the directory at ``path`` and enumerate reclaimable
    orphans. Read-only; never mutates anything. See :data:`FSCK_STATES`.

    Exposed as ``python -m tpusnap fsck <path>``."""
    from .storage_plugin import url_to_storage_plugin_in_event_loop

    owns = resources is None
    if owns:
        event_loop = asyncio.new_event_loop()
        storage = None
    else:
        event_loop, storage = resources
    try:
        if storage is None:
            storage = url_to_storage_plugin_in_event_loop(
                path, event_loop, storage_options
            )
        try:
            return _fsck_impl(path, storage, event_loop)
        finally:
            if owns:
                storage.sync_close(event_loop)
    finally:
        if owns:
            event_loop.close()


def _fsck_impl(
    path: str,
    storage: StoragePlugin,
    event_loop: asyncio.AbstractEventLoop,
) -> FsckReport:
    from .snapshot import SNAPSHOT_METADATA_FNAME

    report = FsckReport(path=path, state="empty")
    listing = storage.sync_list_with_sizes(event_loop)
    report.listing_supported = listing is not None
    report.files = listing
    files = listing or {}

    meta_bytes: Optional[bytes] = None
    read_io = ReadIO(path=SNAPSHOT_METADATA_FNAME)
    try:
        storage.sync_read(read_io, event_loop)
        meta_bytes = read_io.buf.getvalue()
    except Exception as e:
        # Read failed but the listing PROVES the file exists: this is a
        # storage/permission problem, not absence — refusing to classify
        # beats calling a committed snapshot "torn" and steering the
        # operator toward `gc --torn`, which would delete it.
        if report.listing_supported and SNAPSHOT_METADATA_FNAME in files:
            raise RuntimeError(
                f"{path!r}: {SNAPSHOT_METADATA_FNAME} exists but could "
                f"not be read ({e}) — fix storage access and re-run fsck; "
                "refusing to classify"
            ) from e
        meta_bytes = None

    report.journal = read_journal(storage, event_loop)
    # An unparseable journal FILE still proves a take started here — and
    # so does any per-rank record file: every rank eagerly creates its
    # own before writing blobs, which is what keeps a gang-SIGKILL in
    # the tiny pre-marker window classifiable as torn instead of
    # foreign. Only total absence of the whole journal family means no
    # take.
    journal_file_exists = report.journal is not None or (
        report.listing_supported
        and any(is_journal_path(p) for p in files)
    )

    if meta_bytes is not None:
        try:
            report.metadata = decode_metadata(meta_bytes)
        except MetadataError as e:
            report.state = "corrupt-metadata"
            report.detail = str(e)
            return report
        report.state = "committed"
        from .manifest_ops import delta_chain_fields

        delta_fields = delta_chain_fields(report.metadata)
        if delta_fields is not None:
            report.delta = dict(delta_fields)
        referenced = _referenced_locations(report.metadata)
        report.referenced_files = len(referenced)
        if report.journal is not None:
            report.detail = (
                "stale journal present (crash between metadata commit and "
                "journal clear) — reclaimable via gc"
            )
        # Write-back tiering: the upload journal carries the durability
        # ladder of a tiered snapshot's local tier.
        from .tiering import durability_of_journal, read_upload_journal

        tier_journal = read_upload_journal(storage, event_loop)
        report.durability = durability_of_journal(tier_journal)
        if tier_journal is not None:
            report.tier_remote = tier_journal.get("remote")
        if report.listing_supported:
            report.missing_referenced = sorted(
                loc for loc in referenced if loc not in files
            )
            if (
                report.missing_referenced
                and report.durability == "remote-durable"
            ):
                # Past the durable marker, a locally-absent referenced
                # blob is an EVICTED hot-cache entry, not data loss: a
                # restore through the tier URL reads it from the remote
                # (fsck the remote URL to verify the cloud copy itself).
                report.evicted = report.missing_referenced
                report.missing_referenced = []
            # Content-addressed refs: a referenced location held as a
            # CAS ref has no private file here BY DESIGN — resolve it
            # against the shared store before calling it missing. The
            # probe is a DEEP store check even when a composed CAS
            # plugin synthesized the location into the listing: the
            # synthetic entry proves a ref exists, not that the store
            # still holds the blob (a sweep may have raced it away —
            # the one restore-breaking CAS state, "dangling ref").
            from .cas import blob_exists_in_store, blob_key as _cas_key
            from .cas import read_refs, resolve_store_url

            cas_refs, cas_store = read_refs(storage, event_loop)
            if cas_refs:
                report.cas_store = cas_store or resolve_store_url()
                report.cas_refs = len(cas_refs)
                missing = set(report.missing_referenced)
                for loc in sorted(set(cas_refs) & referenced):
                    rec = cas_refs[loc]
                    missing.discard(loc)
                    if blob_exists_in_store(
                        report.cas_store, _cas_key(tuple(rec))
                    ):
                        report.cas_resolved.append(loc)
                        report.cas_dedup_bytes += int(rec[0])
                    else:
                        report.cas_dangling.append(loc)
                report.missing_referenced = sorted(missing)
            if report.missing_referenced:
                report.detail = (
                    f"{len(report.missing_referenced)} referenced blob(s) "
                    "missing from storage — the snapshot will not restore"
                )
            if report.cas_dangling:
                report.detail = (
                    f"{len(report.cas_dangling)} CAS ref(s) DANGLING — "
                    f"the store at {report.cas_store!r} no longer holds "
                    "their blobs; the snapshot will not restore"
                )
            report.orphans = {
                p: sz
                for p, sz in sorted(files.items())
                if p not in referenced
                and p != SNAPSHOT_METADATA_FNAME
                and not _is_legit_sidecar(p)
            }
        return report

    if journal_file_exists:
        report.state = "torn"
        if report.journal is not None and report.journal.stream:
            # A torn delta micro-commit: the journal names the stream,
            # sequence number and parent — recovery is "restore the
            # last committed increment", never this directory.
            report.delta = dict(report.journal.stream)
            world = report.journal.stream.get("world")
            ranks = (
                world.get("ranks") if isinstance(world, dict) else None
            )
            if (
                isinstance(ranks, list)
                and ranks
                and report.listing_supported
            ):
                # Multi-rank epoch: name the GLOBAL rank(s) whose
                # per-rank evidence never landed — whose writes the
                # tear interrupted (journal virtual rank v maps to
                # global ranks[v]).
                have = {
                    v
                    for v in range(len(ranks))
                    if journal_rank_path(v) in files
                }
                missing = [
                    int(ranks[v])
                    for v in range(len(ranks))
                    if v not in have
                ]
                if missing:
                    report.detail = (
                        "torn multi-rank micro-commit: journal evidence "
                        f"missing from global rank(s) {missing} (world "
                        f"{ranks})"
                    )
        if report.journal is not None:
            # Already existence/size-filtered against the listing — what
            # a salvage-retake will actually consider (empty on backends
            # that cannot list, where salvage is disabled).
            records = load_salvage_records(
                storage,
                event_loop,
                report.journal.world_size,
                files=files if report.listing_supported else None,
            )
            report.salvage_records = len(records)
            report.salvage_bytes_present = sum(
                n for n, _, _ in records.values()
            )
        else:
            report.detail = (
                "journal marker missing or unparseable but per-rank "
                "records exist (torn marker write, or a kill inside the "
                "pre-marker window)"
            )
        return report

    # Heartbeat records (.tpusnap/progress/) are observability
    # breadcrumbs, never take evidence or payload: an ABORTED take
    # cleans its blobs and journal but leaves its final "aborted"
    # record for post-mortems — the path must still read as empty
    # (reusable), not foreign. Roofline probe streams (.tpusnap/probe/,
    # TPUSNAP_PROBE=1) are the same class: transient raw bytes a flaky
    # backend's failed cleanup can strand in an aborted dir; they must
    # not lock the path into "foreign" (which gc refuses) — in any
    # OTHER state they stay orphan-visible and reclaimable.
    meaningful = {
        p: sz
        for p, sz in files.items()
        if not p.startswith(
            (
                _PROGRESS_SIDECAR_PREFIX,
                _PROBE_SIDECAR_PREFIX,
                _FLIGHT_SIDECAR_PREFIX,
            )
        )
        # The write-back upload journal is tier bookkeeping, never take
        # evidence: a tiered take that died before its take journal (or
        # with TPUSNAP_DISABLE_JOURNAL=1) must not read as foreign.
        and p != UPLOAD_JOURNAL_PATH
    }
    if meaningful:
        report.state = "foreign"
        report.detail = (
            f"{len(meaningful)} file(s) but no metadata and no journal — "
            "not a tpusnap take (or a pre-journal crash); refusing to "
            "classify as torn"
        )
    else:
        report.state = "empty"
        if not report.listing_supported:
            report.detail = (
                "no metadata, no journal; backend cannot list, so foreign "
                "files cannot be ruled out"
            )
    return report


# ----------------------------------------------------------------------- gc


def _evictable_local_blobs(
    path: str,
    fsck: FsckReport,
    storage: StoragePlugin,
    event_loop: asyncio.AbstractEventLoop,
) -> Dict[str, int]:
    """The referenced local payload blobs ``gc --evict-local`` may
    reclaim from a tiered snapshot's local tier. Refuses (raises)
    unless the snapshot is ``remote-durable`` AND the durable marker
    has aged past the hot-local-cache retention window — the tiering
    gc safety rule."""
    import time as _time

    from .knobs import get_tier_local_retention_s
    from .tiering import read_upload_journal

    if fsck.durability is None:
        raise RuntimeError(
            f"{path!r} is not a tiered snapshot (no upload journal); "
            "--evict-local only applies to write-back tier local dirs"
        )
    if fsck.durability != "remote-durable":
        raise RuntimeError(
            f"{path!r} is {fsck.durability}: its blobs are NOT yet proven "
            "remote — refusing to evict the only durable copy (run "
            "`tpusnap drain` to convergence first)"
        )
    journal = read_upload_journal(storage, event_loop) or {}
    retention = get_tier_local_retention_s()
    durable_at = journal.get("durable_at")
    if retention > 0:
        age = (
            _time.time() - durable_at
            if isinstance(durable_at, (int, float))
            else 0.0
        )
        if age < retention:
            raise RuntimeError(
                f"{path!r} became remote-durable only {age:.0f}s ago; "
                f"TPUSNAP_TIER_LOCAL_RETENTION_S={retention:g} keeps the "
                "hot local cache that long — re-run later or lower the "
                "retention window"
            )
    from .snapshot import SNAPSHOT_METADATA_FNAME

    referenced = (
        _referenced_locations(fsck.metadata) if fsck.metadata else set()
    )
    files = fsck.files or {}
    evictable = {
        p: sz
        for p, sz in sorted(files.items())
        if p in referenced
        and p != SNAPSHOT_METADATA_FNAME
        and not p.startswith(_SIDECAR_PREFIX)
    }
    if fsck.cas_refs:
        # CAS interplay: payload locations held as refs occupy no local
        # bytes here — their blobs live in the SHARED store, and this
        # snapshot's own upload journal proves nothing about them. A
        # composed listing synthesizes them into ``files``, so naive
        # eviction would delete the REF (dropping the gc liveness root
        # other restores rely on). Exclude them — and refuse outright
        # unless the store's upload journal proves every ref'd blob
        # remote: post-eviction this directory restores from remotes,
        # and a ref'd blob with no store-remote evidence would have NO
        # durable copy backing this snapshot's claim.
        from .cas import blob_key as _cas_key
        from .cas import read_refs, resolve_store_url, store_remote_evidence

        cas_refs, cas_store = read_refs(storage, event_loop)
        store_url = cas_store or resolve_store_url()
        ref_locs = set(cas_refs) & referenced
        keys = {_cas_key(tuple(cas_refs[loc])) for loc in ref_locs}
        proven, _remote = (
            store_remote_evidence(store_url, keys)
            if store_url
            else (set(), None)
        )
        unproven = sorted(keys - proven)
        if unproven:
            raise RuntimeError(
                f"{path!r} holds {len(ref_locs)} CAS ref(s) into "
                f"{store_url!r} but the STORE's upload journal proves "
                f"only {len(proven)}/{len(keys)} of their blobs remote "
                "— a snapshot's own durable marker does not cover "
                "shared blobs; run `tpusnap drain --store` to "
                "convergence first"
            )
        evictable = {
            p: sz for p, sz in evictable.items() if p not in ref_locs
        }
    return evictable


@dataclass
class GCReport:
    path: str
    state: str  # the fsck state gc acted on
    dry_run: bool
    reclaimed: Dict[str, int] = field(default_factory=dict)
    errors: List[str] = field(default_factory=list)

    @property
    def bytes_reclaimed(self) -> int:
        return sum(self.reclaimed.values())

    def summary(self) -> str:
        verb = "would reclaim" if self.dry_run else "reclaimed"
        s = (
            f"{self.path}: {self.state}; {verb} {len(self.reclaimed)} "
            f"file(s), {self.bytes_reclaimed} bytes"
        )
        if self.errors:
            s += f" ({len(self.errors)} delete error(s))"
        return s


def gc_snapshot(
    path: str,
    storage_options: Optional[Dict[str, Any]] = None,
    dry_run: bool = True,
    reclaim_torn: bool = False,
    evict_local: bool = False,
) -> GCReport:
    """Reclaim files a reader can never reach.

    - **committed**: deletes the orphans fsck enumerates — files the
      manifest does not reference (stale journals, torn-take leftovers a
      salvage didn't reuse, ``*.tmp.*`` debris). Safe concurrently with
      readers: every deleted file is unreferenced by the committed
      manifest, and the manifest itself is immutable.
    - **torn**: REFUSED by default — the blobs are salvage-resume fuel
      (retaking to the path reuses them). ``reclaim_torn=True`` deletes
      everything including the journal, returning the path to empty.
    - **corrupt-metadata / foreign**: always refused; an operator must
      decide (restore the metadata from a replica, or delete manually).
    - ``evict_local=True`` additionally reclaims a tiered snapshot's
      LOCAL payload blobs — permitted only past ``remote-durable``
      (the one gc safety rule tiering adds: a blob may leave the local
      tier only once the upload journal's durable marker proves the
      remote holds the whole snapshot), and only once the marker is
      older than the ``TPUSNAP_TIER_LOCAL_RETENTION_S`` hot-cache
      window. Metadata and the upload journal are never evicted, so
      the directory keeps classifying as remote-durable and reads
      through the tier URL fall back to the remote.

    ``dry_run=True`` (the default) only reports what would be deleted.
    Exposed as ``python -m tpusnap gc <path> [--force] [--torn]
    [--evict-local]``."""
    from .storage_plugin import url_to_storage_plugin_in_event_loop

    if evict_local:
        # Eviction deletes from the LOCAL tier only: through a tier URL
        # the composed plugin would propagate deletes to the remote —
        # destroying the very durability that licenses the eviction.
        from .tiering import parse_tier_url

        spec = parse_tier_url(path)
        if spec is not None:
            path = spec.local_dir

    event_loop = asyncio.new_event_loop()
    try:
        storage = url_to_storage_plugin_in_event_loop(
            path, event_loop, storage_options
        )
        try:
            fsck = _fsck_impl(path, storage, event_loop)
            report = GCReport(path=path, state=fsck.state, dry_run=dry_run)
            if not fsck.listing_supported:
                raise RuntimeError(
                    f"gc requires a backend that can list files; "
                    f"{path!r} cannot"
                )
            if fsck.state == "committed":
                targets = dict(fsck.orphans)
                if evict_local:
                    targets.update(
                        _evictable_local_blobs(path, fsck, storage, event_loop)
                    )
            elif fsck.state == "torn":
                if not reclaim_torn:
                    raise RuntimeError(
                        f"{path!r} holds a TORN take "
                        f"({fsck.salvage_bytes_present} salvageable bytes): "
                        "retaking to this path resumes it; pass --torn to "
                        "discard the partial take instead"
                    )
                targets = dict(sorted((fsck.files or {}).items()))
            elif fsck.state == "empty":
                targets = {}
            else:
                raise RuntimeError(
                    f"gc refuses to touch {path!r}: fsck state is "
                    f"{fsck.state!r} ({fsck.detail}) — operator decision "
                    "required"
                )
            report.reclaimed = targets
            if dry_run:
                return report
            # Blobs first, journal marker last: if gc itself is killed
            # mid-way, the directory stays classifiable (torn stays torn
            # until its journal goes; committed orphan sets only shrink).
            ordered = sorted(
                targets, key=lambda p: (p == JOURNAL_FNAME, p)
            )
            if evict_local and targets:
                # Cold-first eviction: blobs no reader ever touched go
                # before the fleet's hot tiles, so an interrupted
                # eviction leaves the popular working set on the fast
                # tier. Popularity comes from the access ledgers (the
                # tier URL and its local dir digest identically); no
                # ledgers → plain name order, same as before.
                try:
                    from . import access

                    counts = access.location_read_counts(
                        access.load_ledger_records(path)
                    )
                except Exception:
                    counts = {}
                if counts:
                    ordered = sorted(
                        targets,
                        key=lambda p: (
                            p == JOURNAL_FNAME,
                            counts.get(p, 0),
                            p,
                        ),
                    )
            done: Dict[str, int] = {}
            for p in ordered:
                if (
                    p == JOURNAL_FNAME
                    and report.errors
                    and fsck.state == "torn"
                ):
                    # Some blob deletions failed: removing the marker now
                    # would strand the leftovers as "foreign" (which gc
                    # refuses) — keep the path torn so a re-run can
                    # finish the job.
                    report.errors.append(
                        f"{p}: kept (earlier deletions failed; re-run gc)"
                    )
                    continue
                try:
                    storage.sync_delete(p, event_loop)
                    done[p] = targets[p]
                except Exception as e:
                    report.errors.append(f"{p}: {e}")
            report.reclaimed = done
            if fsck.state == "committed" and fsck.cas_refs:
                # Prune ref-record entries the committed manifest does
                # not reference (a superseded retake's strands): they
                # pin shared-store blobs nothing will ever read. The
                # manifest is immutable, so this is as safe as the
                # orphan deletes above.
                from .cas import prune_refs

                pruned = prune_refs(
                    storage,
                    event_loop,
                    _referenced_locations(fsck.metadata),
                )
                if pruned:
                    logger.info(
                        "gc %s: pruned %d stale CAS ref(s)", path, pruned
                    )
            return report
        finally:
            storage.sync_close(event_loop)
    finally:
        event_loop.close()
