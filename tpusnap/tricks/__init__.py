"""Integration tricks: route other frameworks' checkpoint paths through
tpusnap (counterpart of /root/reference/torchsnapshot/tricks/deepspeed.py,
which monkey-patches DeepSpeedEngine._save_zero_checkpoint onto
Snapshot.async_take)."""
