"""Orbax drop-in: migrate orbax.checkpoint users to tpusnap in one line.

The reference's integration trick reroutes another framework's
checkpoint path through itself (tricks/deepspeed.py:19-27 patches
``DeepSpeedEngine._save_zero_checkpoint`` onto ``Snapshot.async_take``).
The JAX-ecosystem analog: ``PyTreeCheckpointer`` mirrors
``orbax.checkpoint.PyTreeCheckpointer``'s save/restore surface, so

    checkpointer = orbax.checkpoint.PyTreeCheckpointer()

becomes

    checkpointer = tpusnap.tricks.orbax.PyTreeCheckpointer()

and the app gets tpusnap's pipelined, budget-gated, replication-deduped
snapshots (plus ``async_save`` — orbax's AsyncCheckpointer equivalent)
with no other code change. No orbax import is required.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from ..pytree_state import PytreeState
from ..snapshot import PendingSnapshot, Snapshot


class PyTreeCheckpointer:
    """save/restore a pytree at a directory, orbax-style."""

    _KEY = "pytree"

    def save(
        self,
        directory: Any,
        item: Any,
        *,
        force: bool = False,
        incremental_from: Optional[Any] = None,
        **_: Any,
    ) -> None:
        """``incremental_from`` (tpusnap extension): dedup against a
        previous checkpoint directory — unchanged leaves reference the
        base instead of rewriting (see ``Snapshot.take``)."""
        path = os.fspath(directory)
        if force:
            self._remove_existing(path)
        Snapshot.take(
            path,
            {self._KEY: PytreeState(item)},
            incremental_from=(
                os.fspath(incremental_from)
                if incremental_from is not None
                else None
            ),
        )

    def async_save(
        self,
        directory: Any,
        item: Any,
        *,
        incremental_from: Optional[Any] = None,
    ) -> PendingSnapshot:
        """tpusnap extension mirroring orbax's AsyncCheckpointer: returns
        once device buffers are staged; storage I/O and the commit drain
        on a background thread (call ``.wait()`` or let the next save)."""
        return Snapshot.async_take(
            os.fspath(directory),
            {self._KEY: PytreeState(item)},
            incremental_from=(
                os.fspath(incremental_from)
                if incremental_from is not None
                else None
            ),
        )

    def restore(self, directory: Any, item: Optional[Any] = None, **_: Any) -> Any:
        """Restore the saved pytree. With ``item`` (a target pytree of
        arrays), leaves restore onto the targets' shardings/placements and
        the original tree structure is preserved. Without a target, the
        saved *structure* is rebuilt from the manifest (nested dicts keyed
        by pytree key path — orbax's restore-without-args analog) with
        host-resident leaves."""
        path = os.fspath(directory)
        if item is None:
            snapshot = Snapshot(path)
            state = PytreeState(self._placeholder_tree(snapshot))
            snapshot.restore({self._KEY: state})
            return state.tree
        state = PytreeState(item)
        Snapshot(path).restore({self._KEY: state})
        return state.tree

    def _placeholder_tree(self, snapshot: Snapshot) -> Any:
        """Nested dict of int placeholders mirroring the saved pytree's
        key paths (PytreeState's named state_dict layout). Placeholders
        are ints, not None — None is an *empty subtree* to jax.tree_util
        and would leave the target with zero leaves."""
        from ..flatten import _decode
        from ..manifest import is_container_entry

        manifest = snapshot.get_manifest()
        leaf_paths = set()
        for p, entry in manifest.items():
            parts = p.split("/", 1)
            if len(parts) != 2 or is_container_entry(entry):
                continue
            rest = parts[1]
            if rest.startswith(f"{self._KEY}/"):
                leaf_paths.add(rest[len(self._KEY) + 1 :])
        if leaf_paths == {"__value__"}:
            return 0  # bare-leaf pytree (PytreeState's sentinel)
        root: Any = {}
        for lp in sorted(leaf_paths):
            segs = [_decode(s) for s in lp.split("/")]
            node = root
            for seg in segs[:-1]:
                node = node.setdefault(seg, {})
            node[segs[-1]] = 0
        return root

    @staticmethod
    def _remove_existing(path: str) -> None:
        import shutil

        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
