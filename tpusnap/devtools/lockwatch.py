"""Runtime lock-order watchdog (``TPUSNAP_LOCKCHECK=1``).

The PR 6 tier-1 hang was a lock-order bug no test asserted: a join
reachable from a GC finalizer re-acquired ``threading._shutdown_locks_
lock``. Static rules (TPS006) pin the known shapes; this module catches
the UNKNOWN ones at runtime: when installed, every ``threading.Lock``/
``threading.RLock`` created afterwards is wrapped in a tracking proxy
that records, per thread, the stack of currently-held locks. Acquiring
lock B while holding lock A adds the directed edge A→B (keyed by the
locks' CREATION sites, so every instance of a class contributes to one
ordering class) with the acquisition sites as evidence. At any point —
and at process exit — the global graph can be checked for cycles: an
A→B plus B→A pair is two threads one unlucky schedule away from a
deadlock, reported with both locks' names and both acquisition sites
instead of a 2 a.m. hang.

Second check: :func:`note_blocking` — called by the storage layer at
every payload I/O — records any tracked lock the calling thread holds
ACROSS storage I/O. Those are latency/starvation hazards (a lock held
for a disk round-trip), reported informationally (``io_holds``), not
gated: some are deliberate coarse-grained op locks.

Semantics and bounds:

- Only locks created AFTER :func:`install` are tracked (the patch
  replaces the ``threading.Lock``/``RLock`` factories; stdlib internals
  using ``_thread.allocate_lock`` directly are untouched, which keeps
  the interpreter's own locking out of both the overhead and the
  graph).
- Overhead is one pure-Python hop + a thread-local list append per
  acquisition, and a dict update only when an edge is first seen.
  Disabled (the default), locks are never wrapped; the only residual
  cost is a no-op ``note_blocking`` call (one None check) at the
  instrumented storage boundary.
- ``RLock`` re-entry does not self-edge; two DIFFERENT locks from the
  same creation site acquired nested are counted separately
  (``nested_same_site``) and excluded from cycle verdicts — same-site
  nesting is usually a container iterating its children, not an
  ordering bug the AB/BA report could name meaningfully.

The tier-1 suite runs with ``TPUSNAP_LOCKCHECK=1`` (tests/conftest.py)
and fails the session if the suite's whole lock traffic produced any
cycle, so every test doubles as a deadlock detector over the
scheduler / staging-pool / telemetry / comm lock set. Tests that need a
deliberately cyclic graph use a private :class:`LockOrderWatch` over
:func:`raw_lock` primitives so the global graph stays clean.
"""

from __future__ import annotations

import _thread
import atexit
import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

__all__ = [
    "LockOrderWatch",
    "TrackedLock",
    "TrackedRLock",
    "active_watch",
    "install",
    "uninstall",
    "note_blocking",
    "raw_lock",
    "raw_rlock",
]

_allocate_lock = _thread.allocate_lock

# Diagnostic aid: TPUSNAP_LOCKCHECK_DEBUG=<substr> dumps a full Python
# stack to stderr whenever an order edge is recorded whose HELD node
# contains <substr> — how the "which call path created this edge?"
# question gets answered without guessing.
# tpusnap: waive=TPS001 diagnostic of the lint tooling itself, read once
_DEBUG_NODE = os.environ.get("TPUSNAP_LOCKCHECK_DEBUG")


def _short(path: str) -> str:
    parts = path.replace("\\", "/").split("/")
    return "/".join(parts[-2:])


def _site() -> str:
    """file:line of the nearest frame outside this module and the
    threading machinery — the code that created/acquired the lock."""
    try:
        f = sys._getframe(2)
    except ValueError:  # pragma: no cover - shallow stack
        return "?"
    for _ in range(12):
        if f is None:
            break
        fn = f.f_code.co_filename
        # Fast path: frames from this module share the exact co_filename
        # string; threading.py is matched by basename so a test file
        # named *lockwatch.py / *threading.py is not filtered away.
        if fn != __file__ and os.path.basename(fn) != "threading.py":
            return f"{_short(fn)}:{f.f_lineno}"
        f = f.f_back
    return "?"


class LockOrderWatch:
    """The lock-order graph plus per-thread held stacks. Thread-safe;
    its internal mutex is a raw ``_thread`` lock (invisible to itself)
    and strictly leaf-ordered (never held while acquiring anything)."""

    def __init__(self) -> None:
        self._mu = _allocate_lock()
        self._tls = threading.local()
        # (held_node, acquired_node) -> evidence
        self._edges: Dict[Tuple[str, str], Dict] = {}
        # node -> times a same-site pair was nested (excluded from cycles)
        self._nested_same_site: Dict[str, int] = {}
        # (node, tag) -> evidence for locks held across storage I/O
        self._io_holds: Dict[Tuple[str, str], Dict] = {}
        self._locks_created = 0
        self.enabled = True

    # --- bookkeeping called by the proxies ----------------------------

    def _held(self) -> List[Tuple[object, str, str]]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def note_created(self) -> None:
        with self._mu:
            self._locks_created += 1

    def note_acquired(
        self, lock: object, node: str, site: str, blocking: bool = True
    ) -> None:
        """``blocking=False`` acquisitions (trylocks) join the held
        stack — locks held BELOW them still matter — but add no
        incoming order edge: a thread that cannot wait cannot deadlock
        (lockdep's trylock rule).

        Reentrancy guard: the dict/list work below ALLOCATES, so GC can
        fire a finalizer mid-note that acquires tracked locks and
        re-enters this watch on the same thread — straight into a
        self-deadlock on the non-reentrant ``_mu``. A per-thread busy
        flag makes the reentrant note a no-op instead (the finalizer's
        acquire goes unrecorded; its release is identity-matched and
        safely finds nothing)."""
        if not self.enabled or getattr(self._tls, "busy", False):
            return
        self._tls.busy = True
        try:
            self._note_acquired(lock, node, site, blocking)
        finally:
            self._tls.busy = False

    def _note_acquired(self, lock, node, site, blocking) -> None:
        held = self._held()
        debug_edges = []
        if held and blocking:
            with self._mu:
                for _, hnode, hsite in held:
                    if hnode == node:
                        self._nested_same_site[node] = (
                            self._nested_same_site.get(node, 0) + 1
                        )
                        continue
                    key = (hnode, node)
                    e = self._edges.get(key)
                    if e is None:
                        self._edges[key] = {
                            "held_site": hsite,
                            "acquire_site": site,
                            "count": 1,
                        }
                    else:
                        e["count"] += 1
                    if _DEBUG_NODE and _DEBUG_NODE in hnode:
                        debug_edges.append((hnode, hsite))
        # Debug dump OUTSIDE the mutex: print_stack allocates (GC can
        # fire a finalizer that re-enters this watch on the same
        # thread), and _mu is a plain non-reentrant lock.
        for hnode, hsite in debug_edges:
            import traceback

            print(
                f"lockwatch DEBUG edge {hnode} -> {node} "
                f"(held at {hsite}, acquiring at {site}):",
                file=sys.stderr,
            )
            traceback.print_stack(file=sys.stderr)
        held.append((lock, node, site))

    def note_released(self, lock: object) -> None:
        if not self.enabled:
            return
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                del held[i]
                return

    def note_blocking(self, tag: str) -> None:
        """Record every tracked lock the calling thread holds across a
        blocking region (storage I/O). Same reentrancy guard as
        ``note_acquired``: the evidence dicts allocate, GC can fire a
        finalizer mid-note that re-enters the watch on this thread, and
        ``_mu`` is non-reentrant — so the busy flag must be HELD here,
        not just checked."""
        if not self.enabled or getattr(self._tls, "busy", False):
            return
        held = self._held()
        if not held:
            return
        self._tls.busy = True
        try:
            with self._mu:
                for _, node, site in held:
                    key = (node, tag)
                    e = self._io_holds.get(key)
                    if e is None:
                        self._io_holds[key] = {"held_site": site, "count": 1}
                    else:
                        e["count"] += 1
        finally:
            self._tls.busy = False

    # --- analysis -----------------------------------------------------

    def cycles(self) -> List[Dict]:
        """Cycles in the lock-order graph, each with the member locks
        and the edge evidence (where each lock was held / acquired).
        Any cycle is a potential deadlock: there exists a schedule in
        which each participant holds one lock and waits for the next."""
        with self._mu:
            edges = {k: dict(v) for k, v in self._edges.items()}
        adj: Dict[str, List[str]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])

        # Tarjan SCC, iterative.
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        onstack: Dict[str, bool] = {}
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        for root in adj:
            if root in index:
                continue
            work: List[Tuple[str, int]] = [(root, 0)]
            while work:
                node, pi = work[-1]
                if pi == 0:
                    index[node] = low[node] = counter[0]
                    counter[0] += 1
                    stack.append(node)
                    onstack[node] = True
                advanced = False
                succs = adj[node]
                for i in range(pi, len(succs)):
                    nxt = succs[i]
                    if nxt not in index:
                        work[-1] = (node, i + 1)
                        work.append((nxt, 0))
                        advanced = True
                        break
                    if onstack.get(nxt):
                        low[node] = min(low[node], index[nxt])
                if advanced:
                    continue
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        onstack[w] = False
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1:
                        sccs.append(comp)
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])

        out: List[Dict] = []
        for comp in sccs:
            members = set(comp)
            # Extract one concrete cycle inside the SCC by DFS.
            start = comp[0]
            path = [start]
            seen = {start}
            node = start
            while True:
                nxt = next(
                    (
                        b
                        for a, b in edges
                        if a == node and b in members
                    ),
                    None,
                )
                if nxt is None:  # pragma: no cover - SCC guarantees a succ
                    break
                if nxt == start:
                    break
                if nxt in seen:
                    # Trim to the loop portion.
                    path = path[path.index(nxt):]
                    start = nxt
                    break
                path.append(nxt)
                seen.add(nxt)
                node = nxt
            cyc_edges = []
            for i, a in enumerate(path):
                b = path[(i + 1) % len(path)]
                ev = edges.get((a, b), {})
                cyc_edges.append(
                    {
                        "held": a,
                        "acquired": b,
                        "held_at": ev.get("held_site", "?"),
                        "acquired_at": ev.get("acquire_site", "?"),
                        "count": ev.get("count", 0),
                    }
                )
            out.append({"locks": list(path), "edges": cyc_edges})
        return out

    def report(self) -> Dict:
        cycles = self.cycles()
        with self._mu:
            return {
                "locks_created": self._locks_created,
                "edges": len(self._edges),
                "cycles": cycles,
                "io_holds": [
                    {
                        "lock": node,
                        "tag": tag,
                        "held_at": ev["held_site"],
                        "count": ev["count"],
                    }
                    for (node, tag), ev in sorted(self._io_holds.items())
                ],
                "nested_same_site": dict(self._nested_same_site),
            }

    def render(self) -> str:
        rep = self.report()
        lines = [
            f"lockwatch: {rep['locks_created']} locks tracked, "
            f"{rep['edges']} order edges, {len(rep['cycles'])} cycle(s), "
            f"{len(rep['io_holds'])} lock-across-I/O site(s)"
        ]
        for cyc in rep["cycles"]:
            lines.append(f"  CYCLE: {' -> '.join(cyc['locks'] + [cyc['locks'][0]])}")
            for e in cyc["edges"]:
                lines.append(
                    f"    {e['held']} held at {e['held_at']} while "
                    f"acquiring {e['acquired']} at {e['acquired_at']} "
                    f"(x{e['count']})"
                )
        for h in rep["io_holds"]:
            lines.append(
                f"  io-hold: {h['lock']} (held at {h['held_at']}) across "
                f"{h['tag']} x{h['count']}"
            )
        return "\n".join(lines)

    # --- manual wrapping (tests, explicit instrumentation) ------------

    def wrap(self, lock, name: str) -> "TrackedLock":
        """Wrap an EXISTING raw lock under an explicit node name (the
        synthetic-cycle tests use this over :func:`raw_lock` primitives
        so the global graph is not polluted)."""
        if hasattr(lock, "_is_owned"):
            return TrackedRLock(lock, self, name)
        return TrackedLock(lock, self, name)


class TrackedLock:
    """Tracking proxy over a non-reentrant lock. API-compatible with
    ``threading.Lock`` including use as a ``threading.Condition``
    backing lock (the Condition falls back to acquire/release, both of
    which route through here)."""

    _tracked = True

    def __init__(self, lock, watch: LockOrderWatch, name: str) -> None:
        self._lock = lock
        self._watch = watch
        self.name = name
        watch.note_created()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._watch.note_acquired(
                self, self.name, _site(), blocking=blocking
            )
        return ok

    def release(self) -> None:
        self._lock.release()
        self._watch.note_released(self)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, name: str):
        # Passthrough for the long tail of private lock API consumers
        # (e.g. concurrent.futures registers _at_fork_reinit).
        if name == "_lock":
            raise AttributeError(name)
        return getattr(self._lock, name)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<TrackedLock {self.name} wrapping {self._lock!r}>"


class TrackedRLock:
    """Tracking proxy over a reentrant lock. Implements the private
    ``_release_save``/``_acquire_restore``/``_is_owned`` trio so
    ``threading.Condition`` keeps the held-stack consistent across
    ``wait()`` (which fully releases and re-acquires)."""

    _tracked = True

    def __init__(self, lock, watch: LockOrderWatch, name: str) -> None:
        self._lock = lock
        self._watch = watch
        self.name = name
        self._depth = threading.local()
        watch.note_created()

    def _d(self) -> int:
        return getattr(self._depth, "v", 0)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            d = self._d()
            self._depth.v = d + 1
            if d == 0:
                self._watch.note_acquired(
                    self, self.name, _site(), blocking=blocking
                )
        return ok

    def release(self) -> None:
        self._lock.release()
        d = self._d() - 1
        self._depth.v = d
        if d == 0:
            self._watch.note_released(self)

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition protocol (full release across wait()).
    def _is_owned(self) -> bool:
        return self._lock._is_owned()

    def _release_save(self):
        state = self._lock._release_save()
        saved = self._d()
        self._depth.v = 0
        self._watch.note_released(self)
        return (state, saved)

    def _acquire_restore(self, state) -> None:
        inner_state, saved = state
        self._lock._acquire_restore(inner_state)
        self._depth.v = saved
        self._watch.note_acquired(self, self.name, _site())

    def __getattr__(self, name: str):
        if name == "_lock":
            raise AttributeError(name)
        return getattr(self._lock, name)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<TrackedRLock {self.name} wrapping {self._lock!r}>"


# --------------------------------------------------------------- install

_watch: Optional[LockOrderWatch] = None
_orig_lock = None
_orig_rlock = None
_atexit_registered = False


def raw_lock():
    """An UNTRACKED non-reentrant lock, whether or not the watchdog is
    installed (tests building deliberate cycles in a private watch use
    these so the global graph stays clean)."""
    return _allocate_lock()


def raw_rlock():
    """An UNTRACKED reentrant lock (see :func:`raw_lock`)."""
    return (_orig_rlock or threading.RLock)()


def active_watch() -> Optional[LockOrderWatch]:
    return _watch


def note_blocking(tag: str) -> None:
    """Module-level hook for blocking regions (storage I/O): records
    held tracked locks into the active watch; no-op when the watchdog
    is not installed."""
    w = _watch
    if w is not None:
        w.note_blocking(tag)


def install(watch: Optional[LockOrderWatch] = None) -> LockOrderWatch:
    """Patch ``threading.Lock``/``threading.RLock`` so locks created
    from here on are tracked in the (given or fresh) global watch.
    Idempotent: a second install returns the active watch.

    On Pythons where ``threading.Lock`` is a real TYPE rather than a
    factory function (3.13+), replacing it with a factory would break
    every ``isinstance(x, threading.Lock)`` in stdlib/third-party code
    — so the patch degrades gracefully: the watch is still returned
    (manual ``wrap()`` instrumentation works), but the factories are
    left alone and a single WARNING explains why."""
    global _watch, _orig_lock, _orig_rlock, _atexit_registered
    if _watch is not None:
        return _watch
    w = watch or LockOrderWatch()
    if isinstance(threading.Lock, type) or isinstance(threading.RLock, type):
        import logging

        logging.getLogger(__name__).warning(
            "TPUSNAP_LOCKCHECK: threading.Lock/RLock are types on this "
            "Python — global lock tracking disabled (isinstance checks "
            "would break); LockOrderWatch.wrap() still works"
        )
        _watch = w
        return w
    _orig_lock = threading.Lock
    _orig_rlock = threading.RLock

    def _tracked_lock():
        return TrackedLock(_orig_lock(), w, _site())

    def _tracked_rlock():
        return TrackedRLock(_orig_rlock(), w, _site())

    threading.Lock = _tracked_lock
    threading.RLock = _tracked_rlock
    _watch = w
    if not _atexit_registered:
        _atexit_registered = True
        atexit.register(_report_at_exit)
    return w


def uninstall() -> None:
    """Restore the real lock factories and drop the global watch.
    Already-created proxies keep functioning (their watch reference
    stays valid; it just stops being the active one)."""
    global _watch, _orig_lock, _orig_rlock
    if _watch is None:
        return
    if _orig_lock is not None:  # None: degraded install never patched
        threading.Lock = _orig_lock
        threading.RLock = _orig_rlock
    _orig_lock = None
    _orig_rlock = None
    _watch = None


def _report_at_exit() -> None:
    """Opt-in exit report: WARN loudly when the process's lock traffic
    contained an ordering cycle. stderr (not logging): logging may
    already be shut down during interpreter exit."""
    w = _watch
    if w is None:
        return
    try:
        cycles = w.cycles()
        if cycles:
            print(
                "tpusnap lockwatch: POTENTIAL DEADLOCK — lock-order "
                "cycle(s) detected:\n" + w.render(),
                file=sys.stderr,
            )
    except Exception:  # pragma: no cover - exit path must never raise
        pass
