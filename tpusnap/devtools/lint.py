"""AST invariant checker for the tpusnap source tree.

The project's correctness story rests on cross-cutting invariants no
single test enumerates — knob reads only through ``knobs.py``,
monotonic-only clocks in the observability modules, one canonical
definition of the ``.tpusnap`` sidecar namespace, no silent exception
swallows in crash-safety modules, no blocking calls in the scheduler's
async bodies, no thread joins reachable from GC finalizers. Each is a
:class:`Rule` with a stable ``TPSnnn`` id; the engine walks every
``*.py`` file of the package with :mod:`ast` (the tree is PARSED, never
imported — it can lint a seeded temp copy), applies every selected
rule, and subtracts per-line waivers.

Waivers::

    x = os.environ["TPUSNAP_TEST_RANK"]  # tpusnap: waive=TPS001 why

A waive comment suppresses the named rule(s) (comma-separated) on its
own line; a waive inside a pure-comment line applies to the next code
line below it (for block comments above the waived statement). The
reason text is free-form but expected — a waiver is documentation of a
deliberate exception, not an off switch.

CLI: ``python -m tpusnap lint [--json] [--check] [--root DIR]
[--select RULES]`` — ``--check`` exits 2 on any unwaived finding, 0 on
a clean tree; the tier-1 suite and ``scripts/ci_gate.sh`` run it over
the whole package.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Set

_WAIVE_RE = re.compile(r"#\s*tpusnap:\s*waive=([A-Z0-9_,]+)")


@dataclasses.dataclass
class Finding:
    """One rule violation, anchored to a file:line."""

    rule: str
    path: str  # display path, relative to the package root's parent
    line: int
    col: int
    message: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def location(self) -> str:
        return f"{self.path}:{self.line}"


@dataclasses.dataclass
class SourceFile:
    """A parsed package source file plus its waiver map."""

    relpath: str  # relative to the package root, e.g. "telemetry.py"
    display_path: str  # e.g. "tpusnap/telemetry.py"
    source: str
    tree: Optional[ast.AST]
    parse_error: Optional[str]
    waivers: Dict[int, Set[str]]  # line -> waived rule ids


@dataclasses.dataclass
class LintContext:
    """Everything a rule may inspect: the parsed package files plus the
    repo root (for project rules that cross-check docs)."""

    package_root: str
    repo_root: str
    files: List[SourceFile]

    def file(self, relpath: str) -> Optional[SourceFile]:
        for f in self.files:
            if f.relpath == relpath:
                return f
        return None


class Rule:
    """One invariant. Subclasses set ``id``/``title`` and implement
    ``check_file`` (per-file AST walk) and/or ``check_project``
    (repo-level cross-checks, e.g. knob/doc drift)."""

    id: str = "TPS000"
    title: str = ""

    def check_file(
        self, sf: SourceFile, ctx: LintContext
    ) -> Iterable[Finding]:
        return ()

    def check_project(self, ctx: LintContext) -> Iterable[Finding]:
        return ()


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    waived: List[Finding]
    files_scanned: int
    rules_run: List[str]

    @property
    def clean(self) -> bool:
        return not self.findings

    def as_dict(self) -> dict:
        return {
            "clean": self.clean,
            "files_scanned": self.files_scanned,
            "rules_run": self.rules_run,
            "findings": [f.as_dict() for f in self.findings],
            "waived": [f.as_dict() for f in self.waived],
        }


def parse_waivers(source: str) -> Dict[int, Set[str]]:
    """Line → waived rule ids. A waive comment on a code line covers
    that line; a waive in a comment block covers the code line DIRECTLY
    below the block (so the explanation sits above the statement it
    waives). A blank line clears a pending comment waiver — a stale
    waive comment stranded by a refactor must not silently suppress a
    finding on unrelated code further down."""
    waivers: Dict[int, Set[str]] = {}
    pending: Set[str] = set()
    for lineno, line in enumerate(source.splitlines(), 1):
        stripped = line.strip()
        m = _WAIVE_RE.search(line)
        rules = (
            {r for r in m.group(1).split(",") if r} if m is not None else set()
        )
        if stripped.startswith("#"):
            pending |= rules
            continue
        if not stripped:
            pending = set()
            continue
        if rules or pending:
            waivers.setdefault(lineno, set()).update(rules | pending)
        pending = set()
    return waivers


def _collect_files(package_root: str) -> List[SourceFile]:
    package_root = os.path.abspath(package_root)
    pkg_name = os.path.basename(package_root.rstrip(os.sep))
    out: List[SourceFile] = []
    for dirpath, dirnames, filenames in os.walk(package_root):
        dirnames[:] = sorted(
            d for d in dirnames if d != "__pycache__" and not d.startswith(".")
        )
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            abspath = os.path.join(dirpath, fn)
            relpath = os.path.relpath(abspath, package_root).replace(
                os.sep, "/"
            )
            with open(abspath, "r", encoding="utf-8") as f:
                source = f.read()
            tree: Optional[ast.AST] = None
            err: Optional[str] = None
            try:
                tree = ast.parse(source, filename=abspath)
            except SyntaxError as e:
                err = f"{e.msg} (line {e.lineno})"
            out.append(
                SourceFile(
                    relpath=relpath,
                    display_path=f"{pkg_name}/{relpath}",
                    source=source,
                    tree=tree,
                    parse_error=err,
                    waivers=parse_waivers(source),
                )
            )
    return out


def all_rules() -> List[Rule]:
    from .rules import ALL_RULES

    return [cls() for cls in ALL_RULES]


def default_package_root() -> str:
    """The installed tpusnap package directory (what the zero-findings
    gate lints)."""
    import tpusnap

    return os.path.dirname(os.path.abspath(tpusnap.__file__))


def run_lint(
    package_root: Optional[str] = None,
    select: Optional[Sequence[str]] = None,
) -> LintResult:
    """Lint every ``*.py`` under ``package_root`` (default: the
    installed tpusnap package) with the selected rules (default: all).
    Unparseable files surface as ``PARSE`` findings — a tree the linter
    cannot read must not pass as clean."""
    root = os.path.abspath(package_root or default_package_root())
    if not os.path.isdir(root):
        raise RuntimeError(f"lint root is not a directory: {root!r}")
    ctx = LintContext(
        package_root=root,
        repo_root=os.path.dirname(root),
        files=_collect_files(root),
    )
    rules = all_rules()
    if select is not None:
        wanted: Set[str] = set()
        for item in select:
            for tok in item.split(","):
                tok = tok.strip().upper()
                if tok:
                    wanted.add(tok)
        unknown = wanted - {r.id for r in rules}
        if unknown:
            raise RuntimeError(
                f"unknown lint rule(s): {sorted(unknown)} "
                f"(known: {sorted(r.id for r in rules)})"
            )
        rules = [r for r in rules if r.id in wanted]

    raw: List[Finding] = []
    for sf in ctx.files:
        if sf.parse_error is not None:
            raw.append(
                Finding(
                    rule="PARSE",
                    path=sf.display_path,
                    line=1,
                    col=0,
                    message=f"file does not parse: {sf.parse_error}",
                )
            )
            continue
        for rule in rules:
            raw.extend(rule.check_file(sf, ctx))
    for rule in rules:
        raw.extend(rule.check_project(ctx))

    findings: List[Finding] = []
    waived: List[Finding] = []
    waiver_index = {sf.display_path: sf.waivers for sf in ctx.files}
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        if f.rule in waiver_index.get(f.path, {}).get(f.line, ()):
            waived.append(f)
        else:
            findings.append(f)
    return LintResult(
        findings=findings,
        waived=waived,
        files_scanned=len(ctx.files),
        rules_run=[r.id for r in rules],
    )


# ----------------------------------------------------------------- rendering


def render_table(result: LintResult) -> str:
    lines: List[str] = []
    if result.findings:
        width = max(len(f.location()) for f in result.findings)
        for f in result.findings:
            lines.append(
                f"{f.rule:<7} {f.location():<{width}}  {f.message}"
            )
    lines.append(
        f"lint: {len(result.findings)} finding(s), "
        f"{len(result.waived)} waived, {result.files_scanned} files, "
        f"rules {','.join(result.rules_run)}"
    )
    return "\n".join(lines)


def main(args) -> int:
    """``python -m tpusnap lint`` entry point (argparse namespace with
    ``root``/``select``/``json``/``check``)."""
    try:
        result = run_lint(
            package_root=args.root,
            select=[args.select] if args.select else None,
        )
    except RuntimeError as e:
        # stderr, not stdout: --json consumers parse stdout.
        print(f"error: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(result.as_dict(), indent=2))
    else:
        print(render_table(result))
    if args.check:
        return 2 if result.findings else 0
    return 0
