"""Project-native developer tooling: the AST invariant checker
(:mod:`tpusnap.devtools.lint`, ``python -m tpusnap lint``) and the
runtime lock-order watchdog (:mod:`tpusnap.devtools.lockwatch`,
``TPUSNAP_LOCKCHECK=1``).

Kept import-light on purpose: this package is imported from
``tpusnap/__init__`` (lockcheck auto-install) before the heavy
JAX-facing modules, and the lint engine must be runnable against a
source TREE without importing it.
"""
