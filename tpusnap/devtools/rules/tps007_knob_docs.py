"""TPS007 — knob/doc drift. Every ``TPUSNAP_*`` env var defined in
``knobs.py`` must be documented in ``docs/api.md``, and every knob row
in api.md's knob table must still be referenced somewhere in the
package source — an undocumented knob is invisible to operators, and a
documented-but-dead knob is a support trap. This is the lint-engine
port of the original grep test in ``tests/test_knob_docs.py`` (which is
now a thin wrapper over this rule)."""

from __future__ import annotations

import os
import re
from typing import Iterable, List

from ..lint import Finding, LintContext, Rule

_DEFINED_RE = re.compile(r'"(TPUSNAP_[A-Z0-9_]+)"')
_DOC_ROW_RE = re.compile(r"^\|\s*`(TPUSNAP_[A-Z0-9_]+)`", re.M)


class KnobDocDriftRule(Rule):
    id = "TPS007"
    title = "knob/doc drift between knobs.py and docs/api.md"

    def check_project(self, ctx: LintContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        knobs = ctx.file("knobs.py")
        if knobs is None:
            return [
                Finding(
                    rule=self.id,
                    path="knobs.py",
                    line=1,
                    col=0,
                    message="knobs.py not found — knob/doc drift unverifiable",
                )
            ]
        docs_dir = os.path.join(ctx.repo_root, "docs")
        if not os.path.isdir(docs_dir):
            # No docs/ directory next to the package at all: this is an
            # installed copy (site-packages), not a repo checkout — the
            # drift check has nothing to check against and must not
            # fail `lint --check` on a clean install. A CHECKOUT that
            # loses docs/api.md while keeping docs/ still fails below.
            return []
        api_path = os.path.join(docs_dir, "api.md")
        try:
            with open(api_path, "r", encoding="utf-8") as f:
                docs = f.read()
        except OSError:
            return [
                Finding(
                    rule=self.id,
                    path="docs/api.md",
                    line=1,
                    col=0,
                    message=(
                        "docs/ exists but docs/api.md is unreadable — "
                        "knob/doc drift unverifiable"
                    ),
                )
            ]

        # Vacuous-pass guards (the deleted grep tests carried these):
        # zero knobs found or zero table rows means the PATTERNS broke,
        # not that drift is absent — a silently disabled gate is itself
        # a finding.
        if not _DEFINED_RE.search(knobs.source):
            findings.append(
                Finding(
                    rule=self.id,
                    path=knobs.display_path,
                    line=1,
                    col=0,
                    message=(
                        "no TPUSNAP_* knob definitions found in knobs.py "
                        "— did the declaration style change? The drift "
                        "gate would pass vacuously"
                    ),
                )
            )
        if not _DOC_ROW_RE.search(docs):
            findings.append(
                Finding(
                    rule=self.id,
                    path="docs/api.md",
                    line=1,
                    col=0,
                    message=(
                        "no knob table rows found in docs/api.md — did "
                        "the table format change? The drift gate would "
                        "pass vacuously"
                    ),
                )
            )

        # 1. defined but undocumented (anchor: the knob's knobs.py line)
        seen = set()
        for m in _DEFINED_RE.finditer(knobs.source):
            name = m.group(1)
            if name in seen:
                continue
            seen.add(name)
            if name not in docs:
                findings.append(
                    Finding(
                        rule=self.id,
                        path=knobs.display_path,
                        line=knobs.source.count("\n", 0, m.start()) + 1,
                        col=0,
                        message=(
                            f"knob {name} is defined in knobs.py but "
                            "undocumented in docs/api.md"
                        ),
                    )
                )

        # 2. documented but referenced nowhere in the package source
        all_source = "".join(sf.source for sf in ctx.files)
        for m in _DOC_ROW_RE.finditer(docs):
            name = m.group(1)
            if name not in all_source:
                findings.append(
                    Finding(
                        rule=self.id,
                        path="docs/api.md",
                        line=docs.count("\n", 0, m.start()) + 1,
                        col=0,
                        message=(
                            f"knob {name} has an api.md table row but is "
                            "referenced nowhere in the package source"
                        ),
                    )
                )
        return findings
