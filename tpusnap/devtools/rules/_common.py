"""Shared AST helpers for the lint rules: import-alias resolution (so
``import os as o`` / ``from time import time as now`` cannot dodge a
rule that greps would miss) and docstring detection."""

from __future__ import annotations

import ast
from typing import Dict, Optional, Set, Tuple


def import_aliases(tree: ast.AST) -> Tuple[Dict[str, str], Dict[str, Tuple[str, str]]]:
    """(module_aliases, from_imports): ``import os as o`` →
    ``{"o": "os"}``; ``from os import environ as e`` →
    ``{"e": ("os", "environ")}``. Walks the whole tree so function-local
    imports count too."""
    modules: Dict[str, str] = {}
    members: Dict[str, Tuple[str, str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                modules[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                members[a.asname or a.name] = (node.module, a.name)
    return modules, members


def module_alias_names(tree: ast.AST, module: str) -> Set[str]:
    """Local names bound to ``import <module>`` (including aliases)."""
    modules, _ = import_aliases(tree)
    return {name for name, mod in modules.items() if mod == module}


def member_alias_names(tree: ast.AST, module: str, attr: str) -> Set[str]:
    """Local names bound to ``from <module> import <attr>`` aliases."""
    _, members = import_aliases(tree)
    return {
        name for name, (mod, a) in members.items() if mod == module and a == attr
    }


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def statement_string_ids(tree: ast.AST) -> Set[int]:
    """``id()`` of every string Constant that is a bare statement
    expression — docstrings and no-op strings, which carry no behavior
    and are exempt from string-literal rules."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Constant):
            if isinstance(node.value.value, str):
                out.add(id(node.value))
    return out


def call_name(node: ast.Call) -> Optional[str]:
    """The trailing name of a call — ``foo()`` → "foo",
    ``a.b.foo()`` → "foo"."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None
