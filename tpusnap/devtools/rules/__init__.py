"""The shipped lint rules, one module per rule (stable ``TPSnnn`` ids).

Adding a rule: create ``tpsNNN_<slug>.py`` with a :class:`Rule`
subclass, import it here, append to ``ALL_RULES``, and add a row to the
rule table in ``docs/design.md`` (the per-rule test matrix in
``tests/test_lint.py`` expects positive/negative/waived coverage)."""

from .tps001_knob_env import KnobEnvAccessRule
from .tps002_monotonic import MonotonicClockRule
from .tps003_sidecar_literals import SidecarLiteralRule
from .tps004_silent_swallow import SilentSwallowRule
from .tps005_async_blocking import AsyncBlockingCallRule
from .tps006_finalizer_join import FinalizerJoinRule
from .tps007_knob_docs import KnobDocDriftRule

ALL_RULES = [
    KnobEnvAccessRule,
    MonotonicClockRule,
    SidecarLiteralRule,
    SilentSwallowRule,
    AsyncBlockingCallRule,
    FinalizerJoinRule,
    KnobDocDriftRule,
]
