"""TPS001 — every ``TPUSNAP_*`` env var is a knob, and knobs are read
through :mod:`tpusnap.knobs` only. A raw ``os.environ``/``os.getenv``
access elsewhere bypasses the knob registry: no docstring, no default
in one place, invisible to the knob/doc drift gate (TPS007), and no
context-manager override for tests."""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..lint import Finding, LintContext, Rule, SourceFile
from ._common import const_str, member_alias_names, module_alias_names

_EXEMPT_FILES = {"knobs.py"}
_ENV_METHODS = {"get", "setdefault", "pop"}


class KnobEnvAccessRule(Rule):
    id = "TPS001"
    title = "TPUSNAP_* env access outside knobs.py"

    def check_file(
        self, sf: SourceFile, ctx: LintContext
    ) -> Iterable[Finding]:
        if sf.relpath in _EXEMPT_FILES or sf.tree is None:
            return ()
        tree = sf.tree
        os_names = module_alias_names(tree, "os")
        environ_names = member_alias_names(tree, "os", "environ")
        getenv_names = member_alias_names(tree, "os", "getenv")

        def is_environ(node: ast.AST) -> bool:
            if isinstance(node, ast.Name):
                return node.id in environ_names
            return (
                isinstance(node, ast.Attribute)
                and node.attr == "environ"
                and isinstance(node.value, ast.Name)
                and node.value.id in os_names
            )

        def is_getenv(node: ast.AST) -> bool:
            if isinstance(node, ast.Name):
                return node.id in getenv_names
            return (
                isinstance(node, ast.Attribute)
                and node.attr == "getenv"
                and isinstance(node.value, ast.Name)
                and node.value.id in os_names
            )

        def tpusnap_key(node: ast.AST) -> bool:
            s = const_str(node)
            return s is not None and s.startswith("TPUSNAP_")

        findings: List[Finding] = []

        def flag(node: ast.AST, key: str) -> None:
            findings.append(
                Finding(
                    rule=self.id,
                    path=sf.display_path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"raw environment access of {key!r} — route it "
                        "through a tpusnap.knobs getter (registered, "
                        "documented, override-able)"
                    ),
                )
            )

        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr in _ENV_METHODS
                    and is_environ(f.value)
                    and node.args
                    and tpusnap_key(node.args[0])
                ):
                    flag(node, const_str(node.args[0]))
                elif is_getenv(f) and node.args and tpusnap_key(node.args[0]):
                    flag(node, const_str(node.args[0]))
            elif isinstance(node, ast.Subscript):
                if is_environ(node.value) and tpusnap_key(node.slice):
                    flag(node, const_str(node.slice))
            elif isinstance(node, ast.Compare):
                if (
                    tpusnap_key(node.left)
                    and any(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops)
                    and any(is_environ(c) for c in node.comparators)
                ):
                    flag(node, const_str(node.left))
        return findings
