"""TPS006 — no thread joins reachable from a GC-finalizer path without
the :func:`tpusnap.io_types.finalizer_close_scope` guard.

The PR 6 deadlock class: GC can run ``__del__`` from inside a STARTING
thread's ``Thread._set_tstate_lock`` (which holds
``threading._shutdown_locks_lock``); a join on that path re-acquires
the same lock and the process hangs forever. The fix is a policy, and
policies drift — so this rule pins it:

- inside ``__del__``, any call that could transitively join (``join``,
  ``shutdown``, ``stop``, anything close-shaped) must sit under
  ``with finalizer_close_scope():``;
- inside plugin ``close()``/``sync_close()`` methods — the canonical
  finalizer-reachable path — executor ``.shutdown(...)`` and thread
  ``.join(...)`` must go through
  :func:`tpusnap.io_types.shutdown_plugin_executor` (or gate on
  ``close_may_join()``), the ONE place the join-on-close policy lives.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..lint import Finding, LintContext, Rule, SourceFile
from ._common import call_name

# Calls that are safe anywhere: the guard machinery itself.
_GUARD_CALLS = {
    "finalizer_close_scope",
    "close_may_join",
    "shutdown_plugin_executor",
}

_CLOSE_METHODS = {"close", "sync_close", "aclose"}


def _is_scope_with(node: ast.With) -> bool:
    return any(
        isinstance(item.context_expr, ast.Call)
        and call_name(item.context_expr) == "finalizer_close_scope"
        for item in node.items
    )


def _dangerous_in_del(name: str) -> bool:
    # Exact close-shaped names, not a substring net: `is_closed()` /
    # `on_closed()` / `disclose()` in a __del__ are innocuous and a
    # false positive here teaches maintainers to waive reflexively.
    return name in ("join", "shutdown", "stop") or (
        name not in _GUARD_CALLS
        and (name == "close" or name.endswith("_close"))
    )


def _thread_join_like(node: ast.Call) -> bool:
    """Filter the string/path ``join``s out: only attribute joins on
    something that could plausibly be a thread/executor count."""
    f = node.func
    if not isinstance(f, ast.Attribute) or f.attr != "join":
        return True  # not a join at all — caller decides on other names
    v = f.value
    if isinstance(v, ast.Constant) and isinstance(v.value, str):
        return False  # ", ".join(...)
    if isinstance(v, ast.Attribute) and v.attr == "path":
        return False  # os.path.join(...)
    if isinstance(v, ast.Name) and v.id in {"os", "posixpath", "ntpath"}:
        return False
    return True


class FinalizerJoinRule(Rule):
    id = "TPS006"
    title = "thread join reachable from a finalizer path"

    def check_file(
        self, sf: SourceFile, ctx: LintContext
    ) -> Iterable[Finding]:
        if sf.tree is None:
            return ()
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name == "__del__":
                    self._scan_del(node, sf, findings)
                elif node.name in _CLOSE_METHODS:
                    self._scan_close(node, sf, findings)
        return findings

    # --- __del__ ------------------------------------------------------

    def _scan_del(self, fn, sf: SourceFile, findings: List[Finding]) -> None:
        def visit(node: ast.AST, protected: bool) -> None:
            if isinstance(node, ast.With):
                inner = protected or _is_scope_with(node)
                for item in node.items:
                    visit(item, protected)
                for stmt in node.body:
                    visit(stmt, inner)
                return
            if isinstance(node, ast.Call) and not protected:
                name = call_name(node) or ""
                if _dangerous_in_del(name) and _thread_join_like(node):
                    findings.append(
                        Finding(
                            rule=self.id,
                            path=sf.display_path,
                            line=node.lineno,
                            col=node.col_offset,
                            message=(
                                f"`{name}()` in __del__ outside `with "
                                "finalizer_close_scope():` — a join "
                                "reachable from GC self-deadlocks on "
                                "threading._shutdown_locks_lock (the "
                                "PR 6 hang); wrap the close in the "
                                "scope"
                            ),
                        )
                    )
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                visit(child, protected)

        for stmt in fn.body:
            visit(stmt, False)

    # --- close() ------------------------------------------------------

    def _scan_close(self, fn, sf: SourceFile, findings: List[Finding]) -> None:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            if f.attr == "shutdown":
                wait = next(
                    (kw.value for kw in node.keywords if kw.arg == "wait"),
                    None,
                )
                # shutdown(wait=False) never joins; shutdown(
                # wait=close_may_join()) is the policy helper inlined.
                if isinstance(wait, ast.Constant) and wait.value is False:
                    continue
                if (
                    isinstance(wait, ast.Call)
                    and call_name(wait) == "close_may_join"
                ):
                    continue
                findings.append(
                    Finding(
                        rule=self.id,
                        path=sf.display_path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            "executor .shutdown() with a join inside "
                            f"{fn.name}() — close() is finalizer-"
                            "reachable; route through io_types."
                            "shutdown_plugin_executor (the one join-on-"
                            "close policy)"
                        ),
                    )
                )
            elif f.attr == "join" and _thread_join_like(node):
                findings.append(
                    Finding(
                        rule=self.id,
                        path=sf.display_path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"thread .join() inside {fn.name}() — "
                            "close() is finalizer-reachable; gate on "
                            "io_types.close_may_join() or move the "
                            "join off the close path"
                        ),
                    )
                )
