"""TPS005 — no blocking calls inside the scheduler's ``async def``
bodies. The write/read schedulers run every request on one event loop;
a ``time.sleep`` or synchronous file op inside a coroutine stalls ALL
in-flight I/O for its duration (the budget gate, the probe runner and
the abort watcher all share that loop). Blocking work belongs on the
executor (``run_in_executor`` / the staging executor). Calls inside
nested synchronous ``def``s are fine — those run on worker threads."""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..lint import Finding, LintContext, Rule, SourceFile
from ._common import member_alias_names, module_alias_names

SCOPED_MODULES = {"scheduler.py"}

# module → attribute calls that block the calling thread
_BLOCKING_ATTRS = {
    "time": {"sleep"},
    "os": {"open", "fsync", "fdatasync"},
    "io": {"open"},
}


class AsyncBlockingCallRule(Rule):
    id = "TPS005"
    title = "blocking call in an async def body"

    def check_file(
        self, sf: SourceFile, ctx: LintContext
    ) -> Iterable[Finding]:
        if sf.relpath not in SCOPED_MODULES or sf.tree is None:
            return ()
        tree = sf.tree
        mod_aliases = {
            mod: module_alias_names(tree, mod) for mod in _BLOCKING_ATTRS
        }
        sleep_funcs = member_alias_names(tree, "time", "sleep")

        def blocking_call(node: ast.Call) -> str:
            f = node.func
            if isinstance(f, ast.Name):
                if f.id == "open":
                    return "open()"
                if f.id in sleep_funcs:
                    return "time.sleep()"
                return ""
            if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
                for mod, attrs in _BLOCKING_ATTRS.items():
                    if f.attr in attrs and f.value.id in mod_aliases[mod]:
                        return f"{mod}.{f.attr}()"
            return ""

        findings: List[Finding] = []

        def visit(node: ast.AST, fn_name: str) -> None:
            # Nested function definitions get their own scan: sync defs
            # run on worker threads (exempt), nested async defs are
            # found by the outer walk.
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.Call):
                    what = blocking_call(child)
                    if what:
                        findings.append(
                            Finding(
                                rule=self.id,
                                path=sf.display_path,
                                line=child.lineno,
                                col=child.col_offset,
                                message=(
                                    f"blocking {what} inside `async def "
                                    f"{fn_name}` stalls every in-flight "
                                    "request on the scheduler loop — use "
                                    "asyncio.sleep / run_in_executor"
                                ),
                            )
                        )
                visit(child, fn_name)

        for node in ast.walk(tree):
            if isinstance(node, ast.AsyncFunctionDef):
                for stmt in node.body:
                    visit(stmt, node.name)
        return findings
