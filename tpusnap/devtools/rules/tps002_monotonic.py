"""TPS002 — monotonic-only clocks in the observability modules.

Duration/throttle math in telemetry, progress and history must run on
``time.monotonic()``: wall clocks step (NTP, suspend) and a stepped
duration is a 2 a.m. incident, not a test failure. Wall-clock
TIMESTAMPS go through each module's injectable ``_wall`` seam — a bare
``time.time`` REFERENCE stays legal, only direct CALLS are flagged.
This is the AST port of the original grep lint in
``tests/test_knob_docs.py``; unlike the grep it also catches
``from time import time`` and ``import time as t`` aliases."""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..lint import Finding, LintContext, Rule, SourceFile
from ._common import member_alias_names, module_alias_names

# The monotonic-only modules (PR 2's invariant; slo.py born under it —
# RPO/interval math on a stepped wall clock would misreport exposure).
# Paths relative to the package root.
SCOPED_MODULES = {
    "telemetry.py",
    "progress.py",
    "history.py",
    "flight.py",
    "slo.py",
    "liveness.py",
    "fleet.py",
}


class MonotonicClockRule(Rule):
    id = "TPS002"
    title = "wall-clock call in a monotonic-only module"

    def check_file(
        self, sf: SourceFile, ctx: LintContext
    ) -> Iterable[Finding]:
        if sf.relpath not in SCOPED_MODULES or sf.tree is None:
            return ()
        tree = sf.tree
        time_mods = module_alias_names(tree, "time")
        time_funcs = member_alias_names(tree, "time", "time")
        findings: List[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            direct = (
                isinstance(f, ast.Attribute)
                and f.attr == "time"
                and isinstance(f.value, ast.Name)
                and f.value.id in time_mods
            )
            aliased = isinstance(f, ast.Name) and f.id in time_funcs
            if direct or aliased:
                findings.append(
                    Finding(
                        rule=self.id,
                        path=sf.display_path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            "direct wall-clock call in a monotonic-only "
                            "module — durations use time.monotonic(); "
                            "wall timestamps go through the module's "
                            "injectable _wall seam (a bare time.time "
                            "reference, never a call)"
                        ),
                    )
                )
        return findings
