"""TPS003 — one canonical definition of the ``.tpusnap`` sidecar
namespace. The journal writer, fsck's classifier, the heartbeat pump,
the probe runner and the histogram sampler all make decisions keyed on
these paths; a private string copy in any of them is a silent-drift
hazard (rename the namespace in one place and fsck starts calling
committed snapshots foreign). All code references go through the
constants exported by :mod:`tpusnap.io_types`; docstrings and comments
are exempt (they describe the layout, they don't implement it)."""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..lint import Finding, LintContext, Rule, SourceFile
from ._common import statement_string_ids

# Built by concatenation so this rule module does not flag itself.
NEEDLE = ".tpusnap" + "/"

_EXEMPT_FILES = {"io_types.py"}


class SidecarLiteralRule(Rule):
    id = "TPS003"
    title = "sidecar namespace literal outside io_types"

    def check_file(
        self, sf: SourceFile, ctx: LintContext
    ) -> Iterable[Finding]:
        if sf.relpath in _EXEMPT_FILES or sf.tree is None:
            return ()
        doc_ids = statement_string_ids(sf.tree)
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and NEEDLE in node.value
                and id(node) not in doc_ids
            ):
                findings.append(
                    Finding(
                        rule=self.id,
                        path=sf.display_path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"hardcoded sidecar path {node.value!r} — use "
                            "the canonical constants exported by "
                            "tpusnap.io_types (SIDECAR_PREFIX, "
                            "JOURNAL_PATH, PROGRESS_DIR, TELEMETRY_DIR, "
                            "PROBE_DIR)"
                        ),
                    )
                )
        return findings
