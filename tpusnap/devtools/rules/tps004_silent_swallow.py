"""TPS004 — no silent exception swallows in crash-safety modules.

``except Exception: pass`` in the modules that implement abort
propagation, the take journal and fault injection hides exactly the
failures those layers exist to surface. Every swallow must be either
logged (``logger.debug(..., exc_info=True)`` is enough — the point is
that the evidence EXISTS when someone turns the level up) or waived
with a reason (``pass  # tpusnap: waive=TPS004 <why>``), so every
swallow in a crash-safety module is deliberate and self-documenting.
Handlers that return/continue/raise are deliberate control flow and are
not flagged."""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..lint import Finding, LintContext, Rule, SourceFile

# The crash-safety modules: distributed abort + coordination (comm,
# dist_store), the take journal / fsck / gc (lifecycle), and the fault
# injection layer itself (faults).
SCOPED_MODULES = {"comm.py", "dist_store.py", "lifecycle.py", "faults.py"}

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare except
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in _BROAD for e in t.elts
        )
    return False


class SilentSwallowRule(Rule):
    id = "TPS004"
    title = "silent except-pass in a crash-safety module"

    def check_file(
        self, sf: SourceFile, ctx: LintContext
    ) -> Iterable[Finding]:
        if sf.relpath not in SCOPED_MODULES or sf.tree is None:
            return ()
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler) or not _is_broad(node):
                continue
            if not all(isinstance(s, ast.Pass) for s in node.body):
                continue
            anchor = node.body[0]
            findings.append(
                Finding(
                    rule=self.id,
                    path=sf.display_path,
                    line=anchor.lineno,
                    col=anchor.col_offset,
                    message=(
                        "broad exception silently swallowed in a "
                        "crash-safety module — add a logger.debug(..., "
                        "exc_info=True) or waive with a reason"
                    ),
                )
            )
        return findings
