"""Write-load partitioner: spreads replicated write requests across ranks.

Counterpart of /root/reference/torchsnapshot/partitioner.py:24-316. Every
rank holds an identical copy of each replicated value, so any rank can
write it; the partitioner makes sure each replicated unit is written by
exactly one rank, chosen greedily so total write load balances:

- units: one per replicated entry; chunked tensors subpartition per-chunk
  (reference :42-79);
- per-rank starting load = that rank's non-replicated write bytes
  (all-gathered, reference :122-129);
- rank 0 assigns each unit (largest first) to the currently least-loaded
  rank and broadcasts the assignment (reference :144);
- each rank keeps only the write requests assigned to it. Manifest
  consolidation picks the writer's entry version (which may have been
  slab-batched) — see ``consolidate_replicated_entries``.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple, Union

from .comm import Communicator
from .knobs import is_partitioner_disabled
from .manifest import ChunkedTensorEntry, Entry, Manifest, is_replicated
from .io_types import WriteReq

logger = logging.getLogger(__name__)

# A unit key is either a logical path (atomic entries) or
# (logical_path, chunk_location) for per-chunk units.
UnitKey = Union[str, Tuple[str, str]]


def _collect_units(
    entries: Manifest, replicated_paths: List[str], write_req_costs: Dict[str, int]
) -> List[Tuple[UnitKey, List[str], int]]:
    """[(unit_key, [write_req_path], load_bytes)] for replicated entries."""
    units: List[Tuple[UnitKey, List[str], int]] = []
    for logical_path in replicated_paths:
        entry = entries[logical_path]
        if isinstance(entry, ChunkedTensorEntry):
            for chunk in entry.chunks:
                loc = chunk.tensor.location
                units.append(
                    ((logical_path, loc), [loc], write_req_costs.get(loc, 0))
                )
        else:
            loc = getattr(entry, "location", None)
            if loc is None:
                continue
            units.append((logical_path, [loc], write_req_costs.get(loc, 0)))
    return units


def partition_write_reqs(
    entries: Manifest,
    write_reqs: List[WriteReq],
    replicated_paths: List[str],
    comm: Communicator,
) -> List[WriteReq]:
    """Drop replicated write requests not assigned to this rank. Entries
    are left untouched (locations are rank-agnostic)."""
    if comm.world_size == 1 or not replicated_paths or is_partitioner_disabled():
        return write_reqs

    write_req_costs = {
        wr.path: wr.buffer_stager.get_staging_cost_bytes() for wr in write_reqs
    }
    units = _collect_units(entries, sorted(replicated_paths), write_req_costs)
    replicated_req_paths = {p for _, paths, _ in units for p in paths}

    # Starting load: this rank's non-replicated write bytes.
    own_load = sum(
        cost
        for path, cost in write_req_costs.items()
        if path not in replicated_req_paths
    )
    all_loads = comm.all_gather_object(own_load)

    if comm.rank == 0:
        assignment = _greedy_assign(units, all_loads)
    else:
        assignment = None
    assignment = comm.broadcast_object(assignment, src=0)

    keep_paths = {
        path
        for (unit_key, paths, _) in units
        for path in paths
        if assignment[_unit_id(unit_key)] == comm.rank
    }
    return [
        wr
        for wr in write_reqs
        if wr.path not in replicated_req_paths or wr.path in keep_paths
    ]


def _unit_id(unit_key: UnitKey) -> str:
    return unit_key if isinstance(unit_key, str) else f"{unit_key[0]}::{unit_key[1]}"


def _greedy_assign(
    units: List[Tuple[UnitKey, List[str], int]], loads: List[int]
) -> Dict[str, int]:
    """Largest-first argmin-greedy assignment (reference :42-79)."""
    loads = list(loads)
    assignment: Dict[str, int] = {}
    for unit_key, _, cost in sorted(units, key=lambda u: u[2], reverse=True):
        target = min(range(len(loads)), key=lambda r: loads[r])
        loads[target] += cost
        assignment[_unit_id(unit_key)] = target
    return assignment


def consolidate_replicated_entries(
    per_rank_entries: List[Manifest],
    replicated_paths_per_rank: Optional[List[List[str]]] = None,
) -> Manifest:
    """Merge per-rank manifests into the global ``rank/path``-keyed
    manifest, deduping replicated entries onto rank 0's tree while
    preferring the *writer's* entry version (whose location/byte_range
    reflect slab batching) — reference partitioner.py:236-303.

    The writer's version is recognized without carrying the assignment
    around: exactly one rank's copy of a replicated entry was rewritten
    by its batcher (location under ``batched/``) or, if unbatched, all
    copies are identical so any works. Chunked entries merge per-chunk
    the same way.
    """
    global_manifest: Manifest = {}
    world_size = len(per_rank_entries)

    # Pass 1: find the authoritative version of each replicated path.
    authoritative: Dict[str, Entry] = {}
    for r in range(world_size):
        for path, entry in per_rank_entries[r].items():
            if not is_replicated(entry):
                continue
            if path not in authoritative:
                authoritative[path] = entry
                continue
            current = authoritative[path]
            if isinstance(entry, ChunkedTensorEntry) and isinstance(
                current, ChunkedTensorEntry
            ):
                # Per-chunk: prefer batched (slab-located) chunk versions.
                merged_chunks = []
                for cur_chunk, new_chunk in zip(current.chunks, entry.chunks):
                    merged_chunks.append(
                        new_chunk
                        if new_chunk.tensor.location.startswith("batched/")
                        else cur_chunk
                    )
                current.chunks = merged_chunks
            elif getattr(entry, "location", "").startswith("batched/"):
                authoritative[path] = entry

    for r in range(world_size):
        for path, entry in per_rank_entries[r].items():
            if is_replicated(entry):
                if r == 0:
                    global_manifest[f"0/{path}"] = authoritative[path]
                continue
            global_manifest[f"{r}/{path}"] = entry
    return global_manifest
