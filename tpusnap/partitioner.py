"""Write-load partitioner: spreads replicated write requests across ranks.

Counterpart of /root/reference/torchsnapshot/partitioner.py:24-316. Every
rank holds an identical copy of each replicated value, so any rank can
write it; the partitioner makes sure each replicated unit is written by
exactly one rank, chosen greedily so total write load balances:

- units: one per replicated entry; chunked tensors subpartition per-chunk
  (reference :42-79);
- per-rank starting load = that rank's non-replicated write bytes,
  estimated collective-free before prepare so it rides take's single
  pre-staging gather (reference all-gathers separately, :122-129);
- EVERY rank runs the same deterministic argmin-greedy assignment on the
  identical gathered inputs — the reference's rank-0-compute + broadcast
  (reference :144) is one more collective for no benefit;
- each rank keeps only the write requests assigned to it. Manifest
  consolidation picks the writer's entry version (which may have been
  slab-batched) — see ``consolidate_replicated_entries``.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from .knobs import is_partitioner_disabled
from .manifest import ChunkedTensorEntry, Entry, Manifest, is_replicated
from .io_types import WriteReq

logger = logging.getLogger(__name__)


def estimate_write_loads(
    flattened: Dict[str, object],
    replicated_candidates: List[str],
    array_prepare_func=None,
) -> Tuple[List[Tuple[str, int]], int, Dict[str, Tuple[str, List[int]]]]:
    """Pre-prepare, collective-free load estimation for this rank.

    Returns ``(replicated_units, base_load, traced_map)``: one
    ``(unit_id, cost)`` per replicated candidate (chunked arrays
    subpartition per chunk, unit id ``"path::<chunk_idx>"``), the rank's
    non-replicated write bytes, and the traced post-transform
    ``{path: (dtype, shape)}`` geometry for every dense array — handed
    back to prepare_write so untraceable transforms don't execute twice.
    Costs mirror what the preparers will produce — array nbytes under
    the (traced) save-time transform, chunk-grain splits, sys.getsizeof
    for pickled objects (the reference's own approximation,
    object.py:76-78) — so every rank can run the same deterministic
    assignment on the gathered results with NO extra collective and NO
    broadcast. The routing predicates ARE the preparers' own
    (is_supported_array_dtype / is_sharded / should_chunk /
    chunk_row_ranges / trace_array_prepare);
    tests/test_partitioner_batcher.py pins unit ids against
    actually-prepared entries to catch drift.

    ``array_prepare_func(logical_path, arr, tracing)`` must be the same
    transform later given to prepare_write."""
    import functools
    import sys as _sys

    import jax
    import numpy as np

    from .io_preparers.array import is_supported_array_dtype, trace_array_prepare
    from .io_preparers.chunked import chunk_row_ranges, should_chunk
    from .io_preparers.sharded import is_sharded
    from .manifest import PrimitiveEntry
    from .serialization import dtype_to_string, tensor_nbytes

    candidates = set(replicated_candidates)
    units: List[Tuple[str, int]] = []
    traced_map: Dict[str, Tuple[str, List[int]]] = {}
    base_load = 0
    for path in sorted(flattened):
        leaf = flattened[path]
        if PrimitiveEntry.supported(leaf):
            # Inlined in metadata, no write load — but a replicated
            # primitive still needs a zero-cost unit so the intersection
            # marks its entry replicated (manifest dedup onto rank 0).
            if path in candidates:
                units.append((path, 0))
            continue
        if isinstance(leaf, np.generic):  # mirrors prepare_write
            leaf = np.asarray(leaf)
        is_array = isinstance(leaf, (jax.Array, np.ndarray))
        if is_array and isinstance(leaf, jax.Array) and is_sharded(leaf):
            # Sharded entries are never replicated-partitioned; their
            # local shards are this rank's own load — at the STORED
            # dtype's width when a save-time transform casts them
            # (trace cached so the sharded preparer doesn't re-trace).
            try:
                local_nbytes = sum(
                    s.data.nbytes for s in leaf.addressable_shards
                )
            except Exception:
                continue
            try:
                if array_prepare_func is not None:
                    dtype, shape = trace_array_prepare(
                        leaf, functools.partial(array_prepare_func, path)
                    )
                    traced_map[path] = (dtype, shape)
                    stored = tensor_nbytes(dtype, shape)
                    orig = tensor_nbytes(
                        dtype_to_string(leaf.dtype), list(leaf.shape)
                    )
                    if orig:
                        local_nbytes = local_nbytes * stored // orig
            except Exception:
                pass  # untransformed width is still the right order
            base_load += local_nbytes
            continue
        # Mirror prepare_write's routing: only supported-dtype arrays
        # reach the array preparers (and hence the save-time transform);
        # anything else is pickled untransformed.
        if is_array and is_supported_array_dtype(leaf):
            # The stored dtype/shape under the save-time transform — the
            # same trace the preparers will run (cached into traced_map
            # so prepare_write doesn't re-execute untraceable transforms).
            dtype, shape = trace_array_prepare(
                leaf,
                functools.partial(array_prepare_func, path)
                if array_prepare_func is not None
                else None,
            )
            traced_map[path] = (dtype, shape)
            nbytes = tensor_nbytes(dtype, shape)
        else:
            is_array = False
            nbytes = _sys.getsizeof(leaf)
            dtype = None
        if path not in candidates:
            base_load += nbytes
            continue
        if is_array and dtype is not None and should_chunk(leaf):
            for i, (r0, r1) in enumerate(
                chunk_row_ranges(list(shape), dtype, _max_chunk())
            ):
                units.append(
                    (f"{path}::{i}", tensor_nbytes(dtype, [r1 - r0] + list(shape[1:])))
                )
        else:
            units.append((path, nbytes))
    return units, base_load, traced_map


def _max_chunk() -> int:
    from .knobs import get_max_chunk_size_bytes

    return get_max_chunk_size_bytes()


def assign_replicated_units(
    per_rank_units: List[List[Tuple[str, int]]],
    per_rank_base_loads: List[int],
    unit_valid=None,
) -> Tuple[Dict[str, int], set]:
    """Deterministic partition plan from gathered per-rank estimates.

    A unit is partitionable only when EVERY rank listed it (the
    replicated-path intersection, reference snapshot.py:605-638) and
    ``unit_valid`` (if given) accepts it; a rank's non-common candidates
    fall back into its base load since it will write them itself. Every
    rank computes the identical plan — argmin-greedy over identical
    gathered inputs is deterministic — so no broadcast is needed.

    Returns ``(assignment, common_paths)``: unit_id -> writer rank, and
    the set of logical paths whose entries are replicated on all ranks.
    """
    unit_sets = [{u for u, _ in units} for units in per_rank_units]
    common = set.intersection(*unit_sets) if unit_sets else set()
    if unit_valid is not None:
        common = {u for u in common if unit_valid(u)}
    loads = list(per_rank_base_loads)
    for r, units in enumerate(per_rank_units):
        loads[r] += sum(cost for u, cost in units if u not in common)
    # Costs of common units are identical across ranks (same bytes);
    # take them from rank 0's list.
    costs = {u: c for u, c in per_rank_units[0] if u in common}
    shaped_units = [(u, [u], costs[u]) for u in sorted(common)]
    assignment = _greedy_assign(shaped_units, loads)
    common_paths = {u.split("::", 1)[0] for u in common}
    return assignment, common_paths


def filter_assigned_write_reqs(
    entries: Manifest,
    write_reqs: List[WriteReq],
    replicated_paths: List[str],
    assignment: Dict[str, int],
    rank: int,
) -> Tuple[List[WriteReq], Dict[str, List[WriteReq]]]:
    """Drop replicated write requests not assigned to this rank. Entries
    are left untouched (locations are rank-agnostic).

    Returns ``(kept, dropped)`` where ``dropped`` maps each
    partitioned unit id assigned to ANOTHER rank to this rank's own
    (identical-bytes, unstaged) write requests for it — retained so the
    degraded-commit path can ADOPT a dead rank's assignments: any
    survivor can stage and write its own replicated copy in the dead
    writer's place (snapshot.py's ``_degraded_commit``)."""
    if not replicated_paths or is_partitioner_disabled():
        return write_reqs, {}
    keep_paths = set()
    replicated_req_paths = set()
    unit_of_location: Dict[str, str] = {}

    def decide(unit_id: str, location: str) -> None:
        replicated_req_paths.add(location)
        unit_of_location[location] = unit_id
        writer = assignment.get(unit_id)
        if writer is None:
            # A unit of a replicated-marked path missing from the plan
            # means the estimate and the prepared entry disagree (e.g.
            # ranks saw different shapes). Write it ourselves — a
            # duplicate write of identical bytes is harmless, a blob the
            # manifest references but nobody wrote corrupts the snapshot.
            logger.warning(
                "replicated unit %r (blob %r) is not in the partition "
                "plan; writing it on every rank",
                unit_id,
                location,
            )
            keep_paths.add(location)
        elif writer == rank:
            keep_paths.add(location)

    for logical_path in sorted(replicated_paths):
        entry = entries[logical_path]
        if isinstance(entry, ChunkedTensorEntry):
            for i, chunk in enumerate(entry.chunks):
                decide(f"{logical_path}::{i}", chunk.tensor.location)
        else:
            loc = getattr(entry, "location", None)
            if loc is not None:
                decide(logical_path, loc)
    kept = []
    dropped: Dict[str, List[WriteReq]] = {}
    for wr in write_reqs:
        if wr.path not in replicated_req_paths or wr.path in keep_paths:
            kept.append(wr)
        else:
            dropped.setdefault(unit_of_location[wr.path], []).append(wr)
    return kept, dropped


def reassign_dead_units(
    assignment: Dict[str, int],
    dead_ranks,
    live_ranks,
) -> Dict[str, int]:
    """Deterministic adoption plan for a degraded commit: every unit
    whose assigned writer died is re-assigned round-robin across the
    sorted live set. Every survivor computes the identical plan from
    the identical (assignment, dead, live) inputs — the same
    no-broadcast property as the original argmin-greedy."""
    dead = set(dead_ranks)
    live = sorted(live_ranks)
    orphaned = sorted(u for u, w in assignment.items() if w in dead)
    return {u: live[i % len(live)] for i, u in enumerate(orphaned)}




def _greedy_assign(
    units: List[Tuple[str, List[str], int]], loads: List[int]
) -> Dict[str, int]:
    """Largest-first argmin-greedy assignment (reference :42-79)."""
    loads = list(loads)
    assignment: Dict[str, int] = {}
    for unit_id, _, cost in sorted(units, key=lambda u: u[2], reverse=True):
        target = min(range(len(loads)), key=lambda r: loads[r])
        loads[target] += cost
        assignment[unit_id] = target
    return assignment


def consolidate_replicated_entries(
    per_rank_entries: List[Manifest],
    replicated_paths_per_rank: Optional[List[List[str]]] = None,
) -> Manifest:
    """Merge per-rank manifests into the global ``rank/path``-keyed
    manifest, deduping replicated entries onto rank 0's tree while
    preferring the *writer's* entry version (whose location/byte_range
    reflect slab batching) — reference partitioner.py:236-303.

    The writer's version is recognized without carrying the assignment
    around: only the writer's copy was rewritten by its batcher (location
    under ``batched/``), rewritten by incremental dedup (location under
    ``../``), or staged at all (stage-time ``checksum`` recorded — the
    non-writers' copies never stage). If nothing marks a writer
    (checksums disabled, unbatched, non-incremental), all copies are
    identical and any works. Chunked entries merge per-chunk the same
    way (the partitioner assigns chunks of one entry to different
    writer ranks).
    """
    global_manifest: Manifest = {}
    world_size = len(per_rank_entries)

    def writer_marked(t) -> bool:
        return (
            getattr(t, "location", "").startswith(("batched/", "../"))
            or getattr(t, "checksum", None) is not None
        )

    # Pass 1: find the authoritative version of each replicated path.
    authoritative: Dict[str, Entry] = {}
    for r in range(world_size):
        for path, entry in per_rank_entries[r].items():
            if not is_replicated(entry):
                continue
            if path not in authoritative:
                authoritative[path] = entry
                continue
            current = authoritative[path]
            if isinstance(entry, ChunkedTensorEntry) and isinstance(
                current, ChunkedTensorEntry
            ):
                merged_chunks = []
                for cur_chunk, new_chunk in zip(current.chunks, entry.chunks):
                    merged_chunks.append(
                        new_chunk
                        if writer_marked(new_chunk.tensor)
                        and not writer_marked(cur_chunk.tensor)
                        else cur_chunk
                    )
                current.chunks = merged_chunks
            elif writer_marked(entry) and not writer_marked(current):
                authoritative[path] = entry

    for r in range(world_size):
        for path, entry in per_rank_entries[r].items():
            if is_replicated(entry):
                if r == 0:
                    global_manifest[f"0/{path}"] = authoritative[path]
                continue
            global_manifest[f"{r}/{path}"] = entry
    return global_manifest
