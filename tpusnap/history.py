"""Cross-run checkpoint performance history + regression detection.

PR 2/4 made a single take legible (persisted traces, live heartbeats);
every one of those numbers still dies with the process or stays buried
inside one snapshot's sidecar. This module is the cross-RUN memory: an
append-only, size-bounded, per-host JSONL history
(``TPUSNAP_TELEMETRY_DIR/history.jsonl``) of every COMPLETED take and
restore — throughput, phase breakdown, bytes, world size,
salvage/dedup/stall counters — plus the trailing-median regression
check behind ``python -m tpusnap history --check``.

Design constraints, in order:

- **Never fail a take.** Recording is best-effort and exception-free at
  the call sites (:func:`record_summary` is invoked from
  ``telemetry.end_take`` under a try/except).
- **Crash-tolerant.** Appends are single ``os.write`` calls on an
  ``O_APPEND`` descriptor (concurrent ranks/processes interleave whole
  lines, never bytes); a process killed mid-append leaves at most one
  torn FINAL line, which :func:`load_history` (and the compactor)
  silently drop — the acceptance property "history survives a torn
  final line".
- **Size-bounded.** When an append pushes the file past
  ``TPUSNAP_HISTORY_MAX_BYTES`` the oldest lines are compacted away
  (newest kept to half the bound, temp+rename). Compaction racing a
  concurrent appender can drop that appender's in-flight line — an
  accepted best-effort bound, same stance as every other observability
  surface here.
- **Cold-run aware.** The first recorded event of each kind in a
  process is tagged ``cold: true`` (it pays imports, native-library
  load, allocator growth — BENCH_r05's 0.206 first-run outlier in
  ``roofline_fraction_fullscale_runs`` is exactly this shape). The
  regression check matches the cold tag like-for-like: a lone cold
  run among warm ones passes (warmup never pages an operator), while
  an all-cold history — the one-take-per-process fleet — grades cold
  against cold so the gate still fires.

Monotonic-only invariant: durations in events come from the telemetry
summaries' monotonic math; the one wall-clock TIMESTAMP (``ts``) goes
through the module's injectable ``_wall`` seam — direct wall-clock
calls are lint-forbidden in this file (tests/test_knob_docs.py).
"""

from __future__ import annotations

import json
import logging
import os
import statistics
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .knobs import (
    get_explicit_job_id,
    get_history_max_bytes,
    get_telemetry_dir,
    is_history_enabled,
)

logger = logging.getLogger(__name__)

HISTORY_FILENAME = "history.jsonl"

# Wall-clock seam: timestamps only, never duration math (tests inject).
_wall = time.time

# Event kinds with per-process cold tagging already consumed.
_warm_kinds: set = set()
_state_lock = threading.Lock()


def history_path() -> str:
    """The per-host history file (under the telemetry dir)."""
    return os.path.join(get_telemetry_dir(), HISTORY_FILENAME)


def _reset_process_state() -> None:
    """Test aid: forget which kinds consumed their cold tag."""
    with _state_lock:
        _warm_kinds.clear()


# ------------------------------------------------------------- recording


def event_from_summary(kind: str, summary: Dict[str, Any]) -> Dict[str, Any]:
    """Flatten one telemetry summary into a compact history/JSONL event:
    the trend-relevant scalars only (throughput, phases, byte and
    episode counters) — spans and full counter maps stay in the trace
    files."""
    counters = summary.get("counters") or {}
    gauges = summary.get("gauges") or {}
    wall = float(summary.get("take_wall_s") or 0.0)
    byte_counter = (
        "storage.bytes_read" if kind == "restore" else "storage.bytes_written"
    )
    nbytes = int(counters.get(byte_counter, 0))
    ev: Dict[str, Any] = {
        "v": 1,
        "ts": round(_wall(), 3),
        "kind": kind,
        "rank": summary.get("rank", 0),
        "world_size": summary.get("world_size", 1),
        # Job identity: two named jobs (TPUSNAP_JOB_ID) sharing one
        # telemetry dir interleave events in the same history.jsonl,
        # and the regression baseline filters on this so they never
        # grade against each other. Deliberately the EXPLICIT id only —
        # the host-pid default changes every process and would empty
        # every cross-run baseline.
        "job_id": get_explicit_job_id(),
        "take_id": summary.get("take_id"),
        "path": summary.get("path"),
        "wall_s": round(wall, 6),
        "bytes": nbytes,
        # Incremental takes write only the delta — their written-bytes
        # throughput is incommensurable with full takes', so the
        # regression check separates the two populations on this flag.
        "incremental": bool(summary.get("incremental")),
        "throughput_gbps": (
            round(nbytes / wall / 1e9, 6) if wall > 0 and nbytes else None
        ),
        "phases_s": {
            k: round(v, 6) for k, v in (summary.get("phases") or {}).items()
        },
        "stall_episodes": counters.get("progress.stall_episodes", 0),
        "retry_attempts": counters.get("retry.attempts", 0),
        "dedup_skips": counters.get("scheduler.dedup_skipped", 0),
        "blobs_salvaged": counters.get("salvage.blobs_salvaged", 0),
        "bytes_salvaged": counters.get("salvage.bytes_salvaged", 0),
    }
    # The storage backend this run read/wrote (innermost plugin class,
    # tier-aware for restores): the SLO RTO estimator filters its
    # baseline on it so cloud-tier restores never get priced with
    # local-disk history.
    if summary.get("plugin"):
        ev["plugin"] = summary["plugin"]
    if "scheduler.budget_used_bytes" in gauges:
        ev["budget_high_water_bytes"] = int(gauges["scheduler.budget_used_bytes"])
    if "peak_rss_delta_bytes" in gauges:
        ev["peak_rss_delta_bytes"] = int(gauges["peak_rss_delta_bytes"])
    # Async takes: the blocked window (take start → control returned to
    # training). A *_s metric, so `history --check --metric
    # async_blocked_s` gates it upward like every other duration — the
    # pipelined-staging win cannot silently regress.
    if isinstance(summary.get("async_blocked_s"), (int, float)):
        ev["async_blocked_s"] = round(float(summary["async_blocked_s"]), 6)
    # Fused tile compression: the take's resolved policy decision plus
    # realized ratio/codec throughput. Flat scalars so `history --check
    # --metric compress_ratio` (or the bench's effective-GB/s metrics)
    # trend and gate like everything else; absent on bypassed takes
    # keeps old/new event populations comparable.
    comp = summary.get("compress")
    if isinstance(comp, dict):
        ev["compress_decision"] = comp.get("decision")
        ev["compress_reason"] = comp.get("reason")
        if comp.get("codec_gbps"):
            ev["compress_codec_gbps"] = comp["codec_gbps"]
        if comp.get("pipe_gbps") is not None:
            ev["compress_pipe_gbps"] = comp["pipe_gbps"]
    c_in = counters.get("compress.bytes_in", 0)
    c_out = counters.get("compress.bytes_out", 0)
    if c_in and c_out:
        ev["compress_bytes_in"] = int(c_in)
        ev["compress_bytes_out"] = int(c_out)
        ev["compress_ratio"] = round(c_in / c_out, 4)
    # Storage-boundary latency quantiles from the run's log2 histograms
    # (merged across plugin classes, per op): *_s metrics, so `history
    # --check --metric storage_write_p99_s` (and storage_read_p99_s on
    # restores/benches) gates tail latency upward exactly like every
    # other duration.
    for op in ("write", "read"):
        op_lat = None
        for key, st in (summary.get("io_histograms") or {}).items():
            if not key.startswith(f"{op}."):
                continue
            try:
                from .telemetry import LogHistogram

                h = LogHistogram.from_dict(st.get("latency") or {})
            except Exception:
                continue
            if op_lat is None:
                op_lat = h
            else:
                op_lat.merge(h)
        if op_lat is not None and op_lat.count:
            p50, p99 = op_lat.quantile(0.5), op_lat.quantile(0.99)
            if p50 is not None:
                ev[f"storage_{op}_p50_s"] = round(p50, 6)
            if p99 is not None:
                ev[f"storage_{op}_p99_s"] = round(p99, 6)
    # Roofline probes (TPUSNAP_PROBE=1): the drift-immune fraction and
    # the measured ceiling ride the trend — write lane for takes, read
    # lane for restores.
    if isinstance(summary.get("roofline_fraction"), (int, float)):
        ev["roofline_fraction"] = round(float(summary["roofline_fraction"]), 4)
        pw = (summary.get("probe") or {}).get("write_gbps_p50")
        if pw:
            ev["probe_write_gbps"] = pw
    if isinstance(summary.get("restore_roofline_fraction"), (int, float)):
        ev["restore_roofline_fraction"] = round(
            float(summary["restore_roofline_fraction"]), 4
        )
        pr = (summary.get("probe") or {}).get("read_gbps_p50")
        if pr:
            ev["probe_read_gbps"] = pr
    # Access-ledger attribution (restores / read_object scopes): the
    # scope's logical read totals and distinct-byte working set. Flat
    # ints so `analyze`/`tune` can size restore budgets from the HOT
    # working set instead of the whole snapshot, and so amplification
    # trends are greppable straight from history.jsonl.
    acc = summary.get("access")
    if isinstance(acc, dict):
        ev["access_bytes_read"] = int(acc.get("bytes_read") or 0)
        ev["access_reads"] = int(acc.get("reads") or 0)
        ev["access_working_set_bytes"] = int(acc.get("working_set_bytes") or 0)
    # Auto-tuner provenance (TPUSNAP_AUTOTUNE=1): which plan and which
    # knobs this run actually applied, so any regression the tuner
    # causes is attributable — and gated by the same `history --check`
    # that gates everything else.
    if isinstance(summary.get("tuned"), dict):
        ev["tuned"] = summary["tuned"]
    # Checkpoint-SLO section (tpusnap.slo, recorded at the commit
    # anchor): realized commit interval, the interval's change bytes,
    # and the estimated RTO at commit time. commit_interval_s is a
    # *_s metric, so `history --check --metric slo.commit_interval_s`
    # would gate it upward — but the flat copy below is what makes the
    # top-level gate usable without dotted-path lookups.
    slo = summary.get("slo")
    if isinstance(slo, dict):
        ev["slo"] = slo
        if isinstance(slo.get("commit_interval_s"), (int, float)):
            ev["commit_interval_s"] = round(float(slo["commit_interval_s"]), 3)
        if isinstance(slo.get("estimated_rto_s"), (int, float)):
            ev["estimated_rto_s"] = round(float(slo["estimated_rto_s"]), 3)
    return ev


def record_summary(
    kind: str, summary: Dict[str, Any], cold: Optional[bool] = None
) -> Optional[Dict[str, Any]]:
    """Append one COMPLETED take/restore summary to the history.
    Summaries without ``completed: True`` (aborted takes, failed
    restores) are skipped — a half-take's throughput is not a trend
    point. Returns the recorded event, or None when skipped/disabled."""
    if not is_history_enabled():
        return None
    if not summary.get("completed"):
        return None
    ev = event_from_summary(kind, summary)
    if cold is None:
        with _state_lock:
            cold = kind not in _warm_kinds
            _warm_kinds.add(kind)
    if cold:
        ev["cold"] = True
    return record_event(ev)


def append_jsonl_line(path: str, line: str) -> None:
    """Crash-tolerant JSONL append (shared by the history store and the
    JSONL export sink): one O_APPEND write so concurrent writers
    interleave whole lines. O_RDWR (not O_WRONLY) because a crash
    mid-append leaves a torn final line with no newline — blindly
    appending would concatenate the new record onto the torn tail and
    corrupt BOTH; peeking at the last byte and leading with a newline
    isolates the torn fragment on its own (skipped) line."""
    if not line.endswith("\n"):
        line += "\n"
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        size = os.fstat(fd).st_size
        if size > 0 and os.pread(fd, 1, size - 1) != b"\n":
            line = "\n" + line
        os.write(fd, line.encode("utf-8"))
    finally:
        os.close(fd)


def record_event(
    event: Dict[str, Any], path: Optional[str] = None
) -> Optional[Dict[str, Any]]:
    """Atomically append one event line, then enforce the size bound.
    Best-effort: failures log at DEBUG and return None."""
    if not is_history_enabled():
        return None
    path = path or history_path()
    try:
        append_jsonl_line(path, json.dumps(event, separators=(",", ":")))
        _enforce_size_bound(path)
    except Exception:
        logger.debug("history append failed", exc_info=True)
        return None
    return event


def _enforce_size_bound(path: str) -> None:
    max_bytes = get_history_max_bytes()
    try:
        if os.path.getsize(path) <= max_bytes:
            return
    except OSError:
        return
    # Compact: keep the newest whole lines up to half the bound, so the
    # file breathes between compactions instead of rewriting per append.
    with open(path, "rb") as f:
        data = f.read()
    lines = data.split(b"\n")
    kept: List[bytes] = []
    budget = max_bytes // 2
    total = 0
    for ln in reversed(lines):
        if not ln.strip():
            continue
        if total + len(ln) + 1 > budget:
            break
        try:
            json.loads(ln)  # a torn/corrupt line is not worth keeping
        except Exception:
            continue
        kept.append(ln)
        total += len(ln) + 1
    kept.reverse()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(b"\n".join(kept) + (b"\n" if kept else b""))
    os.replace(tmp, path)


# --------------------------------------------------------------- loading


def load_history(
    path: Optional[str] = None, limit: Optional[int] = None
) -> List[Dict[str, Any]]:
    """All parseable events, oldest first. Crash-tolerant: a torn final
    line (or any corrupt line) is skipped, never raised. ``limit`` keeps
    the newest N."""
    path = path or history_path()
    out: List[Dict[str, Any]] = []
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return out
    for ln in data.split(b"\n"):
        if not ln.strip():
            continue
        try:
            ev = json.loads(ln)
        except Exception:
            continue
        if isinstance(ev, dict):
            out.append(ev)
    if limit is not None and limit >= 0:
        out = out[-limit:]
    return out


# ---------------------------------------------------- regression checking

# Metrics where SMALLER is better (durations); everything else
# (throughput, fractions) regresses downward.
_LOWER_IS_BETTER_SUFFIXES = ("_s", "_seconds")


@dataclass
class RegressionReport:
    """Outcome of one trailing-median comparison. ``regressed`` is the
    CI-gate verdict; ``ok`` is False only when there was not enough
    comparable history to form a verdict at all."""

    ok: bool
    regressed: bool
    reason: str
    metric: str
    kind: str
    latest: Optional[float] = None
    baseline_median: Optional[float] = None
    ratio: Optional[float] = None
    n_baseline: int = 0
    window: int = 0
    threshold: float = 0.0
    latest_event: Optional[Dict[str, Any]] = field(default=None, repr=False)

    def to_json(self) -> Dict[str, Any]:
        return {
            "ok": self.ok,
            "regressed": self.regressed,
            "reason": self.reason,
            "metric": self.metric,
            "kind": self.kind,
            "latest": self.latest,
            "baseline_median": self.baseline_median,
            "ratio": self.ratio,
            "n_baseline": self.n_baseline,
            "window": self.window,
            "threshold": self.threshold,
        }


def check_regression(
    events: Optional[List[Dict[str, Any]]] = None,
    *,
    kind: str = "take",
    metric: str = "throughput_gbps",
    window: int = 20,
    threshold: float = 0.25,
    min_baseline: int = 3,
    rank: Optional[int] = 0,
) -> RegressionReport:
    """Compare the latest event's ``metric`` against the trailing median
    of the previous ``window`` comparable events.

    The LATEST event is the genuinely newest one of the kind/rank —
    never an older run that happens to carry the metric (a gate that
    silently evaluates a stale run reads as OK while the most recent
    run went unchecked); a latest run without the metric returns
    ``ok=False``. Comparable = same ``world_size`` AND the same
    ``incremental`` flag as the latest event (an incremental take's
    written-bytes throughput is incommensurable with a full take's),
    same ``rank`` (default 0 — per-rank byte counters make cross-rank
    throughputs incommensurable), metric present, and the same cold
    tag as the latest event. The cold symmetry matters in both
    directions: a lone cold run among warm ones passes (warmup is not
    a regression — there is no cold baseline to grade it against), but
    in one-take-per-process fleets where EVERY event is cold, cold
    runs grade against the trailing cold baseline like-for-like, so
    the gate still fires instead of being structurally green. Fewer
    than ``min_baseline`` comparable baseline events returns
    ``ok=False`` (exit 3 at the CLI) for a warm latest — a guess is
    not a verdict.

    Regression: for throughput-like metrics, latest < median x (1 -
    threshold); for duration metrics (``*_s``), latest > median x (1 +
    threshold)."""
    if events is None:
        events = load_history()
    cand = [
        e
        for e in events
        if e.get("kind") == kind
        and (rank is None or e.get("rank", 0) == rank)
    ]
    if not cand:
        return RegressionReport(
            ok=False,
            regressed=False,
            reason=f"no {kind} events in history",
            metric=metric,
            kind=kind,
            window=window,
            threshold=threshold,
        )
    latest = cand[-1]
    if not isinstance(latest.get(metric), (int, float)):
        if latest.get("cold"):
            return RegressionReport(
                ok=True,
                regressed=False,
                reason=(
                    "latest run is cold-tagged (process warmup) and "
                    f"carries no value for metric {metric!r}; not compared"
                ),
                metric=metric,
                kind=kind,
                window=window,
                threshold=threshold,
                latest_event=latest,
            )
        return RegressionReport(
            ok=False,
            regressed=False,
            reason=(
                f"latest {kind} run has no value for metric {metric!r} "
                "(cannot be checked)"
            ),
            metric=metric,
            kind=kind,
            window=window,
            threshold=threshold,
            latest_event=latest,
        )
    cold_latest = bool(latest.get("cold"))
    baseline_vals = [
        float(e[metric])
        for e in cand[:-1]
        if bool(e.get("cold")) == cold_latest
        and isinstance(e.get(metric), (int, float))
        and e.get("world_size", 1) == latest.get("world_size", 1)
        and bool(e.get("incremental")) == bool(latest.get("incremental"))
        # Same comparability stance as kind/world_size: two different
        # jobs' runs interleaved in a shared telemetry dir must never
        # grade against each other (pre-job_id events are all None,
        # which keeps old histories self-comparable).
        and e.get("job_id") == latest.get("job_id")
    ][-window:]
    if len(baseline_vals) < max(1, min_baseline):
        if cold_latest:
            # A lone cold run among warm ones: warmup, not a regression
            # (and nothing like-for-like to grade it against).
            lv = latest.get(metric)
            return RegressionReport(
                ok=True,
                regressed=False,
                reason=(
                    "latest run is cold-tagged (process warmup); no cold "
                    "baseline to compare against"
                ),
                metric=metric,
                kind=kind,
                latest=float(lv) if isinstance(lv, (int, float)) else None,
                n_baseline=len(baseline_vals),
                window=window,
                threshold=threshold,
                latest_event=latest,
            )
        return RegressionReport(
            ok=False,
            regressed=False,
            reason=(
                f"only {len(baseline_vals)} comparable baseline event(s); "
                f"need {min_baseline}"
            ),
            metric=metric,
            kind=kind,
            latest=float(latest[metric]),
            n_baseline=len(baseline_vals),
            window=window,
            threshold=threshold,
            latest_event=latest,
        )
    median = statistics.median(baseline_vals)
    value = float(latest[metric])
    lower_is_better = metric.endswith(_LOWER_IS_BETTER_SUFFIXES)
    if median > 0:
        ratio = value / median
    else:
        ratio = None
    if lower_is_better:
        regressed = median > 0 and value > median * (1.0 + threshold)
        direction = "slower than"
    else:
        regressed = value < median * (1.0 - threshold)
        direction = "below"
    if regressed:
        reason = (
            f"{metric} {value:.4g} is {direction} the trailing-median "
            f"{median:.4g} by more than {threshold:.0%} "
            f"(n={len(baseline_vals)})"
        )
    else:
        reason = (
            f"{metric} {value:.4g} within {threshold:.0%} of trailing-median "
            f"{median:.4g} (n={len(baseline_vals)})"
        )
    if cold_latest:
        reason += " [cold-vs-cold: every run here is a process-first]"
    return RegressionReport(
        ok=True,
        regressed=regressed,
        reason=reason,
        metric=metric,
        kind=kind,
        latest=value,
        baseline_median=median,
        ratio=round(ratio, 4) if ratio is not None else None,
        n_baseline=len(baseline_vals),
        window=window,
        threshold=threshold,
        latest_event=latest,
    )
