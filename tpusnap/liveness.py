"""Rank-liveness leases: fail-fast detection of dead peers mid-take.

The distributed take protocol is all-ranks-blocking: manifest gathers,
the commit barriers and the async commit's LinearBarrier all park until
EVERY rank arrives. Before this module, one SIGKILLed or wedged rank
stranded every survivor for the full barrier timeout (historically
600 s/1800 s — now ``TPUSNAP_BARRIER_TIMEOUT_S``) and the whole take —
minutes of staged and written work — was lost with it. At fleet scale
(thousands of concurrent jobs, preemptible hosts) rank death is routine,
not exceptional, and a checkpointing service that hangs for 10 minutes
on one preempted host violates the RPO/RTO objectives the SLO tracker
gates on.

Two pieces, both riding machinery that already exists:

- :class:`LeasePublisher` — one per-rank lease record under
  ``tpusnap_lease/<take_id>/<rank>``, republished on every heartbeat
  pump tick (:class:`tpusnap.progress.ProgressMonitor` — NO new
  thread). The record is a monotonically increasing sequence number
  plus a state tag; a final ``done``/``aborted`` publish marks a rank
  that exited the take deliberately, which peers never expire.

- :class:`LivenessMonitor` — consulted from inside every blocking wait
  (the communicator's polling barriers, ``LinearBarrier`` watchers, the
  commit path). Staleness is judged OBSERVER-SIDE: the monitor records,
  on its own monotonic clock, when each peer's sequence last advanced —
  no cross-host clock comparison, no NTP sensitivity. A peer whose
  lease has not advanced for more than the TTL
  (``TPUSNAP_LIVENESS_TTL_S``, default 15 s) is declared dead and the
  wait raises :class:`RankFailedError` naming it — detection within
  ~2x TTL (one TTL of allowed staleness + publish/poll cadence), not
  the barrier timeout.

Composition: the detecting rank's failure path publishes the error
through the existing :class:`~tpusnap.dist_store.TakeAbortMonitor`, so
survivors that have not yet judged the lease themselves abort within
seconds via the normal ``TakeAbortedError`` propagation. With
``TPUSNAP_RANK_FAILURE=degrade`` the take may instead complete on the
survivors — see ``snapshot.py``'s degraded-commit path.

A wedged-but-alive rank (stuck inside one op, heartbeat pump still
running) keeps its lease fresh: liveness distinguishes DEAD from SLOW
by construction, and the slow case stays the stall watchdog's job
(which now also reports lease-expired peers — the ``rank_dead`` flight
event the timeline post-mortem folds in).

Durations here run on the injectable monotonic ``clock``; the module is
listed in the TPS002 monotonic-only lint scope.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Set

logger = logging.getLogger(__name__)

_LEASE_PREFIX = "tpusnap_lease"


def lease_prefix(take_id: str) -> str:
    return f"{_LEASE_PREFIX}/{take_id}/"


def lease_key(take_id: str, rank: int) -> str:
    return f"{lease_prefix(take_id)}{rank}"


class RankFailedError(RuntimeError):
    """A peer rank's liveness lease expired mid-take: the rank is dead
    (SIGKILLed, host lost, process frozen without its pump) from this
    process's point of view. Raised from inside the blocking wait that
    would otherwise have parked until the barrier timeout. ``ranks``
    names every expired rank; ``take_id`` scopes the evidence."""

    def __init__(self, ranks: List[int], take_id: str, detail: str = "") -> None:
        self.ranks = sorted(ranks)
        self.take_id = take_id
        msg = (
            f"rank(s) {self.ranks} failed during take {take_id[:8]}: "
            "liveness lease expired"
        )
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)


class LeasePublisher:
    """This rank's lease: a seq counter republished at the heartbeat
    cadence. Everything is best-effort — a failed publish can never
    fail a take (peers tolerate up to a TTL of staleness)."""

    def __init__(self, kv, take_id: str, rank: int) -> None:
        self.kv = kv
        self.take_id = take_id
        self.rank = rank
        self._seq = 0
        self._state = "live"
        self._lock = threading.Lock()

    def publish(self, state: Optional[str] = None) -> None:
        with self._lock:
            self._seq += 1
            if state is not None:
                self._state = state
            payload = json.dumps(
                {"seq": self._seq, "state": self._state, "rank": self.rank}
            ).encode("utf-8")
        try:
            self.kv.set(lease_key(self.take_id, self.rank), payload)
        except Exception:
            logger.debug("lease publish failed", exc_info=True)

    def finish(self, state: str) -> None:
        """Terminal publish — THE one place a take outcome maps to a
        lease tag peers never expire (the rank exited deliberately;
        barrier keys / abort records carry the outcome). The tick hook
        delegates here when the pump's final record carries a terminal
        state, so the mapping cannot drift."""
        self.publish(state="done" if state == "committed" else "aborted")

    def leave(self) -> None:
        """Terminal publish for a GRACEFUL DEPARTURE (preemption notice
        honored, elastic scale-down): distinct from ``done``/``aborted``
        — the rank neither committed nor failed, it announced it is
        going away. Observers never expire it and never raise
        :class:`RankFailedError` for it; renderers show LEFT, not
        DEAD."""
        self.publish(state="left")

    def make_tick_hook(self) -> Callable[[Optional[dict]], None]:
        """The heartbeat pump piggyback: republish the lease every tick
        (cheap — one KV set per rank per interval, same order as the
        heartbeat itself); the pump's final committed/aborted record
        routes through :meth:`finish`."""

        def hook(record: Optional[dict]) -> None:
            state = record.get("state") if record else None
            if state in ("committed", "aborted"):
                self.finish(state)
            else:
                self.publish()

        return hook

    def cleanup(self) -> None:
        """Best-effort removal of the whole take's lease prefix (leader
        calls this after a successful commit, mirroring the abort- and
        progress-prefix sweeps)."""
        try:
            self.kv.delete_prefix(lease_prefix(self.take_id))
        except Exception:
            logger.debug("lease prefix cleanup failed", exc_info=True)


#: Lease states that mean "this rank exited the take deliberately" —
#: never expired by observers (the outcome travels via barrier keys or
#: abort records, both faster than a TTL). ``left`` is the graceful
#: elastic departure: not a commit, not a failure — the rank announced
#: it is leaving the world, and watchers must never declare it dead.
_TERMINAL_STATES = ("done", "aborted", "left")


class LivenessMonitor:
    """Observer-side lease staleness for one take.

    ``check()`` is designed to run inside poll loops (the communicator's
    polling barriers run their watcher every ~50 ms): it is throttled to
    one KV directory read per ``ttl/5`` and judges staleness on this
    process's monotonic clock — a peer whose lease seq has not advanced
    for > ``ttl_s`` (or that never published within 2x ttl of this
    monitor's start) raises :class:`RankFailedError`.

    The anchor is the monitor's construction time, which the take places
    strictly after the G1 gather — every rank was provably alive then,
    so "no lease yet" is a real signal, not a startup race."""

    def __init__(
        self,
        kv,
        take_id: str,
        rank: int,
        world_size: int,
        ttl_s: float,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.kv = kv
        self.take_id = take_id
        self.rank = rank
        self.world_size = world_size
        self.ttl_s = ttl_s
        self._clock = clock
        now = clock()
        # rank -> (last seen seq or None, monotonic time it last advanced)
        self._last: Dict[int, tuple] = {
            r: (None, now) for r in range(world_size)
        }
        self._terminal: Set[int] = set()
        self._left: Set[int] = set()
        self._last_refresh = -1e18
        self._throttle = max(0.1, ttl_s / 5.0)
        self._announced: Set[int] = set()
        self._lock = threading.Lock()

    # --- observation ------------------------------------------------------

    def _refresh(self, now: float) -> None:
        try:
            entries = self.kv.try_get_dir(lease_prefix(self.take_id))
        except Exception:
            return
        if entries is None:
            return
        prefix = lease_prefix(self.take_id)
        for key, raw in entries.items():
            rel = key[len(prefix):] if key.startswith(prefix) else key
            try:
                r = int(rel)
                rec = json.loads(
                    raw.decode("utf-8") if isinstance(raw, bytes) else raw
                )
                seq = int(rec["seq"])
            except Exception:
                continue
            if r not in self._last:
                continue
            state = rec.get("state")
            if state in _TERMINAL_STATES:
                self._terminal.add(r)
                if state == "left":
                    self._left.add(r)
            prev_seq, _prev_t = self._last[r]
            if seq != prev_seq:
                self._last[r] = (seq, now)

    def expired(self, now: Optional[float] = None) -> List[int]:
        """Sorted peer ranks whose lease is stale past the TTL right
        now (forcing a refresh — no throttle). Terminal leases and this
        rank itself never expire."""
        with self._lock:
            now = self._clock() if now is None else now
            self._refresh(now)
            return self._expired_locked(now)

    def _expired_locked(self, now: float) -> List[int]:
        out = []
        for r, (seq, t) in self._last.items():
            if r == self.rank or r in self._terminal:
                continue
            # A rank that never published gets a 2x-TTL grace from the
            # monitor's anchor (covers a SIGKILL in the tiny window
            # between G1 and its pump's first beat without doubling the
            # common-case detection bound).
            limit = self.ttl_s if seq is not None else 2.0 * self.ttl_s
            if now - t > limit:
                out.append(r)
        return sorted(out)

    # --- the watcher ------------------------------------------------------

    def check(self, exclude: Optional[Set[int]] = None) -> None:
        """Raise :class:`RankFailedError` if any (non-excluded) peer's
        lease expired. Throttled to one KV read per ``ttl/5``; safe to
        call every poll iteration from any thread."""
        if self.ttl_s <= 0:
            return
        with self._lock:
            now = self._clock()
            if now - self._last_refresh >= self._throttle:
                self._last_refresh = now
                self._refresh(now)
            dead = self._expired_locked(now)
            fresh = [r for r in dead if r not in self._announced]
            self._announced.update(fresh)
        for r in fresh:
            # Edge-triggered forensic breadcrumbs: one rank_dead flight
            # event + counter per expired peer, flushed crash-survivably
            # so the timeline post-mortem can name the dead rank even if
            # this survivor is itself killed moments later.
            try:
                from . import flight, telemetry

                telemetry.incr("liveness.rank_dead")
                flight.record(
                    "rank_dead", op=f"rank{r}", rank=r, ttl_s=self.ttl_s
                )
                # Crash-survivable NOW: the survivor raising in a few
                # microseconds may be torn down before the next
                # heartbeat flush, and the dead rank's name is the one
                # fact the post-mortem needs.
                flight.recorder().maybe_flush(force=True)
            except Exception:
                logger.debug("rank_dead breadcrumb failed", exc_info=True)
            try:
                from . import slo as _slo

                _slo.tracker().note_rank_dead([r])
            except Exception:
                logger.debug("rank_dead slo feed failed", exc_info=True)
            logger.warning(
                "tpusnap liveness: rank %d's lease expired (> %.1fs stale) "
                "during take %s — the rank is dead from rank %d's view",
                r,
                self.ttl_s,
                self.take_id[:8],
                self.rank,
            )
        if exclude:
            dead = [r for r in dead if r not in exclude]
        if dead:
            raise RankFailedError(dead, self.take_id)

    def watcher(
        self, exclude: Optional[Set[int]] = None
    ) -> Callable[[], None]:
        """A zero-arg callable for wait-watcher/barrier-watcher slots,
        optionally tolerating an already-acknowledged dead set (the
        degraded commit's barriers run over the live set and must not
        re-raise for the ranks they are degrading around)."""
        return lambda: self.check(exclude=exclude)

    def dead_ranks(self) -> Optional[List[int]]:
        """Already-announced expired ranks, WITHOUT a fresh KV read —
        the stall watchdog's cheap probe (it runs even when nothing is
        waiting in a barrier). None when none observed."""
        with self._lock:
            out = sorted(self._announced)
        return out or None

    def left_ranks(self) -> Optional[List[int]]:
        """Ranks that published a terminal ``left`` lease (graceful
        departure). Observed as a side effect of the throttled
        refreshes — no fresh KV read. None when none observed."""
        with self._lock:
            out = sorted(self._left)
        return out or None
