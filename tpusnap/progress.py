"""Live take/restore observability: heartbeat progress + stall watchdog.

PR 2's telemetry makes a FINISHED take legible; this module covers the
only window an operator actually cares about — a take that is still
running. Three pieces:

- **Heartbeat progress** (:class:`ProgressMonitor`): one daemon thread
  per telemetry-enabled take samples the recorder's observable state
  (:meth:`TakeTelemetry.live_snapshot` — last completed phase, in-flight
  ops, counters, span/event count) and publishes a compact progress
  record at a bounded cadence: at most one publish per
  ``TPUSNAP_HEARTBEAT_INTERVAL_S`` (default 0.5 s), and only when
  something actually changed (a periodic keep-alive bounds staleness) —
  O(world) KV keys per interval, never per op. Records land in the
  coordination KV under ``tpusnap_progress/<take_id>/<rank>`` and, for
  local-filesystem destinations, in
  ``<snapshot>/.tpusnap/progress/rank_<k>.json`` (atomic temp+rename),
  which is what ``python -m tpusnap watch`` tails. Everything is
  best-effort: a failed publish can never fail a take, and
  telemetry-off takes skip the whole subsystem.

- **Stall watchdog** (same thread): when the sampled state stops
  advancing for ``TPUSNAP_STALL_DEADLINE_S`` (default 30 s) while a
  named op is in flight, it emits ONE structured WARNING per stall
  episode naming the blocked op — and, via the polling barrier's
  per-rank arrive keys (``Communicator.barrier_missing_ranks`` /
  ``LinearBarrier.current_missing``), exactly which ranks have not
  arrived. A silent hang becomes an actionable log in seconds instead
  of a bare 600 s barrier timeout. The log record carries a
  ``tpusnap_stall`` dict (rank, op, phase, stalled_s, missing_ranks)
  for structured collectors.

- **Restore traces**: the snapshot is immutable once committed, so
  restore telemetry persists to a LOCAL trace dir
  (``TPUSNAP_TELEMETRY_DIR``, default ``<tmp>/tpusnap-telemetry``)
  keyed by a digest of the snapshot path — rendered by
  ``python -m tpusnap trace --restore <path>``.

Forward progress is detected by OBSERVATION, not by hot-path hooks: the
pump compares successive ``live_snapshot`` signatures, so the take's
pipeline pays nothing beyond the op-token bookkeeping the spans already
do. Clocks are injectable throughout (``clock``/``wall_clock``) so the
throttle/watchdog unit tests run on a fake clock with zero sleeps.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from .knobs import (
    get_job_id,
    get_heartbeat_interval_s,
    get_stall_deadline_s,
    get_telemetry_dir,
)

logger = logging.getLogger(__name__)

from .io_types import PROGRESS_DIR  # canonical sidecar path (io_types)

# Wall-clock seam: record timestamps only; every duration/throttle
# computation here runs on the injectable monotonic ``clock`` — direct
# wall-clock calls are lint-forbidden in this file
# (tests/test_knob_docs.py); only this bare reference is allowed.
_wall = time.time

# Keep-alive: with NO observable change, a record is still re-published
# every this-many intervals so `watch` can distinguish "idle but alive"
# from "process gone" (record timestamp goes stale).
_KEEPALIVE_INTERVALS = 10


def progress_rank_path(rank: int) -> str:
    """Snapshot-relative path of one rank's heartbeat file."""
    return f"{PROGRESS_DIR}/rank_{rank}.json"


def _fmt_short_bytes(n) -> str:
    """Compact byte figure for the watch table's at-risk column."""
    n = float(n)
    for unit in ("B", "K", "M", "G", "T"):
        if n < 1024 or unit == "T":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}T"


def local_root_of(path: str) -> Optional[str]:
    """The local directory a snapshot URL writes into, or None for
    non-local backends (heartbeat files and ``watch`` are local-fs
    only; the KV heartbeat covers the rest)."""
    from urllib.parse import urlsplit

    # Write-back tier URLs: the LOCAL tier is where the heartbeat (and
    # every other) sidecar lives — watch tails it directly.
    try:
        from .tiering import parse_tier_url

        spec = parse_tier_url(path)
        if spec is not None:
            return spec.local_dir
    except ValueError:
        return None
    u = urlsplit(path)
    scheme = u.scheme
    if scheme.startswith("chaos+"):
        scheme = scheme[len("chaos+") :]
    if scheme in ("", "file", "fs"):
        root = u.path if u.scheme else path
        return root or path
    return None


def read_progress_records(root: str) -> List[Dict[str, Any]]:
    """All parseable per-rank heartbeat records under ``root``'s
    progress dir, sorted by rank. Tolerant of torn/absent files (the
    publisher renames atomically, but the dir may not exist yet)."""
    out = []
    pdir = os.path.join(root, PROGRESS_DIR)
    try:
        names = os.listdir(pdir)
    except OSError:
        return out
    for name in sorted(names):
        if not (name.startswith("rank_") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(pdir, name), "r") as f:
                rec = json.load(f)
            if isinstance(rec, dict):
                out.append(rec)
        except Exception:
            continue
    return sorted(out, key=lambda r: r.get("rank", 0))


# ------------------------------------------------------ ProgressMonitor


class ProgressMonitor:
    """Heartbeat pump + stall watchdog for one take.

    One instance per telemetry-enabled take; ``thread=False`` plus an
    injected ``clock`` turns it into a pure state machine for tests
    (drive it with :meth:`tick`)."""

    def __init__(
        self,
        tele,
        rank: int,
        world_size: int,
        take_id: str,
        kv=None,
        local_dir: Optional[str] = None,
        attributions: Optional[List[Callable[[], Optional[List[int]]]]] = None,
        interval_s: Optional[float] = None,
        stall_deadline_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        wall_clock: Callable[[], float] = time.time,
        thread: bool = True,
    ) -> None:
        self.tele = tele
        self.rank = rank
        self.world_size = world_size
        self.take_id = take_id
        self.kv = kv
        self.local_dir = local_dir
        self.interval_s = (
            interval_s if interval_s is not None else get_heartbeat_interval_s()
        )
        self.stall_deadline_s = (
            stall_deadline_s
            if stall_deadline_s is not None
            else get_stall_deadline_s()
        )
        self._attributions = list(attributions or [])
        # Piggyback hooks run once per tick with the freshly built
        # progress record (or None when the throttle skipped building
        # one) — the flight recorder's flush and the SLO tracker's
        # publisher ride here so their cadence shares this pump thread
        # instead of owning another.
        self._tick_hooks: List[Callable[[Optional[Dict[str, Any]]], None]] = []
        # Optional small-dict provider folded into every published
        # record under "slo" (tpusnap.slo): time-since-commit and
        # data-at-risk ride the same heartbeat `watch` and the fleet
        # fold already read.
        self._slo_provider: Optional[Callable[[], Optional[Dict[str, Any]]]] = (
            None
        )
        # Liveness probe (tpusnap.liveness): "which peer ranks' leases
        # have expired?" — folded into stall episodes (dead vs slow
        # rank) and the published heartbeat record's dead_ranks field.
        self._liveness_probe: Optional[
            Callable[[], Optional[List[int]]]
        ] = None
        # Departure probe: "which peer ranks announced a graceful
        # LEAVE?" — rendered LEFT (elastic scale-down), never DEAD.
        self._left_probe: Optional[
            Callable[[], Optional[List[int]]]
        ] = None
        self._clock = clock
        self._wall = wall_clock
        # Job identity on every published record (cached once: the
        # host-pid default shells out to gethostname, not a per-tick
        # cost worth paying).
        try:
            self.job_id = get_job_id()
        except Exception:
            self.job_id = "job"
        self._state = "running"
        self._bytes_planned = 0
        self._start_t = clock()
        self._last_sig: Optional[tuple] = None
        self._last_advance = self._start_t
        self._stall_warned = False
        self._last_pub_t: Optional[float] = None
        self._last_pub_sig: Optional[tuple] = None
        self._last_rate_point = (self._start_t, 0)
        self._mbps = 0.0
        self.published = 0  # publish count (tests assert the throttle)
        self._stopped = False
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if thread:
            self._thread = threading.Thread(
                target=self._run, name="tpusnap-progress", daemon=True
            )
            self._thread.start()

    # --- wiring ---------------------------------------------------------

    def set_bytes_planned(self, nbytes: int) -> None:
        self._bytes_planned = int(nbytes)

    def add_attribution(
        self, fn: Callable[[], Optional[List[int]]]
    ) -> None:
        """Register a callable the watchdog asks "which ranks are we
        waiting on?" when a stall fires (first non-empty answer wins)."""
        self._attributions.append(fn)

    def add_tick_hook(
        self, fn: Callable[[Optional[Dict[str, Any]]], None]
    ) -> None:
        """Register a per-tick piggyback (see ``_tick_hooks``).
        Exceptions are swallowed per hook — the pump must survive any
        subscriber."""
        self._tick_hooks.append(fn)

    def set_slo_provider(
        self, fn: Callable[[], Optional[Dict[str, Any]]]
    ) -> None:
        """Register the SLO field provider (see ``_slo_provider``).
        Exceptions are swallowed — exposure accounting must never fail
        a heartbeat."""
        self._slo_provider = fn

    def set_liveness_probe(
        self, fn: Callable[[], Optional[List[int]]]
    ) -> None:
        """Register the lease-staleness probe (see ``_liveness_probe``).
        Best-effort like every observability hook."""
        self._liveness_probe = fn

    def set_left_probe(
        self, fn: Callable[[], Optional[List[int]]]
    ) -> None:
        """Register the graceful-departure probe (see ``_left_probe``).
        Best-effort like every observability hook."""
        self._left_probe = fn

    def _probe_dead_ranks(self) -> Optional[List[int]]:
        if self._liveness_probe is None:
            return None
        try:
            return self._liveness_probe()
        except Exception:
            return None

    def _probe_left_ranks(self) -> Optional[List[int]]:
        if self._left_probe is None:
            return None
        try:
            return self._left_probe()
        except Exception:
            return None

    # --- the pump -------------------------------------------------------

    def _run(self) -> None:
        # First beat immediately: `watch` sees the take the moment it
        # starts, not one interval later.
        try:
            self.tick(force_publish=True)
        except Exception:
            pass
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # Best-effort, always: a telemetry bug must never take
                # down the pump thread mid-take (or worse, the take).
                logger.debug("progress tick failed", exc_info=True)

    def tick(self, now: Optional[float] = None, force_publish: bool = False) -> None:
        """One pump iteration: advance detection → stall check →
        throttled publish. Public so fake-clock tests drive it."""
        now = self._clock() if now is None else now
        snap = self.tele.live_snapshot()
        sig = (
            snap["phase"],
            tuple(name for _thread, name in snap["ops"]),
            snap["marks"],
            tuple(sorted(snap["counters"].items())),
        )
        if sig != self._last_sig:
            self._last_sig = sig
            self._last_advance = now
            self._stall_warned = False
        else:
            self._check_stall(now, snap)
        record = self._maybe_publish(now, snap, force=force_publish)
        for fn in self._tick_hooks:
            try:
                fn(record)
            except Exception:
                logger.debug("progress tick hook failed", exc_info=True)

    def _check_stall(self, now: float, snap: Dict[str, Any]) -> None:
        if self._stall_warned or self._state != "running":
            return
        stalled_s = now - self._last_advance
        if stalled_s < self.stall_deadline_s:
            return
        ops = snap["ops"]
        if not ops:
            return  # between ops — "no forward progress INSIDE a named op"
        op = ops[0][1]  # oldest in-flight op = what we are blocked on
        missing: Optional[List[int]] = None
        for fn in self._attributions:
            try:
                got = fn()
            except Exception:
                got = None
            if got:
                missing = got
                break
        self._stall_warned = True  # one WARNING per stall episode
        # Surface the episode to the take summary/rollup, the export
        # sinks (tpusnap_stall_episodes_total) and the cross-run
        # history: an explicit rec so the counter lands in THIS take
        # even when a newer take replaced the global recorder.
        try:
            from . import telemetry

            telemetry.incr("progress.stall_episodes", rec=self.tele)
        except Exception:
            pass
        # Lease staleness splits the episode verdict: a stall with a
        # DEAD peer is a rank failure in progress (liveness will fail
        # the wait within ~2xTTL), not merely a slow rank.
        dead = self._probe_dead_ranks()
        info = {
            "rank": self.rank,
            "take_id": self.take_id,
            "op": op,
            "ops": [name for _thread, name in ops],
            "phase": snap["phase"],
            "stalled_s": round(stalled_s, 1),
            "missing_ranks": missing,
            "dead_ranks": dead,
        }
        try:
            from . import flight

            flight.record(
                "stall",
                op=op,
                stalled_s=round(stalled_s, 1),
                phase=snap["phase"],
                missing_ranks=missing,
                dead_ranks=dead,
            )
        except Exception:
            logger.debug("flight stall record failed", exc_info=True)
        logger.warning(
            "tpusnap stall: rank %d made no forward progress for %.1fs "
            "inside op %r (last completed phase %r)%s%s",
            self.rank,
            stalled_s,
            op,
            snap["phase"],
            (
                f"; ranks not arrived: {missing}"
                if missing
                else "; no barrier attribution available"
            ),
            (
                f"; DEAD rank(s) (lease expired): {dead}"
                if dead
                else ""
            ),
            extra={"tpusnap_stall": info},
        )

    # --- publishing -----------------------------------------------------

    def _maybe_publish(
        self, now: float, snap: Dict[str, Any], force: bool = False
    ) -> Optional[Dict[str, Any]]:
        due = (
            self._last_pub_t is None
            or now - self._last_pub_t >= self.interval_s
        )
        changed = self._last_pub_sig != self._last_sig
        keepalive = (
            self._last_pub_t is not None
            and now - self._last_pub_t
            >= _KEEPALIVE_INTERVALS * self.interval_s
        )
        if not force and not (due and changed) and not keepalive:
            return None
        record = self._record(now, snap)
        self._last_pub_t = now
        self._last_pub_sig = self._last_sig
        self.published += 1
        payload = json.dumps(record)
        if self.local_dir is not None:
            try:
                self._write_local(payload)
            except Exception:
                logger.debug("heartbeat file write failed", exc_info=True)
        if self.kv is not None:
            try:
                self.kv.set(self._kv_key(self.rank), payload.encode("utf-8"))
            except Exception:
                logger.debug("heartbeat KV publish failed", exc_info=True)
        return record

    def _kv_key(self, rank: int) -> str:
        return f"tpusnap_progress/{self.take_id}/{rank}"

    def _write_local(self, payload: str) -> None:
        pdir = os.path.join(self.local_dir, PROGRESS_DIR)
        os.makedirs(pdir, exist_ok=True)
        path = os.path.join(pdir, f"rank_{self.rank}.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)

    def _record(self, now: float, snap: Dict[str, Any]) -> Dict[str, Any]:
        counters = snap["counters"]
        written = counters.get("storage.bytes_written", 0)
        staged = counters.get("scheduler.bytes_staged", 0)
        planned = self._bytes_planned
        if self._state == "committed":
            percent: Optional[float] = 100.0
            staged_percent: Optional[float] = 100.0
        elif planned > 0:
            percent = round(min(100.0, 100.0 * written / planned), 1)
            # Pipelined async takes stage residual windows on the
            # background drain — surface that leg's own progress so a
            # watcher can tell "still cloning" from "still writing".
            staged_percent = round(min(100.0, 100.0 * staged / planned), 1)
        else:
            percent = None
            staged_percent = None
        prev_t, prev_b = self._last_rate_point
        if now - prev_t >= self.interval_s:
            self._mbps = round((written - prev_b) / max(now - prev_t, 1e-9) / 1e6, 1)
            self._last_rate_point = (now, written)
        ops = snap["ops"]
        rec = {
            "v": 1,
            "rank": self.rank,
            "world_size": self.world_size,
            "job_id": self.job_id,
            "take_id": self.take_id,
            "state": self._state,
            "phase": snap["phase"],
            "op": ops[0][1] if ops else None,
            "ops": [name for _thread, name in ops],
            "bytes_planned": planned,
            "bytes_written": written,
            "bytes_staged": staged,
            "staged_percent": staged_percent,
            "percent": percent,
            "mbps": self._mbps,
            "beat_age_s": round(now - self._last_advance, 2),
            "elapsed_s": round(now - self._start_t, 2),
            "ts": self._wall(),
        }
        # In-take roofline probes (TPUSNAP_PROBE=1): the latest measured
        # ceiling, so `watch` can render live MB/s as a fraction of the
        # achievable instead of a bare number.
        if snap.get("probe_write_gbps"):
            rec["probe_write_gbps"] = snap["probe_write_gbps"]
        # Peer ranks this rank's liveness monitor has declared dead —
        # `watch` flags them so an operator sees "rank 2 died" on the
        # survivors' rows, not just a stalled percentage.
        dead = self._probe_dead_ranks()
        left = self._probe_left_ranks()
        if left:
            rec["left_ranks"] = left
            # A rank that announced departure is not dead — never let
            # the two flags contradict on one record.
            dead = [r for r in (dead or []) if r not in left] or None
        if dead:
            rec["dead_ranks"] = dead
        if self._slo_provider is not None:
            try:
                slo = self._slo_provider()
                if slo:
                    rec["slo"] = slo
            except Exception:
                logger.debug("slo provider failed", exc_info=True)
        return rec

    # --- lifecycle ------------------------------------------------------

    def finish(self, state: str = "committed") -> None:
        """Stop the pump, publish the final record (``committed`` forces
        100%), then release this rank's KV key — in that order, so no
        in-flight pump tick can recreate a key after its delete. The
        final record survives in the local heartbeat FILE; the KV copy
        is live-monitoring state and is always released (every rank
        deletes its own key so a peer's late publish cannot race rank
        0's sweep back into existence; rank 0 of a committed take also
        sweeps the prefix, which covers SIGKILLed peers that never
        reached finish). Idempotent, never raises."""
        if self._stopped:
            return
        self._state = state
        self.stop()
        try:
            self.tick(force_publish=True)
        except Exception:
            pass
        if self.kv is not None:
            try:
                self.kv.delete_prefix(self._kv_key(self.rank))
                if state == "committed" and self.rank == 0:
                    self.kv.delete_prefix(f"tpusnap_progress/{self.take_id}/")
            except Exception:
                pass

    def stop(self) -> None:
        """Stop the pump thread without a final publish. Idempotent."""
        self._stopped = True
        self._stop_evt.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)


def start_take_monitor(tele, comm, take_id: str, path: str) -> ProgressMonitor:
    """Wire a :class:`ProgressMonitor` for a take: KV target (multi-
    process only), local heartbeat dir (local-fs destinations only),
    and the communicator's barrier attribution."""
    kv = None
    if comm.world_size > 1:
        try:
            from .dist_store import CoordinationKVStore

            kv = CoordinationKVStore()
        except Exception:
            kv = None
    return ProgressMonitor(
        tele,
        rank=comm.rank,
        world_size=comm.world_size,
        take_id=take_id,
        kv=kv,
        local_dir=local_root_of(path),
        attributions=[comm.barrier_missing_ranks],
    )


# ------------------------------------------------------- restore traces


def _path_digest(path: str) -> str:
    # Every spelling of the same local destination (plain path,
    # file://, fs://, chaos+fs://, trailing slash) must digest
    # identically, or `trace --restore <path>` misses traces a
    # differently-spelled restore persisted.
    norm = path.rstrip("/")
    root = local_root_of(norm)
    if root is not None:
        norm = os.path.abspath(root)
    return hashlib.sha1(norm.encode("utf-8")).hexdigest()[:12]


def restore_trace_dir(snapshot_path: str) -> str:
    """Local directory holding the LAST restore's per-rank traces for
    ``snapshot_path`` (the snapshot itself is immutable, so restore
    telemetry cannot ride inside it the way take traces do)."""
    return os.path.join(
        get_telemetry_dir(), f"restore_{_path_digest(snapshot_path)}"
    )


# Run-scoped trace files kept per digest+rank before the oldest are
# reaped. Back-to-back restores of the same snapshot used to clobber
# each other's rank_<k>.json; now each run writes its own suffixed file
# and only the `latest` pointer moves.
RESTORE_TRACE_KEEP = 8

_RANK_LATEST_RE = re.compile(r"^rank_(\d+)\.json$")
_RANK_RUN_RE = re.compile(r"^rank_(\d+)\.[0-9a-f]+\.json$")


def persist_restore_trace(tele, snapshot_path: str) -> str:
    """Write one rank's restore trace
    (``{rank, path, summary, traceEvents}``) under the local trace dir.
    Each restore run writes its own ``rank_<k>.<run>.json`` (atomic
    temp+rename) and repoints the ``rank_<k>.json`` latest-symlink at
    it, so back-to-back restores of the same snapshot no longer clobber
    each other while ``trace --restore`` keeps reading the latest run
    through the unchanged name. Retention is bounded to
    :data:`RESTORE_TRACE_KEEP` runs per digest+rank. Returns the
    run-scoped file path."""
    tdir = restore_trace_dir(snapshot_path)
    os.makedirs(tdir, exist_ok=True)
    run_id = uuid.uuid4().hex[:8]
    out = os.path.join(tdir, f"rank_{tele.rank}.{run_id}.json")
    doc = {
        "rank": tele.rank,
        "path": snapshot_path,
        "kind": "restore",
        "run_id": run_id,
        "summary": tele.summary(),
        "traceEvents": tele.chrome_trace_events(),
    }
    tmp = f"{out}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out)
    latest = os.path.join(tdir, f"rank_{tele.rank}.json")
    try:
        # Atomic repoint: build the new symlink aside, rename over the
        # old one (rename replaces symlinks like any other entry).
        link_tmp = f"{latest}.lnk.{os.getpid()}"
        try:
            os.unlink(link_tmp)
        except OSError:
            pass
        os.symlink(os.path.basename(out), link_tmp)
        os.replace(link_tmp, latest)
    except OSError:
        # Symlink-hostile filesystem: fall back to the pre-fix overwrite
        # semantics for the latest pointer (run files are still kept).
        tmp2 = f"{latest}.tmp.{os.getpid()}"
        with open(tmp2, "w") as f:
            json.dump(doc, f)
        os.replace(tmp2, latest)
    _reap_restore_traces(tdir, tele.rank)
    return out


def _reap_restore_traces(tdir: str, rank: int) -> None:
    """Drop this rank's oldest run-scoped trace files beyond the
    retention bound (best-effort; the latest-symlink's target is never
    younger than the survivors, so it stays valid)."""
    pat = re.compile(rf"^rank_{rank}\.[0-9a-f]+\.json$")
    try:
        runs = [n for n in os.listdir(tdir) if pat.match(n)]
    except OSError:
        return
    if len(runs) <= RESTORE_TRACE_KEEP:
        return
    dated = []
    for name in runs:
        try:
            dated.append((os.stat(os.path.join(tdir, name)).st_mtime, name))
        except OSError:
            continue
    dated.sort()
    for _, name in dated[: max(len(dated) - RESTORE_TRACE_KEEP, 0)]:
        try:
            os.unlink(os.path.join(tdir, name))
        except OSError:
            pass


def load_restore_traces(snapshot_path: str) -> Dict[int, Dict[str, Any]]:
    """Per-rank restore trace docs persisted on THIS machine for
    ``snapshot_path`` (restore issues no collectives, so there is no
    cross-host gather — each host holds its own ranks' traces). Reads
    each rank's ``rank_<k>.json`` latest pointer; when that pointer is
    missing or dangling (a reaped target, a partially-synced dir, an
    older build that never wrote one) the rank falls back to its NEWEST
    run-scoped ``rank_<k>.<run>.json`` by mtime instead of silently
    dropping out of the report."""
    tdir = restore_trace_dir(snapshot_path)
    out: Dict[int, Dict[str, Any]] = {}
    try:
        names = os.listdir(tdir)
    except OSError:
        return out
    run_files: Dict[int, List[str]] = {}
    for name in sorted(names):
        m = _RANK_RUN_RE.match(name)
        if m:
            run_files.setdefault(int(m.group(1)), []).append(name)
            continue
        if not _RANK_LATEST_RE.match(name):
            continue
        try:
            with open(os.path.join(tdir, name), "r") as f:
                doc = json.load(f)
            out[int(doc["rank"])] = doc
        except Exception:
            continue
    for rank, runs in run_files.items():
        if rank in out:
            continue
        dated = []
        for name in runs:
            try:
                dated.append(
                    (os.stat(os.path.join(tdir, name)).st_mtime, name)
                )
            except OSError:
                continue
        for _, name in sorted(dated, reverse=True):
            try:
                with open(os.path.join(tdir, name), "r") as f:
                    doc = json.load(f)
                out[int(doc["rank"])] = doc
                break
            except Exception:
                continue
    return out


# ------------------------------------------------------------ watch UI


def render_watch_table(
    records: List[Dict[str, Any]],
    committed: bool,
    stall_flag_s: float,
    now: Optional[float] = None,
) -> str:
    """One frame of the ``tpusnap watch`` table. ``stall_flag_s`` flags
    ranks whose heartbeat has not advanced for that long (record
    beat_age plus how stale the record itself is). The ``at-risk`` /
    ``commit`` columns (from the record's ``slo`` sub-dict) show
    EXPOSURE — bytes a crash right now would lose, and how long since
    the last committed take — alongside progress."""
    now = _wall() if now is None else now
    lines = [
        f"{'rank':>4}  {'state':<10} {'phase':<16} {'op':<20} "
        f"{'%':>6} {'MB/s':>8} {'at-risk':>8} {'commit':>8} {'beat':>7}"
    ]
    for r in records:
        staleness = max(0.0, now - r.get("ts", now))
        age = r.get("beat_age_s", 0.0) + staleness
        pct = r.get("percent")
        flag = ""
        if r.get("state") == "running" and age > stall_flag_s:
            flag = "  ** STALLED?"
        dead = r.get("dead_ranks")
        if dead:
            flag = f"  ** PEER DEAD {dead}" + flag
        left = r.get("left_ranks")
        if left:
            flag = f"  ** PEER LEFT {left}" + flag
        # With in-take probes on, express live MB/s against the latest
        # self-measured ceiling — "600 MB/s (31% of ceiling)" answers
        # "is that slow?" without leaving the table.
        ceiling = r.get("probe_write_gbps")
        mbps = r.get("mbps", 0.0)
        if ceiling and mbps:
            flag = f"  ({min(mbps / (ceiling * 1e3), 9.99):.0%} of ceiling)" + flag
        slo = r.get("slo") or {}
        at_risk = slo.get("data_at_risk_bytes")
        at_risk_str = _fmt_short_bytes(at_risk) if at_risk is not None else "-"
        # Time since the last COMMIT advances even while the record is
        # stale — exposure grows in real time, unlike progress.
        rpo = slo.get("rpo_s")
        commit_str = f"{rpo + staleness:.0f}s" if rpo is not None else "-"
        lines.append(
            f"{r.get('rank', '?'):>4}  {r.get('state', '?'):<10} "
            f"{(r.get('phase') or '-'):<16.16} {(r.get('op') or '-'):<20.20} "
            f"{(f'{pct:.1f}' if pct is not None else '-'):>6} "
            f"{mbps:>8.1f} {at_risk_str:>8} {commit_str:>8} {age:>6.1f}s{flag}"
        )
    if not records:
        lines.append("(no heartbeat records yet)")
    lines.append(
        "metadata: committed"
        if committed
        else "metadata: not yet written (take in flight)"
    )
    return "\n".join(lines)
