"""Snapshot — the user-facing save/restore/random-access API.

TPU-native counterpart of /root/reference/torchsnapshot/snapshot.py.
Preserved semantics (call stacks in SURVEY.md §3):

- ``take``: coalesce path/replicated globs across ranks → per-key
  ``state_dict()`` in a globally agreed order (with barriers so statefuls
  that run collectives inside ``state_dict`` can't interleave,
  reference :352-368) → flatten → prepare write requests → replicated
  write dedup/partitioning → gather + merge per-rank manifests into a
  global manifest keyed ``rank/logical_path`` (reference :842-853) →
  budget-gated pipelined execution → two-phase commit: rank 0 writes
  ``.snapshot_metadata`` only after every rank finished writing
  (reference :227-234).
- ``async_take``: staging completes before control returns (snapshot is
  consistent); storage I/O + commit happen on a background thread that
  coordinates via a KV-store LinearBarrier — never collectives
  (reference :856-944).
- ``restore``: per-key global order; per-rank manifest view with
  replicated re-expansion and sharded merge; reads scattered/reassembled
  into the target sharding; RNG state restored last (reference :437-481).
- ``read_object``: random access to one object under a memory budget
  (reference :501-594).

TPU-first deltas: replication is **inferred from shardings** — a
fully-replicated multi-process ``jax.Array`` is provably identical on
every rank, so it is deduplicated automatically without the reference's
DDP-module introspection (snapshot.py:791-807); the glob API is kept for
host-side values (numpy arrays, primitives) where no sharding exists.
"""

from __future__ import annotations

import asyncio
import fnmatch
import logging
import threading
import uuid
from typing import Any, Dict, List, Optional, Set

import jax

from .comm import Communicator, get_communicator
from .dist_store import CoordinationKVStore, KVStore, LinearBarrier, MemoryKVStore
from .flatten import flatten, inflate
from .io_preparer import prepare_read, prepare_write
from .io_types import ReadIO, StoragePlugin, WriteIO
from .manifest import (
    Entry,
    Manifest,
    SnapshotMetadata,
    is_container_entry,
    is_replicated,
)
from .manifest_ops import get_manifest_for_rank, handle_sharded_elasticity
from .rng_state import RNGState
from .scheduler import (
    PendingIOWork,
    get_process_memory_budget_bytes,
    sync_execute_read_reqs,
    sync_execute_write_reqs,
)
from .stateful import AppState, Stateful
from .storage_plugin import url_to_storage_plugin_in_event_loop
from .version import __version__

logger = logging.getLogger(__name__)

SNAPSHOT_METADATA_FNAME = ".snapshot_metadata"


class Snapshot:
    def __init__(
        self,
        path: str,
        storage_options: Optional[Dict[str, Any]] = None,
        comm: Optional[Communicator] = None,
    ) -> None:
        self.path = path
        self._storage_options = storage_options
        self._comm = comm
        self._metadata: Optional[SnapshotMetadata] = None

    # ------------------------------------------------------------------ take

    @classmethod
    def take(
        cls,
        path: str,
        app_state: AppState,
        replicated: Optional[List[str]] = None,
        storage_options: Optional[Dict[str, Any]] = None,
        comm: Optional[Communicator] = None,
    ) -> "Snapshot":
        comm = get_communicator(comm)
        event_loop = asyncio.new_event_loop()
        try:
            path, replicated = _coalesce_path_and_replicated(
                path, replicated or [], comm
            )
            storage = url_to_storage_plugin_in_event_loop(
                path, event_loop, storage_options
            )
            pending_io_work, metadata = _take_impl(
                app_state=app_state,
                storage=storage,
                comm=comm,
                replicated=replicated,
                event_loop=event_loop,
                is_async_snapshot=False,
            )
            pending_io_work.sync_complete(event_loop)
            comm.barrier()
            if comm.rank == 0:
                _write_metadata(storage, metadata, event_loop)
            comm.barrier()
            storage.sync_close(event_loop)
        finally:
            event_loop.close()
        snapshot = cls(path, storage_options, comm)
        snapshot._metadata = metadata
        return snapshot

    @classmethod
    def async_take(
        cls,
        path: str,
        app_state: AppState,
        replicated: Optional[List[str]] = None,
        storage_options: Optional[Dict[str, Any]] = None,
        comm: Optional[Communicator] = None,
    ) -> "PendingSnapshot":
        comm = get_communicator(comm)
        event_loop = asyncio.new_event_loop()
        path, replicated = _coalesce_path_and_replicated(path, replicated or [], comm)
        storage = url_to_storage_plugin_in_event_loop(path, event_loop, storage_options)
        pending_io_work, metadata = _take_impl(
            app_state=app_state,
            storage=storage,
            comm=comm,
            replicated=replicated,
            event_loop=event_loop,
            is_async_snapshot=True,
        )
        # Control returns to training here: staging is complete, the
        # snapshot content is frozen; only storage I/O remains.
        return PendingSnapshot(
            path=path,
            pending_io_work=pending_io_work,
            metadata=metadata,
            storage=storage,
            comm=comm,
            event_loop=event_loop,
            storage_options=storage_options,
        )

    # --------------------------------------------------------------- restore

    def restore(self, app_state: AppState) -> None:
        comm = get_communicator(self._comm)
        _validate_app_state(app_state)
        event_loop = asyncio.new_event_loop()
        try:
            storage = url_to_storage_plugin_in_event_loop(
                self.path, event_loop, self._storage_options
            )
            metadata = self._get_metadata(storage, event_loop)
            memory_budget = get_process_memory_budget_bytes(comm)

            global_keys = _gather_keys(comm, sorted(app_state.keys()))
            # RNG state is restored last so that loading other statefuls
            # cannot perturb it (reference snapshot.py:473-481).
            rng_keys = [
                k for k in global_keys if isinstance(app_state.get(k), RNGState)
            ]
            for key in [k for k in global_keys if k not in rng_keys] + rng_keys:
                comm.barrier()
                stateful = app_state.get(key)
                if stateful is None:
                    continue
                _load_stateful(
                    stateful=stateful,
                    key=key,
                    metadata=metadata,
                    rank=comm.rank,
                    storage=storage,
                    memory_budget=memory_budget,
                    event_loop=event_loop,
                )
            storage.sync_close(event_loop)
        finally:
            event_loop.close()

    # ----------------------------------------------------------- random access

    def read_object(
        self,
        path: str,
        obj_out: Any = None,
        memory_budget_bytes: Optional[int] = None,
    ) -> Any:
        """Read a single object by manifest path ``"<rank>/<logical_path>"``
        without restoring anything else (reference snapshot.py:501-594)."""
        comm = get_communicator(self._comm)
        rank_str, _, logical_path = path.partition("/")
        if not rank_str.isdigit() or not logical_path:
            raise ValueError(
                f"Invalid manifest path {path!r} (expected '<rank>/<path>')"
            )
        event_loop = asyncio.new_event_loop()
        try:
            storage = url_to_storage_plugin_in_event_loop(
                self.path, event_loop, self._storage_options
            )
            metadata = self._get_metadata(storage, event_loop)
            local_manifest = get_manifest_for_rank(metadata, int(rank_str))
            if logical_path not in local_manifest:
                raise KeyError(f"{path!r} not found in snapshot manifest")
            entry = local_manifest[logical_path]
            if is_container_entry(entry):
                raise ValueError(
                    f"{path!r} is a container; read its leaves individually"
                )
            read_reqs, fut = prepare_read(
                entry,
                obj_out,
                buffer_size_limit_bytes=memory_budget_bytes,
                logical_path=logical_path,
            )
            budget = memory_budget_bytes or get_process_memory_budget_bytes(comm)
            sync_execute_read_reqs(read_reqs, storage, budget, comm.rank, event_loop)
            storage.sync_close(event_loop)
            return fut.obj
        finally:
            event_loop.close()

    # -------------------------------------------------------------- metadata

    @property
    def metadata(self) -> SnapshotMetadata:
        if self._metadata is None:
            event_loop = asyncio.new_event_loop()
            try:
                storage = url_to_storage_plugin_in_event_loop(
                    self.path, event_loop, self._storage_options
                )
                self._metadata = self._get_metadata(storage, event_loop)
                storage.sync_close(event_loop)
            finally:
                event_loop.close()
        return self._metadata

    def get_manifest(self) -> Manifest:
        return dict(self.metadata.manifest)

    def _get_metadata(
        self, storage: StoragePlugin, event_loop: asyncio.AbstractEventLoop
    ) -> SnapshotMetadata:
        if self._metadata is not None:
            return self._metadata
        read_io = ReadIO(path=SNAPSHOT_METADATA_FNAME)
        try:
            storage.sync_read(read_io, event_loop)
        except Exception as e:
            raise RuntimeError(
                f"Failed to read snapshot metadata at "
                f"{self.path}/{SNAPSHOT_METADATA_FNAME} — not a snapshot, or "
                f"an aborted/incomplete one"
            ) from e
        try:
            self._metadata = SnapshotMetadata.from_yaml(
                read_io.buf.getvalue().decode("utf-8")
            )
        except Exception as e:
            raise RuntimeError(
                f"Corrupt snapshot metadata at "
                f"{self.path}/{SNAPSHOT_METADATA_FNAME}"
            ) from e
        return self._metadata


# ---------------------------------------------------------------- internals


def _validate_app_state(app_state: AppState) -> None:
    for key, stateful in app_state.items():
        if not (hasattr(stateful, "state_dict") and hasattr(stateful, "load_state_dict")):
            raise TypeError(
                f"app_state[{key!r}] ({type(stateful).__name__}) is not "
                "Stateful: it must define state_dict()/load_state_dict()"
            )


def _coalesce_path_and_replicated(
    path: str, replicated: List[str], comm: Communicator
):
    """Rank 0's path wins (with a warning on divergence); replicated globs
    are intersected across ranks (reference snapshot.py:752-812)."""
    if comm.world_size == 1:
        return path, list(replicated)
    rank0_path = comm.broadcast_object(path, src=0)
    if rank0_path != path:
        logger.warning(
            "Rank %d's snapshot path %r differs from rank 0's %r; using rank 0's",
            comm.rank,
            path,
            rank0_path,
        )
    all_globs = comm.all_gather_object(sorted(set(replicated)))
    common = set(all_globs[0])
    for globs in all_globs[1:]:
        common &= set(globs)
    dropped = set(replicated) - common
    if dropped:
        logger.warning(
            "Replicated globs %s were not specified on every rank; ignoring",
            sorted(dropped),
        )
    return rank0_path, sorted(common)


def _gather_keys(comm: Communicator, local_keys: List[str]) -> List[str]:
    if comm.world_size == 1:
        return sorted(local_keys)
    gathered = comm.all_gather_object(local_keys)
    merged: Set[str] = set()
    for keys in gathered:
        merged.update(keys)
    return sorted(merged)


def _infer_replicated_leaf(leaf: Any, world_size: int) -> bool:
    """A fully-replicated multi-process jax.Array is identical on every
    rank by construction — dedup its writes automatically."""
    if world_size <= 1 or not isinstance(leaf, jax.Array):
        return False
    return leaf.is_fully_replicated and not leaf.is_fully_addressable


def _calculate_replicated_paths(
    flattened_paths: List[str], replicated_globs: List[str], comm: Communicator
) -> Set[str]:
    """Glob-matched paths present on ALL ranks (reference :605-638)."""
    matched = [
        p
        for p in flattened_paths
        if any(fnmatch.fnmatch(p, g) for g in replicated_globs)
    ]
    if comm.world_size == 1:
        return set(matched)
    gathered = comm.all_gather_object(sorted(matched))
    common = set(gathered[0])
    for paths in gathered[1:]:
        common &= set(paths)
    return common


def _take_impl(
    app_state: AppState,
    storage: StoragePlugin,
    comm: Communicator,
    replicated: List[str],
    event_loop: asyncio.AbstractEventLoop,
    is_async_snapshot: bool,
):
    _validate_app_state(app_state)
    rank = comm.rank

    # Capture RNG state on entry; other statefuls' state_dict() calls may
    # consume RNG, and take() must be invariant (reference :332-374).
    rng_captured: Dict[str, Dict[str, Any]] = {
        k: v.state_dict() for k, v in app_state.items() if isinstance(v, RNGState)
    }

    global_keys = _gather_keys(comm, sorted(app_state.keys()))
    manifest: Manifest = {}
    flattened_all: Dict[str, Any] = {}
    for key in global_keys:
        if comm.world_size > 1:
            # state_dict() may itself run collectives; the barrier keeps
            # different keys' collectives from interleaving (reference :362-368).
            comm.barrier()
        stateful = app_state.get(key)
        if stateful is None:
            continue
        state_dict = rng_captured.get(key) or stateful.state_dict()
        mft, flat = flatten(state_dict, prefix=key)
        manifest.update(mft)
        flattened_all.update(flat)

    # Undo any RNG perturbation caused by gathering state dicts.
    for key, captured in rng_captured.items():
        app_state[key].load_state_dict(captured)

    replicated_paths = _calculate_replicated_paths(
        list(flattened_all.keys()), replicated, comm
    )

    entries: Manifest = dict(manifest)
    write_reqs = []
    replicated_entry_paths: List[str] = []
    for logical_path, leaf in flattened_all.items():
        is_repl = logical_path in replicated_paths or _infer_replicated_leaf(
            leaf, comm.world_size
        )
        entry, reqs = prepare_write(
            obj=leaf,
            logical_path=logical_path,
            rank=rank,
            replicated=is_repl,
            is_async_snapshot=is_async_snapshot,
        )
        entries[logical_path] = entry
        if is_repl and is_replicated(entry):
            replicated_entry_paths.append(logical_path)
        write_reqs.extend(reqs)

    # Replicated write-load partitioning across ranks.
    from .partitioner import partition_write_reqs

    write_reqs = partition_write_reqs(
        entries, write_reqs, replicated_entry_paths, comm
    )

    # Slab-batch small writes.
    from .batcher import batch_write_requests

    entries_list = list(entries.values())
    entries_list, write_reqs = batch_write_requests(entries_list, write_reqs)
    entries = dict(zip(entries.keys(), entries_list))

    memory_budget = get_process_memory_budget_bytes(comm)
    pending_io_work = sync_execute_write_reqs(
        write_reqs, storage, memory_budget, rank, event_loop
    )
    # The manifest is gathered AFTER staging completes (sync_execute
    # returns at staging-complete; storage I/O may still be in flight):
    # stagers record per-blob checksums into their entries at stage time,
    # and those must land in the committed metadata. The reference
    # gathers before scheduling (snapshot.py:842-853) only because its
    # entries are final at prepare time.
    global_manifest = _gather_manifest(entries, comm)
    metadata = SnapshotMetadata(
        version=__version__, world_size=comm.world_size, manifest=global_manifest
    )
    return pending_io_work, metadata


def _gather_manifest(entries: Manifest, comm: Communicator) -> Manifest:
    """All-gather per-rank manifests; key by ``rank/logical_path``;
    consolidate replicated entries onto rank 0, preferring the writer's
    (possibly slab-batched) entry version (reference :842-853,
    partitioner.py:262-303)."""
    from .partitioner import consolidate_replicated_entries

    if comm.world_size == 1:
        per_rank = [entries]
    else:
        per_rank = comm.all_gather_object(entries)
    return consolidate_replicated_entries(per_rank)


def _write_metadata(
    storage: StoragePlugin,
    metadata: SnapshotMetadata,
    event_loop: asyncio.AbstractEventLoop,
) -> None:
    storage.sync_write(
        WriteIO(
            path=SNAPSHOT_METADATA_FNAME,
            buf=metadata.to_yaml().encode("utf-8"),
        ),
        event_loop,
    )


def _load_stateful(
    stateful: Stateful,
    key: str,
    metadata: SnapshotMetadata,
    rank: int,
    storage: StoragePlugin,
    memory_budget: int,
    event_loop: asyncio.AbstractEventLoop,
) -> None:
    local_manifest = get_manifest_for_rank(metadata, rank)
    local_manifest = {
        p: e
        for p, e in local_manifest.items()
        if p == key or p.startswith(key + "/")
    }
    if not local_manifest:
        logger.warning("No entries for key %r in snapshot; skipping", key)
        return

    # The current state_dict provides restore targets (device placement,
    # shardings, in-place numpy buffers).
    target_manifest, target_flattened = flatten(stateful.state_dict(), prefix=key)
    handle_sharded_elasticity(local_manifest, target_flattened)

    read_reqs = []
    futures: Dict[str, Any] = {}
    for logical_path, entry in local_manifest.items():
        if is_container_entry(entry):
            continue
        reqs, fut = prepare_read(
            entry,
            obj_out=target_flattened.get(logical_path),
            logical_path=logical_path,
        )
        read_reqs.extend(reqs)
        futures[logical_path] = fut

    from .batcher import batch_read_requests

    read_reqs = batch_read_requests(read_reqs)
    sync_execute_read_reqs(read_reqs, storage, memory_budget, rank, event_loop)

    flattened = {p: fut.obj for p, fut in futures.items()}
    container_manifest = {
        p: e for p, e in local_manifest.items() if is_container_entry(e)
    }
    restored = inflate(container_manifest, flattened, prefix=key)
    stateful.load_state_dict(restored)


# ------------------------------------------------------------- async commit


class PendingSnapshot:
    """Handle for an in-flight async snapshot (reference snapshot.py:856-944).

    A background thread drains storage I/O, then synchronizes the commit
    through a KV-store LinearBarrier — NO collectives are allowed off the
    main thread (reference :902). If any rank fails, the error poisons
    the barrier, ``.snapshot_metadata`` is never written, and ``wait()``
    re-raises on every rank.
    """

    BARRIER_TIMEOUT_SEC = 1800.0  # reference snapshot.py:857

    def __init__(
        self,
        path: str,
        pending_io_work: PendingIOWork,
        metadata: SnapshotMetadata,
        storage: StoragePlugin,
        comm: Communicator,
        event_loop: asyncio.AbstractEventLoop,
        storage_options: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.path = path
        self._pending_io_work = pending_io_work
        self._metadata = metadata
        self._storage = storage
        self._comm = comm
        self._event_loop = event_loop
        self._storage_options = storage_options
        self._exc: Optional[BaseException] = None
        self._done = threading.Event()
        self._snapshot: Optional[Snapshot] = None

        # Barrier identity must be agreed on the MAIN thread (this may
        # broadcast); the background thread then only touches the KV store.
        barrier_prefix = f"tpusnap_commit/{uuid.uuid4().hex}"
        barrier_prefix = comm.broadcast_object(barrier_prefix, src=0)
        self._barrier = LinearBarrier(
            store=_get_kv_store(comm),
            prefix=barrier_prefix,
            rank=comm.rank,
            world_size=comm.world_size,
            timeout_sec=self.BARRIER_TIMEOUT_SEC,
        )
        self._thread = threading.Thread(
            target=self._complete_snapshot, name="tpusnap-commit", daemon=True
        )
        self._thread.start()

    def _complete_snapshot(self) -> None:
        try:
            self._pending_io_work.sync_complete(self._event_loop)
            self._barrier.arrive()
            if self._comm.rank == 0:
                _write_metadata(self._storage, self._metadata, self._event_loop)
            self._barrier.depart()
            snapshot = Snapshot(self.path, self._storage_options, self._comm)
            snapshot._metadata = self._metadata
            self._snapshot = snapshot
        except BaseException as e:  # noqa: B902
            self._exc = e
            try:
                self._barrier.report_error(e)
            except Exception:
                pass
        finally:
            try:
                self._storage.sync_close(self._event_loop)
                self._event_loop.close()
            except Exception:
                pass
            self._done.set()

    def wait(self) -> Snapshot:
        self._thread.join()
        if self._exc is not None:
            raise self._exc
        assert self._snapshot is not None
        return self._snapshot

    def done(self) -> bool:
        return self._done.is_set()


def _get_kv_store(comm: Communicator) -> KVStore:
    if comm.world_size == 1:
        return MemoryKVStore()
    return CoordinationKVStore()
