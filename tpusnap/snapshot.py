"""Snapshot — the user-facing save/restore/random-access API.

TPU-native counterpart of /root/reference/torchsnapshot/snapshot.py.
Preserved semantics (call stacks in SURVEY.md §3):

- ``take``: coalesce path/replicated globs across ranks → per-key
  ``state_dict()`` in a globally agreed order (with barriers so statefuls
  that run collectives inside ``state_dict`` can't interleave,
  reference :352-368) → flatten → prepare write requests → replicated
  write dedup/partitioning → gather + merge per-rank manifests into a
  global manifest keyed ``rank/logical_path`` (reference :842-853) →
  budget-gated pipelined execution → two-phase commit: rank 0 writes
  ``.snapshot_metadata`` only after every rank finished writing
  (reference :227-234).
- ``async_take``: control returns at FIRST-WINDOW-STAGED — a
  memory-budget-bounded window of write requests is staged on the
  calling thread (everything, when the state fits
  TPUSNAP_ASYNC_STAGE_WINDOW_BYTES — then the pre-pipeline
  staging-complete semantics hold exactly); residual staging windows,
  storage I/O and the commit happen on a background thread that
  coordinates via a KV-store LinearBarrier — never collectives
  (reference :856-944). ``PendingSnapshot.wait_staged()`` is the
  staging-complete rendezvous for callers that mutate host-aliasing
  state in place.
- ``restore``: per-key global order; per-rank manifest view with
  replicated re-expansion and sharded merge; reads scattered/reassembled
  into the target sharding; RNG state restored last (reference :437-481).
- ``read_object``: random access to one object under a memory budget
  (reference :501-594).

TPU-first deltas: replication is **inferred from shardings** — a
fully-replicated multi-process ``jax.Array`` is provably identical on
every rank, so the sharded preparer's replica-0 dedup stores one copy
automatically without the reference's DDP-module introspection
(snapshot.py:791-807); the glob API is kept for host-side values
(numpy arrays, primitives) where no sharding exists.
"""

from __future__ import annotations

import asyncio
import fnmatch
import functools
import logging
import threading
import uuid
from typing import Any, Dict, List, Optional, Set

import jax

from . import access, telemetry
from .comm import Communicator, get_communicator
from .dist_store import (
    CoordinationKVStore,
    KVStore,
    LinearBarrier,
    MemoryKVStore,
    TakeAbortedError,
    TakeAbortMonitor,
)
from .flatten import flatten, inflate
from .io_preparer import prepare_read, prepare_write
from .liveness import (
    LeasePublisher,
    LivenessMonitor,
    RankFailedError,
)
from .io_types import ReadIO, StoragePlugin, WriteIO
from .manifest import (
    Entry,
    Manifest,
    SnapshotMetadata,
    is_container_entry,
    is_replicated,
)
from .manifest_ops import get_manifest_for_rank, handle_sharded_elasticity
from .rng_state import RNGState
from .scheduler import (
    PendingIOWork,
    get_process_memory_budget_bytes,
    sync_execute_read_reqs,
    sync_execute_write_reqs,
)
from .stateful import AppState, Stateful
from .storage_plugin import url_to_storage_plugin_in_event_loop
from .version import __version__

logger = logging.getLogger(__name__)

SNAPSHOT_METADATA_FNAME = ".snapshot_metadata"


class Snapshot:
    """Handle on a snapshot. The event loop and storage plugin are
    created lazily on first use and REUSED across restore/read_object/
    metadata calls (a GCS plugin holds an authorized session — paying
    its construction per ``read_object`` in a loop is pure overhead;
    the reference rebuilds both per call, snapshot.py:437-520). Call
    ``close()`` (or use the handle as a context manager) to release
    them; they are also re-created transparently after a close."""

    def __init__(
        self,
        path: str,
        storage_options: Optional[Dict[str, Any]] = None,
        comm: Optional[Communicator] = None,
    ) -> None:
        self.path = path
        self._storage_options = storage_options
        self._comm = comm
        self._metadata: Optional[SnapshotMetadata] = None
        self._cached_loop: Optional[asyncio.AbstractEventLoop] = None
        self._cached_storage: Optional[StoragePlugin] = None
        # restore/read_object/metadata serialize on this lock: they share
        # the cached loop, and a second run_until_complete on a running
        # loop raises. Threads wanting concurrent reads use separate
        # Snapshot handles (each carries its own loop + plugin).
        self._op_lock = threading.RLock()

    def _resources(self):
        """(event_loop, storage), cached across calls. Callers hold
        ``_op_lock`` for the duration of their use."""
        if self._cached_loop is None or self._cached_loop.is_closed():
            self._cached_loop = asyncio.new_event_loop()
            self._cached_storage = None
        if self._cached_storage is None:
            self._cached_storage = url_to_storage_plugin_in_event_loop(
                self.path, self._cached_loop, self._storage_options
            )
        return self._cached_loop, self._cached_storage

    def close(self) -> None:
        """Release the cached storage plugin and event loop."""
        self._close(blocking=True)

    def _close(self, blocking: bool) -> None:
        # The finalizer path (__del__) must NOT block on _op_lock: GC
        # can fire on a thread that holds arbitrary locks (e.g. the
        # executor's shutdown locks inside submit), and blocking there
        # while another snapshot's op holds ITS _op_lock and submits is
        # one unlucky schedule from an AB/BA deadlock — the lockwatch
        # watchdog flagged exactly this edge. A contended _op_lock from
        # __del__ means the object is still in use; skipping the close
        # leaks nothing (the next explicit close or GC pass retries).
        if not self._op_lock.acquire(blocking):
            return
        try:
            # GC may run __del__ from inside another running event loop
            # (e.g. while a different snapshot's coroutines execute);
            # run_until_complete is illegal there, so skip the graceful
            # storage close and only drop references.
            try:
                asyncio.get_running_loop()
                in_async_context = True
            except RuntimeError:
                in_async_context = False
            if (
                not in_async_context
                and self._cached_storage is not None
                and self._cached_loop is not None
                and not self._cached_loop.is_closed()
                and not self._cached_loop.is_running()
            ):
                try:
                    self._cached_storage.sync_close(self._cached_loop)
                except Exception:
                    pass
            self._cached_storage = None
            if self._cached_loop is not None:
                try:
                    if not self._cached_loop.is_running():
                        if not blocking:
                            # Finalizer path: loop.close() — here or in
                            # asyncio's own __del__ if we cannot close —
                            # shuts down the loop's DEFAULT executor
                            # (run_in_executor(None, ...), the read-abort
                            # drain uses it) with a BLOCKING
                            # _shutdown_lock acquire, the exact GC-inside-
                            # submit AB/BA window shutdown_plugin_executor
                            # documents. Detach it and trylock-shutdown
                            # instead (we are inside finalizer_close_scope,
                            # so the helper takes the no-wait branch).
                            self._detach_default_executor(self._cached_loop)
                        self._cached_loop.close()
                except Exception:
                    pass
            self._cached_loop = None
        finally:
            self._op_lock.release()

    @staticmethod
    def _detach_default_executor(loop) -> None:
        from .io_types import shutdown_plugin_executor

        try:
            executor = loop._default_executor
            if executor is None:
                return
            loop._default_executor = None
        except Exception:
            return
        shutdown_plugin_executor(executor)

    def __enter__(self) -> "Snapshot":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self) -> None:
        # Best-effort: `Snapshot(path).restore(...)` temporaries are
        # refcount-collected at statement end, so the common drop-the-
        # handle pattern releases its loop and storage promptly without
        # an explicit close(). Finalizer scope: plugin close() must not
        # join threads here — GC can fire inside a starting thread's
        # Thread._set_tstate_lock, where a join self-deadlocks on
        # threading._shutdown_locks_lock (io_types.finalizer_close_scope).
        from .io_types import finalizer_close_scope

        try:
            with finalizer_close_scope():
                self._close(blocking=False)
        except Exception:
            pass

    # ------------------------------------------------------------------ take

    @classmethod
    def take(
        cls,
        path: str,
        app_state: AppState,
        replicated: Optional[List[str]] = None,
        storage_options: Optional[Dict[str, Any]] = None,
        comm: Optional[Communicator] = None,
        per_key_barrier: bool = False,
        incremental_from: Optional[str] = None,
        _custom_array_prepare_func: Optional[Any] = None,
        _extras: Optional[Dict[str, Any]] = None,
        _record_dedup_hashes: bool = False,
    ) -> "Snapshot":
        """``_custom_array_prepare_func(logical_path, arr, tracing)``
        transforms dense, chunked and sharded arrays at save time
        (dtype cast / quantize-on-save; reference
        _custom_tensor_prepare_func, snapshot.py:170-196; threaded into
        the sharded path like reference io_preparer.py:100-106). At
        prepare time it is traced abstractly (``jax.eval_shape`` — zero
        FLOPs) to learn the stored dtype/shape; at stage time it runs
        for real, per local shard for sharded arrays. It must not
        change the shape, and must be deterministic.

        ``incremental_from`` makes this an INCREMENTAL snapshot against a
        previous one at that path (same scheme/bucket; typically a
        sibling directory): any blob whose staged bytes hash to the same
        stage-time checksums (whole-blob + tile-grain CRCs, plus matching
        dtype/shape/box) skips its storage write, and the new manifest
        references the previous snapshot's blob by relative location.
        The result is self-describing and restores/scrubs/read_objects
        like any snapshot — but it REQUIRES the base snapshot(s) to stay
        alive; deleting a base breaks the snapshots layered on it
        (``python -m tpusnap verify`` reports the dangling references).
        Dedup is fine-grained: slab-batched small arrays dedup per
        member (the new slab holds only changed members), and a large
        array whose base entry carries per-tile dedup hashes rewrites
        only its CHANGED checksum tiles — one changed row of a multi-GB
        array costs one tile, with unchanged tiles stored as byte-range
        references into the base blob. Every skip decision requires a
        32-bit CRC AND an independent 64-bit hash to match. Tile-grain
        skips need the PREVIOUS entry to carry per-tile dedup hashes,
        which incremental takes record whenever they WRITE a blob — so
        in a chain, each blob reaches tile grain one take after it
        first rewrites (its unchanged takes skip whole-blob on the
        CRC-only pass). Set TPUSNAP_RECORD_DEDUP_HASHES=1 on the full
        base take to give every blob tile grain from the first
        increment. Pass the same value on every rank.

        ``per_key_barrier=True`` restores the reference's barrier
        between every stateful's ``state_dict()`` call (snapshot.py:
        362-368) — needed only when a stateful runs its own collectives
        inside ``state_dict`` and those must not interleave across keys.
        tpusnap itself issues no device collectives during take, so the
        default skips the barriers (and their extra key gather)."""
        comm = get_communicator(comm)
        event_loop = asyncio.new_event_loop()
        abort_ctx = _TakeAbortContext(comm)
        abort_ctx.event_loop = event_loop
        tele = telemetry.begin_take(comm.rank)
        try:
            (
                pending_io_work,
                metadata,
                path,
                storage,
                late_checksums,
                tele_commit,
            ) = _take_impl(
                path=path,
                app_state=app_state,
                storage_options=storage_options,
                comm=comm,
                replicated=replicated or [],
                event_loop=event_loop,
                is_async_snapshot=False,
                per_key_barrier=per_key_barrier,
                array_prepare_func=_custom_array_prepare_func,
                incremental_from=incremental_from,
                abort_ctx=abort_ctx,
                extras=_extras,
                force_dedup_hashes=_record_dedup_hashes,
            )
            drain_start = tele.now()
            pending_io_work.sync_complete(event_loop)
            # The residual-I/O window: storage writes draining after
            # staging completed.
            tele.record_span(
                "io_drain", drain_start, tele.now() - drain_start, phase=True
            )
            prep_start = tele.now()
            from .knobs import is_durable_commit_enabled

            if is_durable_commit_enabled():
                # Every rank makes its own dirents durable before the
                # commit barrier — rank 0's metadata fsync can only
                # cover directories ITS plugin instance created.
                storage.sync_flush_created_dirs(event_loop)
            if late_checksums is not None:
                # Writes drained: this rank's deferred checksums are
                # final — publish before the barrier; rank 0 applies
                # after it (every rank arrived ⟹ every rank published).
                late_checksums.publish()
            # Writes drained: freeze + persist this rank's trace inside
            # the snapshot and publish its summary — BEFORE the commit
            # barrier, preserving metadata-written-last.
            tele_commit.persist(storage, event_loop, abort_ctx, prep_start)
            # With the abort watcher armed (multi-process), both commit
            # barriers poll for peer abort records and raise
            # TakeAbortedError within seconds instead of burning the
            # full barrier timeout on a failed rank.
            comm.barrier()
            # Barrier passed ⟹ every rank published: EVERY rank patches
            # its local manifest copy (late checksums) and folds the
            # telemetry rollup. Rank 0's patch is load-bearing (it
            # writes the file); a non-leader patch failure falls back
            # to the lazy committed-file read (ADVICE r5 #4).
            meta_cached = True
            try:
                if late_checksums is not None:
                    late_checksums.apply(metadata.manifest)
                if not tele_commit.apply(metadata):
                    # Non-leader KV read came back incomplete: its copy
                    # would diverge from the committed rollup — drop the
                    # cache, keep the take.
                    meta_cached = False
            except Exception:
                if comm.rank == 0:
                    raise
                logger.warning(
                    "Non-leader late-checksum patch failed; falling back "
                    "to reading committed metadata (non-fatal)",
                    exc_info=True,
                )
                meta_cached = False
            if comm.rank == 0:
                abort_ctx.mark_commit_started()
                _write_metadata(storage, metadata, event_loop)
            # The second commit barrier doubles as the cleanup gate:
            # every rank passing it has read the take-scoped KV blobs,
            # so rank 0 can delete them after it.
            comm.barrier()
            if comm.rank == 0:
                if late_checksums is not None:
                    late_checksums.cleanup()
                tele_commit.cleanup()
            # Commit is definitive: mark the take completed (end_take
            # publishes only completed takes to the cross-run history),
            # anchor the SLO tracker (RPO clock restarts, data-at-risk
            # clears), publish the final heartbeat (100%) and stop the
            # pump before the handle is returned.
            tele.meta["completed"] = True
            _record_slo_commit(
                tele, metadata, tele_commit.take_id, path, comm.rank
            )
            tele_commit.finish_progress()
            # Final black-box flush with the committed verdict (the
            # pump's last tick already flushed; this one is forced and
            # carries the take_end event). Never raises.
            from . import flight as _flight_mod

            _flight_mod.recorder().end_take("committed")
            if comm.rank == 0:
                # Metadata committed and every rank departed: the take
                # journal's job is done. Best-effort — a crash before
                # this clear leaves valid metadata + a stale journal,
                # which fsck classifies as committed (gc reclaims the
                # leftovers). Cleared strictly AFTER the metadata write,
                # preserving metadata-written-last.
                from .knobs import is_journal_disabled
                from .lifecycle import clear_journal

                if not is_journal_disabled():
                    clear_journal(
                        storage,
                        event_loop,
                        getattr(storage, "clear_world_size", comm.world_size),
                    )
                if abort_ctx.monitor is not None:
                    abort_ctx.monitor.clear()
                if abort_ctx.lease is not None:
                    abort_ctx.lease.cleanup()
            storage.sync_close(event_loop)
        except RankFailedError as rank_exc:
            # A peer died mid-take. Under TPUSNAP_RANK_FAILURE=degrade
            # (and with the recovery context armed — post-staging,
            # non-incremental), the survivors complete a
            # replicated-only take without it; anything else aborts to
            # a torn state exactly like any failure, with the dead
            # rank named by the flight breadcrumbs.
            try:
                degraded_meta = _maybe_degraded_commit(abort_ctx, rank_exc)
            except BaseException as e:
                abort_ctx.on_failure(e)
                raise
            if degraded_meta is None:
                abort_ctx.on_failure(rank_exc)
                raise
            metadata = degraded_meta
            meta_cached = True  # every survivor built the same manifest
            tele.meta["completed"] = True
            _record_slo_commit(
                tele, metadata, abort_ctx.degrade.take_id, path, comm.rank
            )
            if abort_ctx.progress is not None:
                try:
                    abort_ctx.progress.finish("committed")
                except Exception:
                    pass
            from . import flight as _flight_mod

            _flight_mod.recorder().end_take("committed")
            abort_ctx.degrade.storage.sync_close(
                abort_ctx.degrade.event_loop
            )
        except BaseException as e:
            abort_ctx.on_failure(e)
            raise
        finally:
            # Safety net: on any exit path the pump thread must be gone
            # (on_failure/finish_progress already stopped it; idempotent).
            if abort_ctx.progress is not None:
                abort_ctx.progress.stop()
            telemetry.end_take(tele)
            abort_ctx.disarm()
            event_loop.close()
        snapshot = cls(path, storage_options, comm)
        if meta_cached:
            # Every rank's copy is fully patched (late checksums + the
            # telemetry rollup applied locally from the KV blobs), so
            # every rank caches it — no per-rank metadata GET against
            # cloud storage on first access. The rare non-leader patch
            # failure leaves the handle uncached; its first metadata
            # access reads the committed file rank 0 wrote.
            snapshot._metadata = metadata
        return snapshot

    @classmethod
    def async_take(
        cls,
        path: str,
        app_state: AppState,
        replicated: Optional[List[str]] = None,
        storage_options: Optional[Dict[str, Any]] = None,
        comm: Optional[Communicator] = None,
        per_key_barrier: bool = False,
        incremental_from: Optional[str] = None,
        _custom_array_prepare_func: Optional[Any] = None,
        _extras: Optional[Dict[str, Any]] = None,
        _record_dedup_hashes: bool = False,
        _force_clone_staging: bool = False,
        _stream_capture: bool = False,
    ) -> "PendingSnapshot":
        comm = get_communicator(comm)
        event_loop = asyncio.new_event_loop()
        abort_ctx = _TakeAbortContext(comm)
        abort_ctx.event_loop = event_loop
        tele = telemetry.begin_take(comm.rank)
        try:
            (
                pending_io_work,
                metadata,
                path,
                storage,
                late_checksums,
                tele_commit,
            ) = _take_impl(
                path=path,
                app_state=app_state,
                storage_options=storage_options,
                comm=comm,
                replicated=replicated or [],
                event_loop=event_loop,
                is_async_snapshot=True,
                per_key_barrier=per_key_barrier,
                array_prepare_func=_custom_array_prepare_func,
                incremental_from=incremental_from,
                abort_ctx=abort_ctx,
                extras=_extras,
                force_dedup_hashes=_record_dedup_hashes,
                force_clone_staging=_force_clone_staging,
                stream_capture=_stream_capture,
            )
            # Control returns to training here: the blocked window is
            # over — the first staging window is staged (ALL staging,
            # when the state fits TPUSNAP_ASYNC_STAGE_WINDOW_BYTES or
            # the take is incremental); residual windows clone on the
            # background drain, interleaved with storage I/O. Callers
            # that mutate host-aliasing state IN PLACE synchronize on
            # wait_staged(); functional JAX updates never need to.
            return PendingSnapshot(
                path=path,
                pending_io_work=pending_io_work,
                metadata=metadata,
                storage=storage,
                comm=comm,
                event_loop=event_loop,
                storage_options=storage_options,
                late_checksums=late_checksums,
                abort_ctx=abort_ctx,
                tele_commit=tele_commit,
                force_clone_staging=_force_clone_staging,
            )
        except BaseException as e:
            telemetry.end_take(tele)
            abort_ctx.on_failure(e)
            abort_ctx.disarm()
            event_loop.close()
            raise

    # ---------------------------------------------------------------- stream

    @classmethod
    def stream(
        cls,
        root: str,
        app_state: AppState,
        cadence_s: Optional[float] = None,
        replicated: Optional[List[str]] = None,
        storage_options: Optional[Dict[str, Any]] = None,
        comm: Optional[Communicator] = None,
        max_chain: Optional[int] = None,
    ) -> "DeltaStream":
        """Continuous delta checkpointing: open a :class:`~tpusnap.delta.
        DeltaStream` under ``root`` — a full base snapshot now, then one
        journaled incremental micro-commit per ``cadence_s`` (default
        ``TPUSNAP_DELTA_CADENCE_S``) shipping only tiles/blobs whose
        fresh CRC32C+XXH64 pair differs from the last committed
        increment. A crash at any instant recovers, via base + committed
        delta chain, to a state no older than ~one cadence interval
        (``tpusnap.delta.resolve_chain(root).head`` names the recovery
        head; ``Snapshot(head).restore`` replays it transparently).
        ``close()`` the stream (or use it as a context manager) to stop.
        See :mod:`tpusnap.delta` for the step-consistency contract
        (``mark_step``/``commit_now``) and chain compaction."""
        from .delta import DeltaStream

        return DeltaStream(
            root,
            app_state,
            cadence_s=cadence_s,
            replicated=replicated,
            storage_options=storage_options,
            comm=comm,
            max_chain=max_chain,
        )

    # --------------------------------------------------------------- restore

    def restore(self, app_state: AppState, per_key_barrier: bool = False) -> None:
        """Each rank restores its own manifest view independently — the
        default restore issues no barriers and no per-key collectives
        (the snapshot is immutable and every rank reads storage
        directly; the reference barriers once per key,
        snapshot.py:459-470, which at 16+ processes x many keys is pure
        serial KV overhead). The one exception: a fresh process gathers
        hostnames ONCE to size the memory budget (cached thereafter; a
        take in the same process pre-populates it) — so all ranks must
        enter a cold restore together, as they do on any SPMD restart.

        ``per_key_barrier=True`` restores the reference's global key
        order + barrier-per-key — needed only when a stateful runs its
        own collectives inside ``load_state_dict``."""
        comm = get_communicator(self._comm)
        _validate_app_state(app_state)
        with self._op_lock:
            self._restore_locked(app_state, comm, per_key_barrier)

    def async_restore(self, app_state: AppState) -> "PendingRestore":
        """Restore on a background thread; training-adjacent work
        (compilation, data pipeline warmup) overlaps the storage reads.
        ``app_state``'s statefuls must not be touched until ``wait()``
        returns — ``load_state_dict`` runs on the background thread.

        Safe off the main thread because the default restore issues NO
        collectives: the one cold-start collective (the memory-budget
        hostname gather) is taken HERE, on the calling thread, before
        the thread starts. ``per_key_barrier`` restores are inherently
        collective and have no async form (beyond the reference, which
        has no async restore either) — a stateful whose
        ``load_state_dict`` runs device collectives must declare
        ``load_requires_collectives = True`` (see ``Stateful``) and is
        REJECTED here: running its collectives from this background
        thread, unordered against other ranks, deadlocks or corrupts
        (the reference bans collectives off-thread the same way,
        snapshot.py:902)."""
        comm = get_communicator(self._comm)
        _validate_app_state(app_state)
        offenders = sorted(
            key
            for key, stateful in app_state.items()
            if getattr(stateful, "load_requires_collectives", False)
        )
        if offenders:
            raise ValueError(
                f"async_restore cannot restore {offenders}: their "
                "load_state_dict declares load_requires_collectives=True, "
                "and collectives must not run on the background restore "
                "thread (unordered across ranks -> deadlock/corruption). "
                "Use restore(per_key_barrier=True) for these statefuls."
            )
        # Cold-start collective on the calling thread; cached afterwards.
        memory_budget = get_process_memory_budget_bytes(comm)
        return PendingRestore(self, app_state, comm, memory_budget)

    def _restore_locked(
        self, app_state, comm, per_key_barrier, memory_budget=None
    ) -> None:
        # Restore telemetry: a dedicated recorder (thread-local overlay,
        # so an in-flight take's global recorder is never disturbed)
        # with contiguous phases (restore.plan → per-key targets/
        # prepare/read/load) and the scheduler's storage_read/consume op
        # spans. The snapshot is immutable, so the trace persists to
        # the LOCAL trace dir (TPUSNAP_TELEMETRY_DIR) — rendered by
        # `python -m tpusnap trace --restore <path>`.
        tele = telemetry.begin_restore(comm.rank)
        tele.meta.update(path=self.path, world_size=comm.world_size)
        mark = telemetry.PhaseMarker(rec=tele, from_start=True)
        # Access-ledger scope around the whole read path: every ReadReq
        # the restore executes attributes (logical path, byte range,
        # source tier) to this reader's sidecar — the raw material for
        # `tpusnap heatmap`. Opened manually (not read_scope) so the
        # disk flush waits until the telemetry wall has closed below;
        # otherwise it reads as unspanned restore time on tiny loads.
        ledger = access.open_ledger(
            self.path, default_source=self._access_default_source()
        )
        try:
            with telemetry.use(tele):
                with access.use(ledger):
                    self._restore_instrumented(
                        app_state, comm, per_key_barrier, memory_budget, mark
                    )
            # Only a restore that ran to completion becomes a history
            # trend point; the summary itself still publishes either way.
            tele.meta["completed"] = True
        finally:
            if ledger is not None:
                # In-memory totals only: the summary needs the access_*
                # fields, but the flush and fleet publish happen after
                # finalize() so they stay outside the measured wall.
                tele.meta["access"] = {
                    "bytes_read": ledger.total_bytes,
                    "reads": ledger.total_reads,
                    "working_set_bytes": ledger.working_set_bytes(),
                }
            # The tuned overlay is scoped to the operation that applied
            # it — knob reads after the restore see the plain env again.
            from .knobs import clear_tuned_plan

            clear_tuned_plan()
            tele.finalize()
            summary = tele.summary()
            telemetry.publish_restore_summary(summary)
            if tele.enabled:
                try:
                    from .progress import persist_restore_trace

                    persist_restore_trace(tele, self.path)
                except Exception:
                    logger.warning(
                        "Failed to persist restore trace (non-fatal)",
                        exc_info=True,
                    )
            if ledger is not None:
                try:
                    ledger.flush()
                except Exception:
                    logger.debug(
                        "access ledger flush failed", exc_info=True
                    )
                self._publish_access_stats(ledger)

    def _restore_instrumented(
        self, app_state, comm, per_key_barrier, memory_budget, mark
    ) -> None:
        event_loop, storage = self._resources()
        try:
            from .storage_plugin import storage_plugin_label

            # Which backend this restore reads from (tier-aware): the
            # history event's `plugin` field, what the SLO RTO
            # estimator filters its baseline on.
            telemetry.current().meta["plugin"] = storage_plugin_label(storage)
        except Exception:
            pass
        # Auto-tuner reconcile (TPUSNAP_AUTOTUNE=1): install this
        # cell's plan BEFORE the budget/knob reads below, so the
        # restore runs with the tuned values; the applied subset rides
        # the summary into the history event for attribution.
        from . import tune as _tune

        tuned = _tune.maybe_apply(
            "restore", storage=storage, world_size=comm.world_size
        )
        if tuned:
            telemetry.current().meta["tuned"] = tuned
        metadata = self._get_metadata(storage, event_loop)
        if memory_budget is None:
            memory_budget = get_process_memory_budget_bytes(comm)

        multi = comm.world_size > 1
        if per_key_barrier and multi:
            keys = _gather_keys(comm, sorted(app_state.keys()))
        else:
            keys = sorted(app_state.keys())
        # Metadata read/decode + budget + (optional) key gather.
        mark("restore.plan")
        # RNG state is restored last so that loading other statefuls
        # cannot perturb it (reference snapshot.py:473-481).
        rng_keys = [
            k for k in keys if isinstance(app_state.get(k), RNGState)
        ]
        for key in [k for k in keys if k not in rng_keys] + rng_keys:
            if per_key_barrier and multi:
                comm.barrier()
            stateful = app_state.get(key)
            if stateful is None:
                continue
            _load_stateful(
                stateful=stateful,
                key=key,
                metadata=metadata,
                rank=comm.rank,
                storage=storage,
                memory_budget=memory_budget,
                event_loop=event_loop,
                mark=mark,
            )

    # ----------------------------------------------------------- random access

    def read_object(
        self,
        path: str,
        obj_out: Any = None,
        memory_budget_bytes: Optional[int] = None,
    ) -> Any:
        """Read a single object by manifest path ``"<rank>/<logical_path>"``
        without restoring anything else (reference snapshot.py:501-594)."""
        comm = get_communicator(self._comm)
        rank_str, _, logical_path = path.partition("/")
        if not rank_str.isdigit() or not logical_path:
            raise ValueError(
                f"Invalid manifest path {path!r} (expected '<rank>/<path>')"
            )
        with self._op_lock:
            return self._read_object_locked(
                path, rank_str, logical_path, obj_out, memory_budget_bytes, comm
            )

    def _read_object_locked(
        self, path, rank_str, logical_path, obj_out, memory_budget_bytes, comm
    ) -> Any:
        event_loop, storage = self._resources()
        metadata = self._get_metadata(storage, event_loop)
        local_manifest = get_manifest_for_rank(metadata, int(rank_str))
        if logical_path not in local_manifest:
            raise KeyError(f"{path!r} not found in snapshot manifest")
        entry = local_manifest[logical_path]
        if is_container_entry(entry):
            raise ValueError(
                f"{path!r} is a container; read its leaves individually"
            )
        read_reqs, fut = prepare_read(
            entry,
            obj_out,
            buffer_size_limit_bytes=memory_budget_bytes,
            logical_path=logical_path,
        )
        budget = memory_budget_bytes or get_process_memory_budget_bytes(comm)
        # Random access is the lazy-serving path the heatmap exists to
        # credit: scope just this object's reads so partial readers show
        # up with coverage << 1 instead of vanishing.
        with access.read_scope(
            self.path, default_source=self._access_default_source()
        ) as ledger:
            sync_execute_read_reqs(
                read_reqs, storage, budget, comm.rank, event_loop
            )
        if ledger is not None:
            self._publish_access_stats(ledger)
        return fut.obj

    def _access_default_source(self) -> str:
        """Ambient source tier for access-ledger records whose ReadIO
        carries no explicit stamp (tiering/CAS override per read)."""
        try:
            from .storage_plugin import storage_plugin_label

            _, storage = self._resources()
            return access.default_source_for_plugin(storage_plugin_label(storage))
        except Exception:
            return "local"

    def _publish_access_stats(self, ledger) -> None:
        """Fold a finished read scope's totals into this process's fleet
        reader record (the reader side of `tpusnap fleet`). The restore
        path stamps its telemetry meta separately, inside the wall.
        Best-effort: attribution never fails a read."""
        try:
            from . import fleet
            from .progress import _path_digest

            snapshot_bytes = 0
            if self._metadata is not None:
                snapshot_bytes = access.snapshot_stored_nbytes(self._metadata)
            fleet.note_reader_scope(
                _path_digest(self.path),
                snapshot_bytes,
                ledger.total_bytes,
                ledger.total_reads,
            )
        except Exception:
            logger.debug("fleet reader stats publish failed", exc_info=True)

    # ------------------------------------------------------------- integrity

    def verify(self):
        """Stream-verify every blob of this snapshot against the checksums
        recorded in its manifest (see :mod:`tpusnap.inspect`). Returns a
        :class:`tpusnap.inspect.ScrubReport`; ``report.clean`` is False on
        any corruption/truncation. Also exposed as
        ``python -m tpusnap verify <path>``."""
        from .inspect import verify_snapshot

        with self._op_lock:
            event_loop, storage = self._resources()
            return verify_snapshot(
                self.path,
                self._storage_options,
                metadata=self._metadata,
                resources=(event_loop, storage),
            )

    def materialize(self) -> Dict[str, int]:
        """Make an incremental snapshot self-contained by copying every
        base-referenced blob into it and rewriting the manifest (see
        :func:`tpusnap.inspect.materialize_snapshot`); afterwards the
        base snapshot(s) may be deleted. No-op on full snapshots."""
        from .inspect import materialize_snapshot

        with self._op_lock:
            event_loop, storage = self._resources()
            stats = materialize_snapshot(
                self.path,
                self._storage_options,
                resources=(event_loop, storage),
            )
            self._metadata = None  # manifest was rewritten on disk
        return stats

    # -------------------------------------------------------------- metadata

    @property
    def metadata(self) -> SnapshotMetadata:
        if self._metadata is None:
            with self._op_lock:
                event_loop, storage = self._resources()
                self._metadata = self._get_metadata(storage, event_loop)
        return self._metadata

    def get_manifest(self) -> Manifest:
        return dict(self.metadata.manifest)

    def _get_metadata(
        self, storage: StoragePlugin, event_loop: asyncio.AbstractEventLoop
    ) -> SnapshotMetadata:
        if self._metadata is not None:
            return self._metadata
        read_io = ReadIO(path=SNAPSHOT_METADATA_FNAME)
        try:
            storage.sync_read(read_io, event_loop)
        except Exception as e:
            raise RuntimeError(
                f"Failed to read snapshot metadata at "
                f"{self.path}/{SNAPSHOT_METADATA_FNAME} — not a snapshot, or "
                f"an aborted/incomplete one"
            ) from e
        from .manifest import MetadataError, decode_metadata

        try:
            self._metadata = decode_metadata(read_io.buf.getvalue())
        except MetadataError as e:
            raise RuntimeError(
                f"Corrupt snapshot metadata at "
                f"{self.path}/{SNAPSHOT_METADATA_FNAME}: {e} — run "
                f"`python -m tpusnap fsck {self.path}` to classify"
            ) from e
        except Exception as e:
            raise RuntimeError(
                f"Corrupt snapshot metadata at "
                f"{self.path}/{SNAPSHOT_METADATA_FNAME}"
            ) from e
        return self._metadata


# ---------------------------------------------------------------- internals


class _TakeAbortContext:
    """Failure-path bookkeeping for one take.

    Armed (multi-process) once G1 agrees the take_id: installs the
    :class:`TakeAbortMonitor` as the communicator's wait watcher, so
    every subsequent collective wait and commit barrier raises
    :class:`TakeAbortedError` within seconds of any rank's failure
    instead of burning the barrier timeout. On failure it publishes this
    rank's abort record, best-effort deletes the blobs this rank staged
    (so the path stays reusable and aborted takes leave no orphan
    storage), and drops this rank's late-checksum blob. Blob deletion is
    suppressed once the metadata commit may have started — orphan blobs
    are safe, dangling manifest references are not (the
    metadata-written-last ⟺ restorable invariant)."""

    def __init__(self, comm: Communicator) -> None:
        self.comm = comm
        self.monitor: Optional[TakeAbortMonitor] = None
        self.storage: Optional[StoragePlugin] = None
        self.event_loop: Optional[asyncio.AbstractEventLoop] = None
        self.write_paths: List[str] = []
        self.late_checksums: Optional["_LateChecksums"] = None
        self.tele_commit: Optional["_TelemetryCommit"] = None
        # Heartbeat/watchdog monitor (tpusnap.progress) — stopped with
        # a final "aborted" record on any failure path.
        self.progress = None
        # Rank-liveness layer (tpusnap.liveness): the lease this rank
        # publishes, the monitor every blocking wait consults, and —
        # when the failure policy is `degrade` — the context the
        # survivors complete a replicated-only take from.
        self.lease: Optional[LeasePublisher] = None
        self.liveness: Optional[LivenessMonitor] = None
        self.degrade: Optional["_DegradeContext"] = None
        self.commit_started = False
        # Set once the take's journal exists: an ABORTED take (as opposed
        # to a SIGKILLed one) cleans its blobs, so it also clears the
        # journal — leaving the path classifiably empty, not torn.
        self.journal_world_size: Optional[int] = None

    def arm(self, monitor: TakeAbortMonitor) -> None:
        self.monitor = monitor
        self._install_watcher()

    def arm_liveness(
        self, lease: LeasePublisher, liveness: LivenessMonitor
    ) -> None:
        """Installed once the heartbeat pump exists (strictly after
        ``arm``): the combined wait watcher now also judges lease
        staleness, so a blocked collective raises RankFailedError
        within ~2x TTL of a peer's death."""
        self.lease = lease
        self.liveness = liveness
        self._install_watcher()

    def _install_watcher(self) -> None:
        monitor, liveness = self.monitor, self.liveness
        if monitor is None:
            return
        if liveness is None:
            self.comm.set_wait_watcher(monitor.check)
            return

        def watcher() -> None:
            monitor.check()
            liveness.check()

        self.comm.set_wait_watcher(watcher)

    def barrier_watchers(self) -> List:
        """Watcher list for LinearBarrier-based waits (the async
        commit): peer-abort records AND lease expiry."""
        out = []
        if self.monitor is not None:
            out.append(self.monitor.check)
        if self.liveness is not None:
            out.append(self.liveness.check)
        return out

    def disarm(self) -> None:
        if self.monitor is not None:
            self.comm.clear_wait_watcher()

    def mark_commit_started(self) -> None:
        self.commit_started = True
        if self.monitor is not None:
            self.monitor.mark_commit_started()

    def on_failure(self, exc: BaseException) -> None:
        """Publish + clean up; never raises."""
        if self.progress is not None:
            try:
                self.progress.finish("aborted")
            except Exception:
                pass
        # SLO bookkeeping: release the dead take's telemetry record
        # (its counters would otherwise stay referenced for the process
        # lifetime) WITHOUT clearing the exposure — nothing committed,
        # so the planned bytes are still at risk.
        try:
            from . import slo as _slo

            _slo.tracker().note_take_aborted()
        except Exception:
            pass
        # The black box records the abort and force-flushes: an aborted
        # take's forensic breadcrumb survives even though its blobs and
        # journal are about to be cleaned.
        try:
            from . import flight as _flight_mod

            _flight_mod.record("abort", op=type(exc).__name__)
            _flight_mod.recorder().end_take("aborted")
        except Exception:
            pass
        if self.monitor is not None and not isinstance(exc, TakeAbortedError):
            self.monitor.publish(exc)
        # A RANK-FAILURE abort keeps everything: the survivors' completed
        # blobs are good bytes and the journal records are their salvage
        # evidence — deleting them would reduce the retake to byte zero,
        # and the dead rank cannot clean its own either way. The torn
        # state it leaves is exactly what fsck/timeline classify (naming
        # the dead rank) and what the retake's dual-hash salvage reuses.
        rank_failure = isinstance(exc, RankFailedError) or isinstance(
            getattr(exc, "__cause__", None), RankFailedError
        )
        keep_blobs = (
            self.commit_started
            or rank_failure
            or (
                self.monitor is not None
                and self.monitor.commit_may_have_started()
            )
        )
        if (
            not keep_blobs
            and self.storage is not None
            and self.event_loop is not None
        ):
            deletes_failed = False
            for path in self.write_paths:
                try:
                    self.storage.sync_delete(path, self.event_loop)
                except FileNotFoundError:
                    pass  # dedup/salvage-skipped or never-written path
                except Exception:
                    deletes_failed = True
            # Blobs gone: clear this rank's journal records (rank 0 also
            # the marker) so the path reads as empty, not torn. Records
            # go before the marker — a crash mid-cleanup stays torn. If
            # any of THIS rank's blob deletions failed, keep the marker
            # too: the leftovers must stay classifiable as torn (gc
            # --torn can finish the job), not become foreign debris.
            # Best-effort only across ranks — a PEER whose cleanup fails
            # after rank 0 cleared the marker still leaves foreign
            # files; that residual case needs a manual delete.
            if self.journal_world_size is not None:
                from .lifecycle import clear_journal, journal_rank_path

                try:
                    if self.comm.rank != 0:
                        self.storage.sync_delete(
                            journal_rank_path(self.comm.rank), self.event_loop
                        )
                    elif not deletes_failed:
                        clear_journal(
                            self.storage,
                            self.event_loop,
                            self.journal_world_size,
                        )
                except Exception:
                    pass
        if self.late_checksums is not None:
            try:
                self.late_checksums.discard()
            except Exception:
                pass
        if self.tele_commit is not None:
            try:
                self.tele_commit.discard()
            except Exception:
                pass
        if self.storage is not None and self.event_loop is not None:
            try:
                self.storage.sync_close(self.event_loop)
            except Exception:
                pass


def _validate_app_state(app_state: AppState) -> None:
    for key, stateful in app_state.items():
        if not (hasattr(stateful, "state_dict") and hasattr(stateful, "load_state_dict")):
            raise TypeError(
                f"app_state[{key!r}] ({type(stateful).__name__}) is not "
                "Stateful: it must define state_dict()/load_state_dict()"
            )


def _gather_keys(comm: Communicator, local_keys: List[str]) -> List[str]:
    if comm.world_size == 1:
        return sorted(local_keys)
    gathered = comm.all_gather_object(local_keys)
    merged: Set[str] = set()
    for keys in gathered:
        merged.update(keys)
    return sorted(merged)


def _take_impl(
    path: str,
    app_state: AppState,
    storage_options: Optional[Dict[str, Any]],
    comm: Communicator,
    replicated: List[str],
    event_loop: asyncio.AbstractEventLoop,
    is_async_snapshot: bool,
    per_key_barrier: bool = False,
    array_prepare_func: Optional[Any] = None,
    incremental_from: Optional[str] = None,
    abort_ctx: Optional["_TakeAbortContext"] = None,
    extras: Optional[Dict[str, Any]] = None,
    force_dedup_hashes: bool = False,
    force_clone_staging: bool = False,
    stream_capture: bool = False,
):
    """Core take flow. Exactly TWO all-gathers in the default
    multi-process path (the reference issues ~6 collectives,
    snapshot.py:752-853; the round-2 port issued 6 serial-KV gathers):

    - G1 (pre-staging): path + replicated globs + per-rank write-load
      estimates + hostnames ride one gather. Glob/path coalescing, the
      replicated-path intersection, the write-load partition plan (each
      rank runs the same deterministic argmin-greedy — no broadcast),
      and the local-world-size memory-budget divisor are all derived
      from it locally.
    - G2 (post-staging): the per-rank manifest gather, after stagers
      have recorded checksums into their entries.

    Plus the two commit barriers in ``take``. ``per_key_barrier=True``
    adds the reference's key gather + barrier-per-key for statefuls
    that run collectives inside ``state_dict()``.
    """
    _validate_app_state(app_state)
    rank = comm.rank
    multi = comm.world_size > 1
    # Contiguous phase spans (state_dict → plan → prepare → stage →
    # manifest_gather → metadata) tiling the take's timeline from t0;
    # the trace CLI's coverage figure is their sum over the take
    # wall-clock.
    mark = telemetry.phase_marker(from_start=True)

    # Capture RNG state on entry; other statefuls' state_dict() calls may
    # consume RNG, and take() must be invariant (reference :332-374).
    rng_captured: Dict[str, Dict[str, Any]] = {
        k: v.state_dict() for k, v in app_state.items() if isinstance(v, RNGState)
    }

    if per_key_barrier and multi:
        # Safety mode: globally ordered state_dict() calls with a barrier
        # between keys (reference :352-368).
        keys = _gather_keys(comm, sorted(app_state.keys()))
    else:
        keys = sorted(app_state.keys())

    manifest: Manifest = {}
    flattened_all: Dict[str, Any] = {}
    for key in keys:
        if per_key_barrier and multi:
            comm.barrier()
        stateful = app_state.get(key)
        if stateful is None:
            continue
        state_dict = rng_captured.get(key) or stateful.state_dict()
        mft, flat = flatten(state_dict, prefix=key)
        manifest.update(mft)
        flattened_all.update(flat)

    # Undo any RNG perturbation caused by gathering state dicts.
    for key, captured in rng_captured.items():
        app_state[key].load_state_dict(captured)
    mark("state_dict", keys=len(keys))

    # Local replicated candidates: glob-matched host-side values. A
    # fully-replicated multi-process jax.Array needs no glob — it routes
    # to the sharded preparer, whose replica-0 dedup stores one copy.
    globs = sorted(set(replicated))
    matched = {
        p
        for p in flattened_all
        if any(fnmatch.fnmatch(p, g) for g in globs)
    }

    assignment: Dict[str, int] = {}
    local_world_size: Optional[int] = None
    if multi:
        from .partitioner import assign_replicated_units, estimate_write_loads

        units, base_load, traced_map = estimate_write_loads(
            flattened_all, sorted(matched), array_prepare_func=array_prepare_func
        )
        from .knobs import get_node_name

        gathered = comm.all_gather_object(
            {
                "path": path,
                "globs": globs,
                "units": units,
                "base_load": base_load,
                "hostname": get_node_name(),
                # Scopes the late-checksum KV keys to this take; rank
                # 0's value wins (like the path) — riding G1 instead of
                # paying a broadcast.
                "take_id": uuid.uuid4().hex,
            }
        )
        take_id = gathered[0]["take_id"]
        # Path coalescing: rank 0's wins (reference :766-767).
        if gathered[0]["path"] != path:
            logger.warning(
                "Rank %d's snapshot path %r differs from rank 0's %r; "
                "using rank 0's",
                rank,
                path,
                gathered[0]["path"],
            )
        path = gathered[0]["path"]
        # Glob coalescing: only globs specified on every rank count
        # (reference :778-788).
        common_globs = set(gathered[0]["globs"])
        for g in gathered[1:]:
            common_globs &= set(g["globs"])
        dropped = set(globs) - common_globs
        if dropped:
            logger.warning(
                "Replicated globs %s were not specified on every rank; "
                "ignoring",
                sorted(dropped),
            )

        # A unit is partitionable when every rank listed it AND its path
        # matches a glob every rank specified.
        def unit_valid(uid: str) -> bool:
            p = uid.split("::", 1)[0]
            return any(fnmatch.fnmatch(p, g) for g in common_globs)

        assignment, replicated_paths = assign_replicated_units(
            [g["units"] for g in gathered],
            [g["base_load"] for g in gathered],
            unit_valid,
        )
        my_host = gathered[rank]["hostname"]
        local_world_size = sum(
            1 for g in gathered if g["hostname"] == my_host
        )
        traced_geometry = traced_map
        if abort_ctx is not None:
            # take_id is agreed: arm distributed abort propagation. From
            # here every collective wait in this take (the G2 gather's
            # barrier, the commit barriers/broadcasts) polls for peer
            # abort records and raises TakeAbortedError within seconds.
            abort_ctx.arm(
                TakeAbortMonitor(_get_kv_store(comm), take_id, rank)
            )
    else:
        # Single-process takes journal under their own id (no KV scoping
        # needed; _LateChecksums/_TelemetryCommit stay inactive at
        # world_size == 1 regardless).
        take_id = uuid.uuid4().hex
        replicated_paths = matched
        traced_geometry = {}
    # The G1 gather + write-load partition plan (single-process: just
    # the glob intersection — cheap, but keeping the phases contiguous
    # is what makes coverage meaningful).
    mark("plan")
    if mark.rec is not None:
        # Identity context for the summary consumers (export sinks,
        # cross-run history): take_id and the coalesced path are final
        # here. ``completed`` is set by the caller strictly after the
        # commit.
        mark.rec.meta.update(
            take_id=take_id,
            path=path,
            world_size=comm.world_size,
            incremental=incremental_from is not None,
        )

    storage = url_to_storage_plugin_in_event_loop(
        path, event_loop, storage_options
    )
    try:
        from .storage_plugin import storage_plugin_label

        # Which backend this take writes (innermost plugin class):
        # stamps the history event's `plugin` field — the tune
        # planner's cell key, and what keeps local-NVMe medians from
        # pricing cloud takes (restores have stamped it since PR 12).
        telemetry.current().meta["plugin"] = storage_plugin_label(storage)
    except Exception:
        pass
    # Auto-tuner reconcile (TPUSNAP_AUTOTUNE=1): install this cell's
    # plan BEFORE the staging/window/budget knob reads below; explicit
    # env vars win knob-by-knob, and the applied subset rides the
    # summary into the history event for attribution.
    from . import tune as _tune

    _tuned = _tune.maybe_apply(
        "take", storage=storage, world_size=comm.world_size
    )
    if _tuned:
        try:
            telemetry.current().meta["tuned"] = _tuned
        except Exception:
            pass
    # Crash-safe lifecycle (tpusnap.lifecycle): if the destination holds
    # a TORN take (journal present, no committed metadata), load its
    # completion records — staged blobs whose dual hash matches skip
    # their storage writes (salvage-resume). Then every rank wraps its
    # plugin in the journaling layer, and rank 0 writes the journal
    # marker BEFORE any blob write so a SIGKILLed take stays
    # distinguishable from a committed snapshot or foreign files.
    from .lifecycle import (
        JournalingStoragePlugin,
        TakeJournal,
        load_salvage_records,
        read_journal,
        write_journal,
    )

    from .knobs import is_journal_disabled

    journal_enabled = not is_journal_disabled()
    salvage_records = None
    # Covers every rank that may hold a journal record at this path: a
    # retake over a torn take with a LARGER world size must still clear
    # the torn ranks' record files at commit.
    journal_clear_ws = comm.world_size
    prior_journal = (
        read_journal(storage, event_loop) if journal_enabled else None
    )
    if prior_journal is not None:
        journal_clear_ws = max(journal_clear_ws, prior_journal.world_size)
        try:
            files = storage.sync_list_with_sizes(event_loop)
        except Exception:
            files = None
        # Salvage requires a listing (load_salvage_records cross-checks
        # every record against the blobs actually present — load-bearing
        # for correctness); it also gives the metadata-existence probe
        # for free (a committed snapshot with a stale journal must NOT
        # trigger salvage).
        if files is not None and SNAPSHOT_METADATA_FNAME not in files:
            salvage_records = load_salvage_records(
                storage, event_loop, prior_journal.world_size, files=files
            )
            if salvage_records:
                logger.info(
                    "Torn take %s found at %r: %d completed blob record(s) "
                    "loaded for salvage-resume",
                    prior_journal.take_id[:8],
                    path,
                    len(salvage_records),
                )
    # The CAS layer (if composed) was built before the take knew its
    # rank: per-rank ref records need it (rank 0's file must not be
    # clobbered by rank 3's flush).
    from .cas import find_cas_plugin

    cas_layer = find_cas_plugin(storage)
    if cas_layer is not None:
        cas_layer.rank = rank
    storage = JournalingStoragePlugin(storage, rank, salvage_records)
    storage.clear_world_size = journal_clear_ws
    if journal_enabled:
        if rank == 0:
            import time as _time

            write_journal(
                storage,
                event_loop,
                TakeJournal(
                    take_id=take_id,
                    world_size=comm.world_size,
                    started_at=_time.time(),
                    incremental_from=incremental_from,
                    version=__version__,
                    # Delta-chain membership rides the journal so a
                    # SIGKILLed micro-commit stays explainable as
                    # "seq N over member X", not an anonymous torn take.
                    stream=(extras or {}).get("delta"),
                ),
            )
        # EVERY rank eagerly creates its record file before any of its
        # blob writes: the journal-before-blobs invariant would
        # otherwise be rank-0-only — a gang-SIGKILL while a fast peer
        # wrote blobs before rank 0's marker landed would leave debris
        # fsck can only call foreign. Any journal-family file counts as
        # take evidence, so the unclassifiable window shrinks to this
        # one tiny write per rank. The write carries the SEEDED salvage
        # records (not an empty map), so a salvage-retake that itself
        # crashes early still leaves the torn take's evidence for the
        # third attempt.
        try:
            storage.sync_seed_record_file(event_loop)
        except Exception:
            logger.warning(
                "Failed to create journal record file (non-fatal)",
                exc_info=True,
            )
    if abort_ctx is not None:
        abort_ctx.storage = storage
        if journal_enabled:
            abort_ctx.journal_world_size = journal_clear_ws

    # Live observability (tpusnap.progress): heartbeat pump + stall
    # watchdog for the rest of this take. Telemetry-off takes skip the
    # subsystem entirely; everything it does is best-effort.
    progress_monitor = None
    if mark.rec is not None and mark.rec.enabled:
        try:
            from .progress import start_take_monitor

            progress_monitor = start_take_monitor(
                mark.rec, comm, take_id, path
            )
            if abort_ctx is not None:
                abort_ctx.progress = progress_monitor
        except Exception:
            logger.warning(
                "Failed to start progress monitor (non-fatal)", exc_info=True
            )
    # Black-box flight recorder (tpusnap.flight): arm this take's
    # crash-surviving flush destinations and piggyback the periodic
    # flush on the heartbeat pump — from here, a SIGKILL loses at most
    # one flush interval of events (`python -m tpusnap timeline`).
    try:
        from . import flight as _flight_mod
        from .progress import local_root_of

        _frec = _flight_mod.recorder()
        _frec.configure_take(
            rank, take_id, comm.world_size, path, local_root_of(path)
        )
        if progress_monitor is not None and _frec.enabled:
            progress_monitor.add_tick_hook(_flight_mod.make_tick_hook(_frec))
    except Exception:
        logger.warning(
            "Failed to configure flight recorder (non-fatal)", exc_info=True
        )
    # Rank-liveness leases (tpusnap.liveness): this rank's lease rides
    # the heartbeat pump (no new thread) and the monitor joins every
    # blocking wait's watcher, so a SIGKILLed peer fails the take with
    # RankFailedError within ~2x TPUSNAP_LIVENESS_TTL_S instead of
    # parking until the barrier timeout. Requires the pump (telemetry
    # on — SPMD-identical on every rank) and a coordination KV.
    if (
        multi
        and abort_ctx is not None
        and progress_monitor is not None
        and progress_monitor.kv is not None
    ):
        from .knobs import get_liveness_ttl_s

        ttl = get_liveness_ttl_s()
        if ttl > 0:
            try:
                lease = LeasePublisher(progress_monitor.kv, take_id, rank)
                lease.publish()  # alive NOW, not one pump tick later
                liveness_monitor = LivenessMonitor(
                    progress_monitor.kv,
                    take_id,
                    rank,
                    comm.world_size,
                    ttl_s=ttl,
                )
                progress_monitor.add_tick_hook(lease.make_tick_hook())
                progress_monitor.set_liveness_probe(
                    liveness_monitor.dead_ranks
                )
                progress_monitor.set_left_probe(
                    liveness_monitor.left_ranks
                )
                abort_ctx.arm_liveness(lease, liveness_monitor)
            except Exception:
                logger.warning(
                    "Failed to arm rank-liveness leases (non-fatal)",
                    exc_info=True,
                )

    # Checkpoint-SLO tracker (tpusnap.slo): the exposure gauges (RPO,
    # data-at-risk, estimated RTO) publish at the heartbeat cadence on
    # the same pump thread, and the slo sub-dict rides every heartbeat
    # record (what `watch`'s at-risk column and rank 0's fleet fold
    # read). Best-effort like everything observability.
    if progress_monitor is not None:
        try:
            from . import slo as _slo

            _slo.tracker().refresh_rto()
            _slo.attach_to_take(
                progress_monitor, take_id, rank, comm.world_size
            )
        except Exception:
            logger.warning(
                "Failed to attach SLO tracker (non-fatal)", exc_info=True
            )

    # Fleet status mirror (tpusnap.fleet): when TPUSNAP_FLEET_DIR is
    # set, rank 0 republishes this job's compact status record into the
    # shared fleet directory on the same tick-hook pump — what
    # `tpusnap fleet` aggregates across jobs. No-op otherwise.
    if progress_monitor is not None:
        try:
            from . import fleet as _fleet

            _fleet.attach_to_take(progress_monitor)
        except Exception:
            logger.warning(
                "Failed to attach fleet publisher (non-fatal)", exc_info=True
            )

    # Incremental snapshot: this rank's view of the base snapshot's
    # manifest, blob locations rewritten relative to the NEW root.
    prev_entries: Manifest = {}
    if incremental_from is not None:
        from .knobs import is_checksum_disabled

        if is_checksum_disabled():
            # Dedup compares stage-time checksums; without them every
            # blob would silently rewrite in full — refuse instead.
            raise ValueError(
                "incremental_from requires checksums; unset "
                "TPUSNAP_DISABLE_CHECKSUM to take an incremental snapshot"
            )
        prev_entries, base_root_candidates = _load_prev_entries(
            incremental_from, storage_options, rank, path, event_loop
        )
    else:
        base_root_candidates = []

    entries: Manifest = dict(manifest)
    write_reqs = []
    replicated_entry_paths: List[str] = []
    from .knobs import is_dedup_hash_recording_forced

    record_dedup_hashes = (
        incremental_from is not None
        or force_dedup_hashes
        or is_dedup_hash_recording_forced()
    )
    for logical_path, leaf in flattened_all.items():
        is_repl = logical_path in replicated_paths
        entry, reqs = prepare_write(
            obj=leaf,
            logical_path=logical_path,
            rank=rank,
            replicated=is_repl,
            is_async_snapshot=is_async_snapshot,
            array_prepare_func=(
                functools.partial(array_prepare_func, logical_path)
                if array_prepare_func is not None
                else None
            ),
            array_prepare_traced=traced_geometry.get(logical_path),
            prev_entry=prev_entries.get(logical_path),
            record_dedup_hashes=record_dedup_hashes,
            # Multi-process replicated entries keep blob-grain geometry:
            # the write-load estimator's unit ids (computed on every
            # rank without prev-entry knowledge) must match what was
            # prepared.
            allow_tile_dedup=not (multi and is_repl),
        )
        entries[logical_path] = entry
        if is_repl and is_replicated(entry):
            replicated_entry_paths.append(logical_path)
        write_reqs.extend(reqs)

    # Keep only the replicated write requests the plan assigned to this
    # rank (plan computed identically on every rank from G1 — the
    # reference's rank-0-compute + broadcast is one more collective).
    dropped_replicated: Dict[str, List] = {}
    if multi and replicated_entry_paths:
        from .partitioner import filter_assigned_write_reqs

        write_reqs, dropped_replicated = filter_assigned_write_reqs(
            entries, write_reqs, replicated_entry_paths, assignment, rank
        )

    # Slab-batch small writes.
    from .batcher import batch_write_requests

    entries_list = list(entries.values())
    entries_list, write_reqs = batch_write_requests(entries_list, write_reqs)
    entries = dict(zip(entries.keys(), entries_list))

    # Fused tile compression (tpusnap.compress): ONE measured
    # compress-or-bypass decision per take — the codec's measured
    # throughput against the probe-reported pipe ceiling — armed on the
    # eligible stagers (standalone dense blobs after batching; slab
    # members and shards bypass by construction). Never fails a take.
    from . import compress as _compress

    _compress.apply_take_policy(write_reqs, storage, event_loop, rec=mark.rec)
    if force_clone_staging:
        # Per-TAKE clone-staging override (delta micro-commits:
        # free-running captures cannot rendezvous with the training
        # thread, so COW's write-time mutation check would fail every
        # commit). Armed on the stagers like the compress policy above
        # — scoped to THIS take's requests, never a process-global env
        # flip that would race concurrent takes on other threads.
        # Batched slabs hold their members as (offset, nbytes, stager)
        # tuples; the member stagers are the ones that consult COW.
        def _arm_clone(st):
            if hasattr(st, "force_clone"):
                st.force_clone = True
            for _m in getattr(st, "members", None) or []:
                _arm_clone(_m[2] if isinstance(_m, tuple) else _m)

        for _wr in write_reqs:
            _arm_clone(_wr.buffer_stager)
    if abort_ctx is not None:
        # The final set of blob paths this rank may write — an aborting
        # take best-effort deletes them so the path stays reusable
        # (dedup-skipped paths are never written; deleting them is a
        # harmless no-op failure).
        abort_ctx.write_paths = [wr.path for wr in write_reqs]
    planned_payload = sum(
        wr.buffer_stager.get_planned_bytes() for wr in write_reqs
    )
    if progress_monitor is not None:
        # Denominator of the heartbeat's byte progress — PAYLOAD bytes,
        # not staging cost (async array clones charge 2x cost; dividing
        # written/staged bytes by that capped the percentages at ~50).
        # Dedup/salvage skips make written < planned, so the committed
        # record forces 100% (the mid-flight figure is best-effort by
        # design).
        progress_monitor.set_bytes_planned(planned_payload)
    # Data-at-risk floor (tpusnap.slo): everything this take stages is
    # at risk until its commit clears it; incremental takes refine the
    # figure live from the dual-hash skip counters. Recorded even with
    # telemetry off (the tracker is bookkeeping, not spans).
    try:
        from . import slo as _slo

        _rec = mark.rec
        # Identity must not depend on the telemetry knob: attach (the
        # tick-hook wiring) is skipped when the pump is off, but the
        # sidecar/commit bookkeeping still runs per rank.
        _slo.tracker().configure(rank, comm.world_size)
        _slo.tracker().note_planned(
            planned_payload,
            incremental=incremental_from is not None,
            live_counters=(
                (lambda: _rec.live_snapshot()["counters"])
                if _rec is not None
                else None
            ),
            # The capture anchor: this take's commit makes THIS
            # instant's state durable — not the (possibly minutes
            # later) commit instant.
            take_id=take_id,
        )
    except Exception:
        logger.debug("slo note_planned failed", exc_info=True)

    # Non-incremental takes hash on the WRITE path instead of the
    # staging window (see ArrayBufferStager.defer_checksums) — the hash
    # pass moves off the window async_take blocks training on. With
    # world_size == 1 the gathered manifest holds the SAME entry
    # objects the stagers annotate, so late values land in the commit
    # directly; multi-process manifests gather by VALUE at
    # staging-complete, so the late values ride the commit barrier's KV
    # store instead (_LateChecksums). Applied after batching: slab
    # members hash inside their slab's staging (the member write reqs
    # no longer exist to carry a late hash). Incremental takes need
    # hashes at stage time for dedup and never defer.
    late_checksums: Optional[_LateChecksums] = _NO_LATE_CHECKSUMS
    if incremental_from is None:
        from .io_preparers.array import ArrayBufferStager

        deferred = []
        for wr in write_reqs:
            if isinstance(wr.buffer_stager, ArrayBufferStager):
                wr.buffer_stager.defer_checksums = True
                deferred.append(wr.buffer_stager)
        if multi:
            late_checksums = _LateChecksums(comm, take_id, deferred)
            if abort_ctx is not None:
                abort_ctx.late_checksums = late_checksums

    memory_budget = get_process_memory_budget_bytes(
        comm, local_world_size=local_world_size
    )
    mark("prepare", write_reqs=len(write_reqs))
    # Async-take scheduling mode. PIPELINED (the default async path):
    # the blocked window stages only a TPUSNAP_ASYNC_STAGE_WINDOW_BYTES
    # window of write requests before control returns; the remaining
    # windows clone on the background drain, interleaved with their
    # storage I/O — blocked time and clone RSS are O(window), not
    # O(state). Incremental takes cannot pipeline: their dedup
    # decisions mutate entry locations at stage time and must be final
    # before the manifest gather below, so they keep the strict
    # stage-everything-first mode (their blocked window is inherently
    # the hash pass). A window of 0 also restores strict semantics.
    from .knobs import get_async_stage_window_bytes

    pipelined = (
        is_async_snapshot
        and incremental_from is None
        and get_async_stage_window_bytes() is not None
    )
    stage_eagerly = None
    if pipelined and multi:
        # Multi-process manifests gather BY VALUE right after this call
        # returns: stagers that annotate entries at stage time (slabs,
        # objects — everything that does not defer its checksums to the
        # write path) must stage inside the blocked window or their
        # values would miss the gathered manifest. Deferring array
        # stagers transport theirs through _LateChecksums instead.
        stage_eagerly = lambda wr: not getattr(  # noqa: E731
            wr.buffer_stager, "defer_checksums", False
        )
    pending_io_work = sync_execute_write_reqs(
        write_reqs,
        storage,
        memory_budget,
        rank,
        event_loop,
        # Non-pipelined async takes: training is blocked until staging
        # completes, so writes wait their turn (they drain in the
        # background via PendingIOWork) instead of stealing CPU from
        # the staging pass — see scheduler._WriteScheduler.
        prioritize_staging=is_async_snapshot and not pipelined,
        pipelined_staging=pipelined,
        stage_eagerly=stage_eagerly,
    )
    # The manifest is gathered once sync_execute returns (storage I/O —
    # and, for pipelined async takes, residual staging windows — may
    # still be in flight): stagers whose entry annotations must land in
    # the gathered manifest have staged by now (everything, for sync and
    # incremental takes; the eager set above for pipelined multi-process
    # takes — single-process manifests share the entry OBJECTS, whose
    # late annotations land before the commit encodes them). The
    # reference gathers before scheduling (snapshot.py:842-853) only
    # because its entries are final at prepare time.
    # The "stage" phase is the window async_take blocks training on
    # (first-window-staged for pipelined takes, staging-complete
    # otherwise); the scheduler's "stage_blocked"/"stage_window" op
    # spans are the interior measurements.
    mark("stage", write_reqs=len(write_reqs))
    from .knobs import get_rank_failure_policy

    if (
        multi
        and abort_ctx is not None
        and abort_ctx.liveness is not None
        and (
            (incremental_from is None and not is_async_snapshot)
            or stream_capture
        )
        and get_rank_failure_policy() == "degrade"
    ):
        # Everything a degraded commit needs is final here — armed
        # BEFORE the manifest gather, the first all-ranks wait a dead
        # peer can strand: from this point a RankFailedError in any
        # collective or commit wait can hand the survivors a complete
        # recovery context. Armed ONLY under the degrade policy (the
        # retained dropped reqs pin the caller's replicated buffers
        # across the commit window — a cost abort-mode users must not
        # pay) and ONLY for sync takes: an async caller may mutate
        # host-aliasing state the moment control returns, so adoption's
        # re-staging could capture post-return bytes for the adopted
        # values while the rest of the snapshot holds the capture-time
        # state — async rank failures abort fast instead (still
        # seconds, torn and salvageable). Incremental takes never
        # degrade either (their dedup decisions reference per-rank base
        # views the dead rank's evidence is part of).
        #
        # STREAM CAPTURES (`stream_capture=True`, delta-stream epoch
        # micro-commits) are the deliberate exception to both
        # exclusions, because the stream pins both hazards shut:
        # `_force_clone_staging` freezes every byte into take-owned
        # clones before control returns (adoption re-stages CLONED
        # capture-time bytes, never post-return caller state), and the
        # stream's epoch protocol hands every member the same parent
        # member as the dedup base, with replicated state SPMD-
        # identical across members — so a survivor's dedup view of a
        # replicated entry is byte-for-byte the dead rank's. A sharded
        # leaf still refuses inside the degraded commit itself
        # (_degrade_eligible), aborting to a torn, salvageable epoch.
        abort_ctx.degrade = _DegradeContext(
            comm=comm,
            take_id=take_id,
            storage=storage,
            event_loop=event_loop,
            entries=entries,
            dropped_replicated=dropped_replicated,
            assignment=assignment,
            memory_budget=memory_budget,
            extras=dict(extras) if extras else None,
            pending_io_work=pending_io_work,
        )
    global_manifest = _gather_manifest(entries, comm)
    mark("manifest_gather")
    import time

    metadata = SnapshotMetadata(
        version=__version__,
        world_size=comm.world_size,
        manifest=global_manifest,
        created_at=time.time(),
        # Record which base roots the external references point into:
        # retention/info/materialize then never parse roots out of
        # location strings (ambiguous when a base path contains a
        # numeric directory). Computed from the gathered manifest, so
        # identical on the rank that commits.
        base_roots=_referenced_base_roots(
            global_manifest, base_root_candidates
        )
        or None,
        # Caller-provided sidecar data (e.g. a delta stream's chain
        # fields) — merged under, never over, the commit-time additions
        # (the telemetry rollup lands on top of this dict).
        extras=dict(extras) if extras else None,
    )
    mark("metadata")
    tele_commit = _TelemetryCommit(
        mark.rec, comm, take_id, progress=progress_monitor
    )
    if abort_ctx is not None:
        abort_ctx.tele_commit = tele_commit
    return pending_io_work, metadata, path, storage, late_checksums, tele_commit


def _referenced_base_roots(
    manifest: Manifest, candidates: List[str]
) -> List[str]:
    """The subset of candidate base roots actually referenced by the
    manifest's external (``../``) blob locations — matched with the
    SAME longest-prefix rule readers use (``base_root_of_location``),
    so what the writer records is byte-identical to what a reader
    resolves."""
    if not candidates:
        return []
    from .inspect import base_root_of_location

    roots = set()
    for entry in manifest.values():
        for t in _prev_entry_tensors(entry):
            loc = t.location
            if not loc.startswith("../"):
                continue
            matched = base_root_of_location(loc, known_roots=candidates)
            if matched in candidates:
                roots.add(matched)
    return sorted(roots)


def _relative_ref_prefix(base_path: str, new_path: str) -> str:
    """Relative reference from the NEW snapshot root to the BASE
    snapshot root (``"../step_1000"`` for siblings). Cross-snapshot blob
    references are stored relative so a snapshot tree moves/renames as a
    unit; both snapshots must live on the same scheme and bucket/host."""
    import os
    import posixpath
    from urllib.parse import urlsplit

    # Write-back tier URLs are not urlsplit-parseable (the scheme embeds
    # a path); do the relative math on the LOCAL mirror dirs — the
    # mirror layout guarantees the same relative relationship holds in
    # the remote tier, so one recorded reference serves both.
    from .tiering import parse_tier_url

    try:
        for is_base, url in ((True, base_path), (False, new_path)):
            spec = parse_tier_url(url)
            if spec is not None:
                if is_base:
                    base_path = spec.local_dir
                else:
                    new_path = spec.local_dir
    except ValueError:
        pass  # malformed tier URL: fall through to the plain-path error

    a, b = urlsplit(base_path), urlsplit(new_path)
    if a.scheme != b.scheme or a.netloc != b.netloc:
        raise ValueError(
            f"incremental_from {base_path!r} must share the scheme and "
            f"bucket/host of the snapshot path {new_path!r}"
        )
    if a.scheme in ("", "file"):
        pa, pb = os.path.abspath(a.path or base_path), os.path.abspath(
            b.path or new_path
        )
    else:
        pa, pb = a.path, b.path
    rel = posixpath.relpath(pa, pb)
    if rel == ".":
        raise ValueError(
            "incremental_from must name a different snapshot than the one "
            "being taken"
        )
    return rel


def _rewrite_entry_locations(entry: Entry, rel_prefix: str) -> Entry:
    """Deep copy of ``entry`` with every blob location re-expressed
    relative to the new snapshot root (collapsing chained references:
    a base that itself references an older base resolves to the older
    one directly, so incremental chains do not deepen lookups)."""
    import copy
    import posixpath

    from .manifest import ChunkedTensorEntry, ObjectEntry, ShardedEntry, TensorEntry

    e = copy.deepcopy(entry)

    def fix(t):
        t.location = posixpath.normpath(posixpath.join(rel_prefix, t.location))

    if isinstance(e, (TensorEntry, ObjectEntry)):
        fix(e)
    elif isinstance(e, ChunkedTensorEntry):
        for c in e.chunks:
            fix(c.tensor)
    elif isinstance(e, ShardedEntry):
        for s in e.shards:
            fix(s.tensor)
    return e


def _load_prev_entries(
    incremental_from: str,
    storage_options: Optional[Dict[str, Any]],
    rank: int,
    new_path: str,
    event_loop: asyncio.AbstractEventLoop,
):
    """This rank's manifest view of the base snapshot (replicated
    re-expansion + sharded merge, like restore uses), with every blob
    location rewritten relative to the new snapshot root — ready to hand
    to ``prepare_write`` as dedup candidates. Returns
    ``(entries, base_root_candidates)``: the candidates are every base
    root a rewritten location can point into — the base itself plus the
    base's own recorded roots (chained references collapse through
    them), re-expressed relative to the new snapshot."""
    import posixpath

    rel_prefix = _relative_ref_prefix(incremental_from, new_path)
    storage = url_to_storage_plugin_in_event_loop(
        incremental_from, event_loop, storage_options
    )
    try:
        from .manifest import decode_metadata

        read_io = ReadIO(path=SNAPSHOT_METADATA_FNAME)
        try:
            storage.sync_read(read_io, event_loop)
            prev_md = decode_metadata(read_io.buf.getvalue())
        except Exception as e:
            raise RuntimeError(
                f"incremental_from={incremental_from!r} is not a readable "
                "snapshot (missing or corrupt .snapshot_metadata)"
            ) from e
    finally:
        storage.sync_close(event_loop)
    view = get_manifest_for_rank(prev_md, rank)

    # Dedup compares stage-time checksums against the base's. A base
    # taken with checksums disabled (or by a build with a different
    # checksum algorithm) can never match — every blob would silently
    # rewrite in full, the exact outcome incremental_from exists to
    # avoid. Refuse up front while the user can still fix it.
    from . import _native
    from .inspect import entry_nbytes

    algo_prefix = _native.checksum_algorithm() + ":"
    blob_entries = [e for e in view.values() if entry_nbytes(e) > 0]
    usable = any(
        (t.checksum or "").startswith(algo_prefix)
        for e in blob_entries
        for t in _prev_entry_tensors(e)
    )
    if blob_entries and not usable:
        raise ValueError(
            f"incremental_from={incremental_from!r} carries no "
            f"{algo_prefix[:-1]} checksums (taken with checksums disabled "
            "or by a different build?) — dedup is impossible, every blob "
            "would silently rewrite in full"
        )
    candidates = [rel_prefix] + [
        posixpath.normpath(posixpath.join(rel_prefix, r))
        for r in (prev_md.base_roots or [])
    ]
    return (
        {p: _rewrite_entry_locations(e, rel_prefix) for p, e in view.items()},
        candidates,
    )


def _prev_entry_tensors(entry: Entry):
    from .manifest import (
        ChunkedTensorEntry,
        ObjectEntry,
        ShardedEntry,
        TensorEntry,
    )

    if isinstance(entry, (TensorEntry, ObjectEntry)):
        yield entry
    elif isinstance(entry, ChunkedTensorEntry):
        for c in entry.chunks:
            yield c.tensor
    elif isinstance(entry, ShardedEntry):
        for s in entry.shards:
            yield s.tensor


def _gather_manifest(entries: Manifest, comm: Communicator) -> Manifest:
    """All-gather per-rank manifests; key by ``rank/logical_path``;
    consolidate replicated entries onto rank 0, preferring the writer's
    (possibly slab-batched) entry version (reference :842-853,
    partitioner.py:262-303)."""
    from .partitioner import consolidate_replicated_entries

    if comm.world_size == 1:
        per_rank = [entries]
    else:
        per_rank = comm.all_gather_object(entries)
    return consolidate_replicated_entries(per_rank)


class _LateChecksums:
    """Transports write-path-deferred checksums into every rank's
    manifest before the metadata commit (VERDICT r4: deferral was
    restricted to world_size == 1 because multi-process manifests
    gather by VALUE at staging-complete, before the write path has
    hashed anything — so multi-process takes paid the whole hash pass
    inside the blocked window).

    Pure KV traffic riding the commit protocol's existing
    synchronization — zero extra collectives, usable from the async
    commit's background thread:

    - after a rank's writes drain (all its late checksums recorded in
      its own entry objects), ``publish`` puts one blob of
      {location: field tuple} under a take-scoped key;
    - after the commit barrier's arrive phase (every rank arrived ⟹
      every rank published), EVERY rank ``apply``s: ONE ``try_get_dir``
      RPC collects every rank's blob (not world_size serial gets — the
      O(N²) pattern ``all_gather_object`` was engineered away from)
      and patches that rank's stale by-value manifest copy by blob
      location. Rank 0's patch is load-bearing (it writes the file);
      non-leader patches let the take hand every rank a handle with
      CACHED metadata instead of world_size−1 metadata GETs against
      cloud storage on first access (ADVICE r5 #4) — a non-leader
      patch failure just falls back to the lazy file read;
    - ``cleanup`` (rank 0 only, strictly after the SECOND commit
      barrier — every rank passed it ⟹ every rank has read the blobs)
      DELETES the key prefix, so the coordination service does not
      accumulate one blob per rank per take for the job's lifetime.

    ``take_id`` is agreed via the take's existing G1 gather (rank 0's
    value), not a new broadcast. Every rank publishes — possibly an
    empty dict — whenever deferral is enabled, so rank 0 can detect a
    missing blob as an error rather than a slow rank."""

    def __init__(self, comm: Communicator, take_id: str, stagers) -> None:
        self.comm = comm
        self.take_id = take_id
        self.stagers = stagers

    @property
    def active(self) -> bool:
        return self.comm.world_size > 1

    def _key(self, rank: int) -> str:
        return f"tpusnap_late_cs/{self.take_id}/{rank}"

    def publish(self) -> None:
        if not self.active:
            return
        import pickle

        fields = {}
        for st in self.stagers:
            e = st.entry
            if e is None or e.checksum is None:
                continue
            fields[e.location] = (
                e.checksum,
                e.tile_rows,
                e.tile_checksums,
                e.dedup_hash,
                # Without the per-tile hashes the committed base loses
                # tile-grain dedup for the NEXT increment (the 64-bit
                # evidence rule would force a whole-blob rewrite).
                e.tile_dedup_hashes,
                # Compressed-blob layout fields: a compressed stager
                # annotates these at stage time (fused with the codec
                # pass) but pipelines like any deferring stager, so
                # they ride the same KV transport into every rank's
                # by-value manifest copy.
                e.codec,
                e.uncompressed_nbytes,
                e.comp_tile_sizes,
            )
        _get_kv_store(self.comm).set(
            self._key(self.comm.rank), pickle.dumps(fields)
        )

    def _prefix(self) -> str:
        return f"tpusnap_late_cs/{self.take_id}/"

    def discard(self) -> None:
        """Abort path: best-effort removal of this rank's published blob
        — the commit that would have consumed and deleted it will never
        run, and the coordination service must not accumulate one blob
        per rank per aborted take."""
        if not self.active:
            return
        try:
            _get_kv_store(self.comm).delete_prefix(self._key(self.comm.rank))
        except Exception:
            pass

    def apply(self, manifest: Manifest) -> None:
        """Patch this rank's manifest copy from the published blobs.
        Callers hold proof every rank published (all ranks arrived at
        the commit barrier). Read-only on the KV store — see
        ``cleanup`` for the deletion."""
        if not self.active:
            return
        import pickle

        from .manifest import ChunkedTensorEntry, ShardedEntry, TensorEntry

        by_loc: Dict[str, TensorEntry] = {}
        for entry in manifest.values():
            if isinstance(entry, TensorEntry):
                tes = [entry]
            elif isinstance(entry, ChunkedTensorEntry):
                tes = [c.tensor for c in entry.chunks]
            elif isinstance(entry, ShardedEntry):
                tes = [s.tensor for s in entry.shards]
            else:
                continue
            for te in tes:
                by_loc[te.location] = te
        store = _get_kv_store(self.comm)
        blobs = store.try_get_dir(self._prefix())
        if blobs is None or len(blobs) < self.comm.world_size:
            # Backend without dir-get (or a torn listing): per-key
            # fallback.
            blobs = {
                self._key(r): store.get(self._key(r), timeout_sec=120.0)
                for r in range(self.comm.world_size)
            }
        for raw in blobs.values():
            for loc, fields in pickle.loads(raw).items():
                cs, tr, tcs, dh, tdh = fields[:5]
                codec, unb, cts = (
                    fields[5:8] if len(fields) >= 8 else (None, None, None)
                )
                te = by_loc.get(loc)
                if te is None:
                    continue  # e.g. an elastic reader's partial view
                if te.checksum is None:
                    te.checksum = cs
                    te.tile_rows = tr
                    te.tile_checksums = tcs
                if te.dedup_hash is None:
                    te.dedup_hash = dh
                if te.tile_dedup_hashes is None:
                    te.tile_dedup_hashes = tdh
                if te.codec is None and codec is not None:
                    te.codec = codec
                    te.uncompressed_nbytes = unb
                    te.comp_tile_sizes = cts

    def cleanup(self) -> None:
        """Leader-only, strictly after the final commit barrier (every
        rank passed it ⟹ every rank has applied): delete the take-scoped
        keys so the coordination service does not grow per take."""
        if not self.active:
            return
        _get_kv_store(self.comm).delete_prefix(self._prefix())


_NO_LATE_CHECKSUMS = None  # single-process takes thread None through


class _TelemetryCommit:
    """Transport for per-take telemetry (:mod:`tpusnap.telemetry`),
    riding the commit protocol exactly like :class:`_LateChecksums`:

    - ``persist`` (every rank, writes drained, BEFORE the commit
      barrier): freeze the recorder, write this rank's Chrome trace to
      ``.tpusnap/telemetry/rank_<k>.json`` through the take's own
      storage plugin, and publish the compact summary under a
      take-scoped KV key. Persisting before the barrier preserves the
      metadata-written-last invariant: an abort can orphan a trace
      file (registered for the abort path's blob cleanup), but a
      committed snapshot never references state that predates its
      traces.
    - ``apply`` (rank 0, after the barrier's arrive ⟹ every rank
      published): ONE ``try_get_dir`` collects the summaries, the
      cross-rank rollup lands in ``metadata.extras["telemetry"]``, and
      the KV prefix is deleted.

    Everything is best-effort: telemetry failures log and never fail a
    take."""

    def __init__(
        self,
        tele: Optional[telemetry.TakeTelemetry],
        comm: Communicator,
        take_id: Optional[str],
        progress=None,
    ) -> None:
        self.tele = tele
        self.comm = comm
        self.take_id = take_id
        self.progress = progress
        self._summary: Optional[Dict[str, Any]] = None

    def finish_progress(self, state: str = "committed") -> None:
        """Publish the final heartbeat (100% at commit) and stop the
        pump; idempotent and best-effort like everything here."""
        if self.progress is not None:
            try:
                self.progress.finish(state)
            except Exception:
                pass

    def stop_progress(self) -> None:
        if self.progress is not None:
            try:
                self.progress.stop()
            except Exception:
                pass

    def _prefix(self) -> str:
        return f"tpusnap_tele/{self.take_id}/"

    def _key(self, rank: int) -> str:
        return f"{self._prefix()}{rank}"

    def persist(
        self,
        storage: StoragePlugin,
        event_loop: asyncio.AbstractEventLoop,
        abort_ctx: Optional["_TakeAbortContext"] = None,
        prep_start: Optional[float] = None,
    ) -> None:
        if self.tele is None:
            return
        try:
            self.tele.finalize()
            if prep_start is not None:
                # Tail phase (durable dirent flush + late-checksum
                # publish, between the I/O drain and this freeze) so the
                # phases tile the whole persisted wall-clock.
                self.tele.record_span(
                    "commit_prep",
                    prep_start,
                    max(self.tele.take_wall_s - prep_start, 0.0),
                    phase=True,
                )
            self._summary = self.tele.summary()
        except Exception:
            logger.warning("Telemetry summary failed (non-fatal)", exc_info=True)
            return
        if self.tele.enabled:
            from .telemetry import telemetry_rank_path

            trace_path = telemetry_rank_path(self.tele.rank)
            if abort_ctx is not None:
                # An aborting take deletes its staged blobs so the path
                # stays reusable; the trace file is cleaned up with them.
                abort_ctx.write_paths.append(trace_path)
            try:
                storage.sync_write(
                    WriteIO(path=trace_path, buf=self.tele.to_json().encode("utf-8")),
                    event_loop,
                )
            except Exception:
                logger.warning(
                    "Failed to persist telemetry trace %r (non-fatal)",
                    trace_path,
                    exc_info=True,
                )
        if self.comm.world_size > 1 and self.take_id is not None:
            import pickle

            try:
                _get_kv_store(self.comm).set(
                    self._key(self.comm.rank), pickle.dumps(self._summary)
                )
            except Exception:
                logger.warning(
                    "Failed to publish telemetry summary (non-fatal)",
                    exc_info=True,
                )

    def apply(self, metadata: SnapshotMetadata) -> bool:
        """Every rank, after the commit barrier's arrive phase (all
        ranks published): fold the cross-rank rollup into THIS rank's
        metadata copy. Rank 0's fold lands in the committed file (and
        tolerates a partial KV read — committing SOME rollup beats
        failing the take); a NON-LEADER whose KV read came back
        incomplete returns False WITHOUT folding, so the caller drops
        its cached copy rather than caching a rollup that diverges from
        the committed file (ADVICE r5 #4). Read-only on the KV store —
        ``cleanup`` deletes the prefix."""
        if self.tele is None:
            # Telemetry-off take: no rank published a summary and the
            # committed file carries no rollup — nothing to fold, and
            # the empty KV prefix must not read as a failed patch.
            return True
        summaries = []
        if self.comm.world_size > 1 and self.take_id is not None:
            import pickle

            try:
                store = _get_kv_store(self.comm)
                blobs = store.try_get_dir(self._prefix())
                for _, raw in sorted((blobs or {}).items()):
                    try:
                        summaries.append(pickle.loads(raw))
                    except Exception:
                        pass
            except Exception:
                blobs = None
                summaries = []
            if (
                self.comm.rank != 0
                and len(summaries) < self.comm.world_size
            ):
                return False
        if not summaries and self._summary is not None:
            summaries = [self._summary]
        try:
            rollup = telemetry.rollup_summaries(summaries)
        except Exception:
            logger.warning("Telemetry rollup failed (non-fatal)", exc_info=True)
            return self.comm.rank == 0
        if rollup:
            metadata.extras = dict(metadata.extras or {})
            metadata.extras["telemetry"] = rollup
        return True

    def cleanup(self) -> None:
        """Leader-only, strictly after the final commit barrier: every
        rank has folded its rollup, delete the take-scoped keys."""
        if self.comm.world_size > 1 and self.take_id is not None:
            try:
                _get_kv_store(self.comm).delete_prefix(self._prefix())
            except Exception:
                logger.debug(
                    "telemetry KV cleanup failed (non-fatal)", exc_info=True
                )

    def discard(self) -> None:
        """Abort path: drop this rank's published summary blob."""
        if self.comm.world_size > 1 and self.take_id is not None:
            try:
                _get_kv_store(self.comm).delete_prefix(self._key(self.comm.rank))
            except Exception:
                pass


# ----------------------------------------------------- degraded commit


class _DegradeContext:
    """Everything the survivors of a rank failure need to finish a
    replicated-only take without the dead rank(s): the fully-annotated
    local manifest (entries carry their checksums once writes drain),
    the partition plan, and this rank's UNSTAGED write requests for
    replicated units assigned to other ranks — identical bytes, so any
    survivor can adopt a dead writer's assignments."""

    def __init__(
        self,
        comm: Communicator,
        take_id: str,
        storage: StoragePlugin,
        event_loop: asyncio.AbstractEventLoop,
        entries: Manifest,
        dropped_replicated: Dict[str, List],
        assignment: Dict[str, int],
        memory_budget: int,
        extras: Optional[Dict[str, Any]],
        pending_io_work: PendingIOWork,
    ) -> None:
        self.comm = comm
        self.take_id = take_id
        self.storage = storage
        self.event_loop = event_loop
        self.entries = entries
        self.dropped_replicated = dropped_replicated
        self.assignment = assignment
        self.memory_budget = memory_budget
        self.extras = extras
        self.pending_io_work = pending_io_work


def _degrade_eligible(per_rank_entries: List[Manifest]) -> Optional[str]:
    """None when every survivor leaf entry is replicated (the SPMD
    program shape proves the dead rank's were too — its bytes exist on
    every survivor); otherwise the reason degrading is impossible. A
    sharded or per-rank-unique entry on any survivor means the dead
    rank held unique partitions whose bytes died with it."""
    from .manifest import PrimitiveEntry

    for entries in per_rank_entries:
        for path, entry in entries.items():
            if is_container_entry(entry):
                continue
            if is_replicated(entry):
                continue
            kind = type(entry).__name__
            if isinstance(entry, PrimitiveEntry):
                return (
                    f"{path!r} is a per-rank primitive (not replicated-"
                    "glob-marked); the dead rank's value is unknowable"
                )
            return f"{path!r} is {kind}: the dead rank held unique state"
    return None


def _degraded_prefix(take_id: str) -> str:
    return f"tpusnap_degraded/{take_id}"


def _maybe_degraded_commit(
    abort_ctx: Optional["_TakeAbortContext"],
    exc: RankFailedError,
) -> Optional[SnapshotMetadata]:
    """Entry point for both commit paths' ``except RankFailedError``:
    returns the committed (degraded) metadata when the policy allows
    and the take is eligible, None when degrade mode is off or the
    failure predates the recovery context. Raises (RankFailedError with
    the eligibility reason, or whatever the degraded protocol hit) when
    degrade was attempted and could not complete — the caller then
    aborts to a torn state exactly as in abort mode."""
    from .knobs import get_rank_failure_policy

    if (
        abort_ctx is None
        or abort_ctx.degrade is None
        or abort_ctx.liveness is None
        or get_rank_failure_policy() != "degrade"
    ):
        return None
    return _degraded_commit(abort_ctx, exc)


def _degraded_commit(
    abort_ctx: "_TakeAbortContext", exc: RankFailedError
) -> SnapshotMetadata:
    """Complete a replicated-only take on the survivor set.

    Pure KV + storage traffic over take-scoped keys (legal from the
    async commit's background thread, independent of the communicator's
    possibly-desynced sequence counters):

    1. every survivor publishes its fully-annotated local manifest and
       meets a survivor-set LinearBarrier (liveness-watched, with the
       acknowledged dead set excluded);
    2. eligibility: every survivor leaf must be replicated — else raise
       (abort to torn; fsck/timeline name the dead rank);
    3. adoption: units the dead rank(s) were assigned are re-planned
       deterministically across the survivors
       (``partitioner.reassign_dead_units``); each adopter stages and
       writes its own identical-bytes copies (journal evidence recorded
       as usual) and publishes the adopted entry versions;
    4. the new leader (min survivor) consolidates the survivor
       manifests, substitutes the adopted entries, records the adoption
       under ``extras["degraded"]``, and commits; a final barrier gates
       journal/KV cleanup.

    All survivors compute every decision from identical gathered inputs
    — no broadcasts. A survivor whose dead-set observation diverges
    (two near-simultaneous failures racing detection) parks in a
    barrier the others never join and aborts at the barrier timeout:
    degraded commit fails safe to torn, never to a wrong manifest."""
    import pickle
    import time as _time

    from . import flight as _flight_mod
    from .partitioner import (
        consolidate_replicated_entries,
        reassign_dead_units,
    )

    ctx = abort_ctx.degrade
    liveness = abort_ctx.liveness
    comm, rank = ctx.comm, ctx.comm.rank
    dead = sorted(set(exc.ranks) | set(liveness.expired()))
    live = sorted(set(range(comm.world_size)) - set(dead))
    if rank not in live or not dead:
        raise exc
    leader = live[0]
    logger.warning(
        "tpusnap degraded commit: rank(s) %s died during take %s; "
        "%d survivor(s) attempting to complete it (leader: rank %d)",
        dead,
        ctx.take_id[:8],
        len(live),
        leader,
    )
    _flight_mod.record("degraded_commit", op="start", dead_ranks=dead)
    kv = _get_kv_store(comm)
    prefix = _degraded_prefix(ctx.take_id)
    watchers = [liveness.watcher(exclude=set(dead))]
    if abort_ctx.monitor is not None:
        watchers.append(abort_ctx.monitor.check)

    def barrier(name: str) -> None:
        b = LinearBarrier(
            store=kv,
            prefix=f"{prefix}/{name}",
            rank=rank,
            world_size=comm.world_size,
            ranks=live,
            watchers=watchers,
        )
        b.arrive()
        b.depart()

    # 0. This rank's writes must be fully drained — the published
    # entries carry their write-path checksums only then.
    if not ctx.pending_io_work.drained():
        ctx.pending_io_work.sync_complete(ctx.event_loop)

    # 1. Publish + gather the survivor manifests.
    kv.set(f"{prefix}/m/{rank}", pickle.dumps(ctx.entries))
    barrier("b1")
    blobs = kv.try_get_dir(f"{prefix}/m/") or {}
    per_rank: List[Manifest] = [{} for _ in range(comm.world_size)]
    got = set()
    for key, raw in blobs.items():
        try:
            r = int(key.rsplit("/", 1)[-1])
        except ValueError:
            continue
        if r in live:
            per_rank[r] = pickle.loads(raw)
            got.add(r)
    for r in live:
        if r not in got:
            # Torn dir listing (the barrier proved the publish): per-key
            # fallback, bounded.
            per_rank[r] = pickle.loads(
                kv.get(f"{prefix}/m/{r}", timeout_sec=120.0)
            )

    # 2. Eligibility — identical verdict on every survivor.
    reason = _degrade_eligible([per_rank[r] for r in live])
    if reason is not None:
        _flight_mod.record("degraded_commit", op="refused", reason=reason)
        raise RankFailedError(
            dead,
            ctx.take_id,
            detail=f"degrade refused: {reason}; aborting to a torn state",
        ) from exc

    # 3. Adoption: deterministic re-plan, then each adopter stages and
    # writes its own replicated copies of the dead writers' units.
    adoption = reassign_dead_units(ctx.assignment, dead, live)
    my_units = sorted(u for u, w in adoption.items() if w == rank)
    my_reqs = [
        wr for u in my_units for wr in ctx.dropped_replicated.get(u, [])
    ]
    if my_reqs:
        adopt_work = sync_execute_write_reqs(
            my_reqs,
            ctx.storage,
            ctx.memory_budget,
            rank,
            ctx.event_loop,
        )
        adopt_work.sync_complete(ctx.event_loop)
    adopted_payload = {}
    for u in adoption:
        if adoption[u] != rank:
            continue
        path, _, chunk = u.partition("::")
        entry = ctx.entries.get(path)
        if entry is None:
            continue
        adopted_payload[u] = entry
    kv.set(f"{prefix}/a/{rank}", pickle.dumps(adopted_payload))
    barrier("b2")

    # 4. Every survivor builds the identical degraded manifest (the
    # leader's copy is the one that commits; the others cache it).
    # Replicated entries consolidate into rank 0's tree — when rank 0
    # itself died, stand the new leader's (SPMD-identical) manifest in
    # for slot 0 so the replicated tree still materializes.
    if 0 in dead:
        per_rank[0] = per_rank[leader]
    global_manifest = consolidate_replicated_entries(per_rank)
    # Same torn-listing defense as the /m/ gather: barrier b2 proved
    # every survivor published, so a rank missing from the dir read
    # gets a bounded per-key fallback — and an unreadable blob RAISES
    # (degrade fails safe to torn) rather than silently committing a
    # manifest missing that adopter's substitutions.
    adopted_blobs = kv.try_get_dir(f"{prefix}/a/") or {}
    adopted_by_rank: Dict[int, bytes] = {}
    for key, raw in adopted_blobs.items():
        try:
            r = int(key.rsplit("/", 1)[-1])
        except ValueError:
            continue
        if r in live:
            adopted_by_rank[r] = raw
    for r in live:
        if r not in adopted_by_rank:
            adopted_by_rank[r] = kv.get(
                f"{prefix}/a/{r}", timeout_sec=120.0
            )
    n_adopted = 0
    for _r, raw in sorted(adopted_by_rank.items()):
        payload = pickle.loads(raw)
        for unit, entry in sorted(payload.items()):
            path, _, chunk = unit.partition("::")
            gkey = f"0/{path}"
            if gkey not in global_manifest:
                continue
            n_adopted += 1
            if chunk:
                # Chunk-grain adoption: substitute only the dead
                # writer's chunk; live writers' chunks keep their
                # (possibly annotated) versions.
                idx = int(chunk)
                cur = global_manifest[gkey]
                if hasattr(cur, "chunks") and idx < len(cur.chunks):
                    cur.chunks[idx] = entry.chunks[idx]
            else:
                # Whole-entry adoption: the authoritative (dead
                # writer's) version may reference a slab or carry stale
                # annotations — the adopter's entry describes the blob
                # it actually wrote.
                global_manifest[gkey] = entry
    extras = dict(ctx.extras or {})
    extras["degraded"] = {
        "dead_ranks": dead,
        "live_ranks": live,
        "adopted_units": sorted(adoption),
        "adopters": {u: w for u, w in sorted(adoption.items())},
    }
    metadata = SnapshotMetadata(
        version=__version__,
        world_size=comm.world_size,
        manifest=global_manifest,
        created_at=_time.time(),
        extras=extras,
    )
    if rank == leader:
        abort_ctx.mark_commit_started()
        _write_metadata(ctx.storage, metadata, ctx.event_loop)
    barrier("b3")
    if rank == leader:
        from .knobs import is_journal_disabled
        from .lifecycle import clear_journal

        if not is_journal_disabled():
            clear_journal(
                ctx.storage,
                ctx.event_loop,
                getattr(ctx.storage, "clear_world_size", comm.world_size),
            )
        if abort_ctx.monitor is not None:
            abort_ctx.monitor.clear()
        if abort_ctx.lease is not None:
            abort_ctx.lease.cleanup()
        # The normal commit's leader cleanup never ran: sweep this
        # take's transport prefixes (late checksums / telemetry
        # summaries some ranks may have published before the death)
        # along with the degraded protocol's own keys.
        for p in (
            prefix + "/",
            f"tpusnap_late_cs/{ctx.take_id}/",
            f"tpusnap_tele/{ctx.take_id}/",
        ):
            try:
                kv.delete_prefix(p)
            except Exception:
                logger.debug("degraded KV cleanup failed", exc_info=True)
    _flight_mod.record(
        "degraded_commit", op="committed", dead_ranks=dead, adopted=n_adopted
    )
    logger.warning(
        "tpusnap degraded commit SUCCEEDED: take %s committed by %d "
        "survivor(s); rank(s) %s's %d replicated unit(s) were adopted",
        ctx.take_id[:8],
        len(live),
        dead,
        n_adopted,
    )
    return metadata


def _record_slo_commit(
    tele: Optional[telemetry.TakeTelemetry],
    metadata: SnapshotMetadata,
    take_id: Optional[str],
    path: str,
    rank: int,
) -> None:
    """Anchor the checkpoint-SLO tracker on a definitive commit (both
    commit paths call this strictly after the metadata write, right
    where ``completed`` is set): close the interval, clear the
    data-at-risk accumulators, refresh the RTO estimate against THIS
    RANK's restore view bytes (what a recovery would actually read),
    and fold the compact ``slo`` section into the summary the history
    event records. Best-effort — never fails a take."""
    try:
        from . import slo as _slo
        from .inspect import rank_payload_nbytes

        snapshot_bytes = rank_payload_nbytes(metadata, rank)
        counters: Dict[str, int] = {}
        incremental = False
        if tele is not None:
            counters = tele.live_snapshot()["counters"]
            incremental = bool(tele.meta.get("incremental"))
        section = _slo.tracker().record_commit(
            take_id or "",
            path,
            snapshot_bytes,
            incremental=incremental,
            counters=counters,
        )
        if tele is not None:
            tele.meta["slo"] = section
    except Exception:
        logger.debug("slo commit record failed", exc_info=True)


def _write_metadata(
    storage: StoragePlugin,
    metadata: SnapshotMetadata,
    event_loop: asyncio.AbstractEventLoop,
) -> None:
    # Atomic (temp+rename on fs): a crash mid-write must not leave a
    # torn metadata file — it would be indistinguishable from corruption.
    # Durability (power-loss survival of the commit) is knob-opted: the
    # fsync after a multi-GB take flushes the storage cache of the whole
    # take (see knobs.is_durable_commit_enabled).
    from .knobs import is_durable_commit_enabled
    from .manifest import encode_metadata

    storage.sync_write_atomic(
        WriteIO(
            path=SNAPSHOT_METADATA_FNAME,
            # Self-checksummed (manifest.encode_metadata): restore/fsck
            # detect a torn or bit-rotted metadata file with a clear
            # MetadataError instead of a JSON traceback.
            buf=encode_metadata(metadata),
        ),
        event_loop,
        durable=is_durable_commit_enabled(),
    )


def load_snapshot(
    path: str,
    rank: int = 0,
    storage_options: Optional[Dict[str, Any]] = None,
    memory_budget_bytes: Optional[int] = None,
) -> Dict[str, Any]:
    """Load a whole snapshot into host memory WITHOUT the original
    program: no statefuls, no target arrays — the nested structure is
    rebuilt from the manifest (dicts/lists/tuples, host numpy leaves,
    primitives). ``rank`` selects the manifest view (replicated entries
    are visible to every rank; sharded entries come back as full dense
    arrays). The debugging/migration companion to ``restore``: inspect a
    checkpoint from a plain REPL, or feed it to another framework.

    Peak memory is the whole selected state plus transient read buffers
    (budget-gated); use ``Snapshot.read_object`` for one value.
    """
    out: Dict[str, Any] = {}
    # Out-of-band single-process tool: the no-op Communicator, NOT
    # get_communicator() — auto-detection inside a live jax.distributed
    # job would turn the budget's hostname gather into a collective that
    # only this rank executes (deadlock).
    budget = memory_budget_bytes or get_process_memory_budget_bytes(
        Communicator()
    )
    with Snapshot(path, storage_options) as snap:
        with snap._op_lock:
            event_loop, storage = snap._resources()
            metadata = snap._get_metadata(storage, event_loop)
            local_manifest = get_manifest_for_rank(metadata, rank)
            top_keys = sorted({p.split("/", 1)[0] for p in local_manifest})
            for key in top_keys:
                key_manifest = {
                    p: e
                    for p, e in local_manifest.items()
                    if p == key or p.startswith(key + "/")
                }
                out[key] = _read_and_inflate(
                    key, key_manifest, {}, storage, budget, rank, event_loop
                )
    return out


def _read_and_inflate(
    key: str,
    key_manifest: Manifest,
    target_flattened: Dict[str, Any],
    storage: StoragePlugin,
    memory_budget: int,
    rank: int,
    event_loop: asyncio.AbstractEventLoop,
    mark: Optional[telemetry.PhaseMarker] = None,
) -> Any:
    """The one read pipeline for a key's manifest subtree: prepare reads
    (against targets when given), batch, execute under the budget,
    inflate. Shared by ``restore`` (targets from the current state_dict,
    which also threads its phase marker) and ``load_snapshot`` (no
    targets, no marker)."""
    from .batcher import batch_read_requests

    read_reqs = []
    futures: Dict[str, Any] = {}
    for logical_path, entry in key_manifest.items():
        if is_container_entry(entry):
            continue
        reqs, fut = prepare_read(
            entry,
            obj_out=target_flattened.get(logical_path),
            logical_path=logical_path,
        )
        read_reqs.extend(reqs)
        futures[logical_path] = fut
    read_reqs = batch_read_requests(read_reqs)
    if mark is not None:
        mark("restore.prepare", reqs=len(read_reqs))
    sync_execute_read_reqs(read_reqs, storage, memory_budget, rank, event_loop)
    if mark is not None:
        # Storage reads + consume (deserialize/HtoD) under the budget.
        mark("restore.read", reqs=len(read_reqs))
    flattened = {p: fut.obj for p, fut in futures.items()}
    container_manifest = {
        p: e for p, e in key_manifest.items() if is_container_entry(e)
    }
    return inflate(container_manifest, flattened, prefix=key)


def _load_stateful(
    stateful: Stateful,
    key: str,
    metadata: SnapshotMetadata,
    rank: int,
    storage: StoragePlugin,
    memory_budget: int,
    event_loop: asyncio.AbstractEventLoop,
    mark: Optional[telemetry.PhaseMarker] = None,
) -> None:
    local_manifest = get_manifest_for_rank(metadata, rank)
    local_manifest = {
        p: e
        for p, e in local_manifest.items()
        if p == key or p.startswith(key + "/")
    }
    if not local_manifest:
        logger.warning("No entries for key %r in snapshot; skipping", key)
        return

    # The current state_dict provides restore targets (device placement,
    # shardings, in-place numpy buffers).
    target_manifest, target_flattened = flatten(stateful.state_dict(), prefix=key)
    handle_sharded_elasticity(local_manifest, target_flattened)
    if mark is not None:
        mark("restore.targets", key=key)

    restored = _read_and_inflate(
        key,
        local_manifest,
        target_flattened,
        storage,
        memory_budget,
        rank,
        event_loop,
        mark=mark,
    )
    stateful.load_state_dict(restored)
    if mark is not None:
        mark("restore.load", key=key)


# ------------------------------------------------------------- async commit


class _BackgroundWork:
    """Shared scaffold for the background-thread handles (async take's
    commit drain, async restore): daemon thread, exception capture,
    join-and-reraise. Subclasses implement ``_body`` and optionally
    ``_on_error`` / ``_cleanup`` (both run on the background thread)."""

    _thread_name = "tpusnap-bg"

    def _start(self) -> None:
        self._exc: Optional[BaseException] = None
        self._done = threading.Event()
        self._thread = threading.Thread(
            target=self._trampoline, name=self._thread_name, daemon=True
        )
        self._thread.start()

    def _trampoline(self) -> None:
        try:
            self._body()
        except BaseException as e:  # noqa: B902 - re-raised from wait()
            self._exc = e
            try:
                self._on_error(e)
            except Exception:
                pass
        finally:
            try:
                self._cleanup()
            except Exception:
                pass
            self._done.set()

    def _body(self) -> None:
        raise NotImplementedError

    def _on_error(self, exc: BaseException) -> None:
        pass

    def _cleanup(self) -> None:
        pass

    def _join_and_reraise(self) -> None:
        self._thread.join()
        if self._exc is not None:
            raise self._exc

    def done(self) -> bool:
        return self._done.is_set()


class PendingSnapshot(_BackgroundWork):
    """Handle for an in-flight async snapshot (reference snapshot.py:856-944).

    A background thread drains the residual staging windows of a
    pipelined take (interleaved with their storage I/O — see
    scheduler._WriteScheduler) and the remaining writes, then
    synchronizes the commit through a KV-store LinearBarrier — NO
    collectives are allowed off the main thread (reference :902). If any
    rank fails, the error poisons the barrier, ``.snapshot_metadata`` is
    never written, and ``wait()`` re-raises on every rank.
    ``staged()``/``wait_staged()`` expose the staging-complete boundary
    (content frozen); ``wait()`` the committed snapshot.
    """

    # Historically a 1800.0 literal (reference snapshot.py:857); now
    # 3x TPUSNAP_BARRIER_TIMEOUT_S (knobs.get_commit_barrier_timeout_s),
    # resolved at construction.
    _thread_name = "tpusnap-commit"

    def __init__(
        self,
        path: str,
        pending_io_work: PendingIOWork,
        metadata: SnapshotMetadata,
        storage: StoragePlugin,
        comm: Communicator,
        event_loop: asyncio.AbstractEventLoop,
        storage_options: Optional[Dict[str, Any]] = None,
        late_checksums: Optional["_LateChecksums"] = None,
        abort_ctx: Optional["_TakeAbortContext"] = None,
        tele_commit: Optional["_TelemetryCommit"] = None,
        force_clone_staging: bool = False,
    ) -> None:
        self.path = path
        self._pending_io_work = pending_io_work
        self._metadata = metadata
        self._storage = storage
        self._comm = comm
        self._event_loop = event_loop
        self._storage_options = storage_options
        self._late_checksums = late_checksums
        self._abort_ctx = abort_ctx
        self._tele_commit = tele_commit
        self._snapshot: Optional[Snapshot] = None
        # Captured at take time: under COW the staged() rendezvous must
        # report the SAFE-TO-MUTATE boundary (writes+verifies drained,
        # live bytes no longer read), not merely staging-complete.
        # A force-clone take (delta micro-commits) staged real copies,
        # so its rendezvous is the plain staging-complete boundary.
        from .knobs import is_async_cow_enabled

        self._cow_rendezvous = (
            is_async_cow_enabled() and not force_clone_staging
        )

        # Barrier identity must be agreed on the MAIN thread (this may
        # broadcast); the background thread then only touches the KV store.
        barrier_prefix = f"tpusnap_commit/{uuid.uuid4().hex}"
        barrier_prefix = comm.broadcast_object(barrier_prefix, src=0)
        # GC proof point: the commit barrier will prove consumption of
        # everything pending NOW; collectives the main thread issues
        # later (a newer take on the same communicator) stay pending.
        self._gc_epoch = comm.gc_epoch()
        from .knobs import get_commit_barrier_timeout_s

        commit_timeout = get_commit_barrier_timeout_s()
        # Peer abort records surface as TakeAbortedError — and a dead
        # peer's lease expiry as RankFailedError — from the background
        # commit's barrier waits within seconds.
        watchers = (
            abort_ctx.barrier_watchers() if abort_ctx is not None else None
        )
        self._barrier = LinearBarrier(
            store=_get_kv_store(comm),
            prefix=barrier_prefix,
            rank=comm.rank,
            world_size=comm.world_size,
            timeout_sec=commit_timeout,
            watchers=watchers or None,
        )
        # The cleanup gate (ADVICE r5 #4): after the commit barrier's
        # depart, every rank patches its local manifest copy from the
        # take-scoped KV blobs; this second barrier proves every rank
        # has READ them before rank 0 deletes the prefix.
        self._post_barrier = (
            LinearBarrier(
                store=_get_kv_store(comm),
                prefix=barrier_prefix + "-post",
                rank=comm.rank,
                world_size=comm.world_size,
                timeout_sec=commit_timeout,
                watchers=watchers or None,
            )
            if comm.world_size > 1
            else None
        )
        # The main thread is done with collectives for this take; free
        # the communicator's wait watcher for any newer take. The
        # background commit keeps abort awareness via the barrier
        # watcher above.
        if abort_ctx is not None:
            abort_ctx.disarm()
        # The background commit synchronizes through the LinearBarrier,
        # not the communicator — point the stall watchdog's straggler
        # attribution at its arrive keys.
        if tele_commit is not None and tele_commit.progress is not None:
            tele_commit.progress.add_attribution(self._barrier.current_missing)
        # Control is about to return to training: release the recorder's
        # process-global slot (a newer take may install its own); the
        # background drain records through captured references + the
        # thread-local overlay in _body.
        if tele_commit is not None and tele_commit.tele is not None:
            # The blocked window (take start → control returns here):
            # the one number async_take exists to minimize, recorded
            # before the background thread starts so the summary/history
            # field is never mutated concurrently. Regression-gated via
            # `tpusnap history --check --metric async_blocked_s`.
            tele = tele_commit.tele
            blocked_s = tele.now()
            tele.meta["async_blocked_s"] = round(blocked_s, 6)
            tele.record_span("async_blocked", 0.0, blocked_s)
            telemetry.release_global(tele)
        self._start()

    def _body(self) -> None:
        # A RankFailedError from the barrier waits here ordinarily
        # takes the normal abort path (_on_error): a plain async take
        # never runs the degraded commit — the caller may mutate
        # host-aliasing state the moment async_take returns, so
        # adoption's re-staging could capture post-return bytes (the
        # degrade context is not armed for it). Stream captures
        # (`_stream_capture=True`) DO arm it — their force-cloned
        # staging froze take-owned copies of every byte, so adoption
        # re-stages capture-time state regardless of what the caller
        # does after return — and the handler below completes the
        # micro-commit on the survivors. A failed or refused degrade
        # re-raises into the normal abort path (torn, salvageable).
        tele = self._tele_commit.tele if self._tele_commit is not None else None
        with telemetry.use(tele):
            try:
                self._body_impl()
            except RankFailedError as rank_exc:
                degraded_meta = _maybe_degraded_commit(
                    self._abort_ctx, rank_exc
                )
                if degraded_meta is None:
                    raise
                self._commit_degraded(degraded_meta)

    def _commit_degraded(self, metadata: SnapshotMetadata) -> None:
        # Mirror of the sync take's degraded tail: the survivor-set
        # protocol already wrote the metadata and cleared the journal;
        # this rank only records the commit and builds the handle.
        # Storage/event-loop teardown stays in _cleanup, as on the
        # normal path.
        self._metadata = metadata
        ctx = self._abort_ctx
        assert ctx is not None and ctx.degrade is not None
        try:
            self._comm.gc_consumed_keys(self._gc_epoch)
        except Exception:
            pass
        if self._tele_commit is not None:
            if self._tele_commit.tele is not None:
                self._tele_commit.tele.meta["completed"] = True
            _record_slo_commit(
                self._tele_commit.tele,
                metadata,
                ctx.degrade.take_id,
                self.path,
                self._comm.rank,
            )
            self._tele_commit.finish_progress()
        from . import flight as _flight_mod

        _flight_mod.recorder().end_take("committed")
        snapshot = Snapshot(self.path, self._storage_options, self._comm)
        # Every survivor built the identical degraded manifest.
        snapshot._metadata = metadata
        self._snapshot = snapshot

    def _body_impl(self) -> None:
        tele = self._tele_commit.tele if self._tele_commit is not None else None
        drain_start = tele.now() if tele is not None else 0.0
        self._pending_io_work.sync_complete(self._event_loop)
        if tele is not None:
            tele.record_span(
                "io_drain", drain_start, tele.now() - drain_start, phase=True
            )
        prep_start = tele.now() if tele is not None else None
        from .knobs import is_durable_commit_enabled

        if is_durable_commit_enabled():
            # Per-rank dirent durability before the commit barrier (see
            # the sync take's identical step).
            self._storage.sync_flush_created_dirs(self._event_loop)
        if self._late_checksums is not None:
            # Writes drained: publish this rank's deferred checksums
            # (pure KV traffic — legal off the main thread, like the
            # barrier itself).
            self._late_checksums.publish()
        if self._tele_commit is not None:
            # Writes drained: persist this rank's trace + publish its
            # summary before the commit barrier (metadata still last).
            self._tele_commit.persist(
                self._storage, self._event_loop, self._abort_ctx, prep_start
            )
        self._barrier.arrive()
        if self._comm.rank == 0:
            # arrive() returned ⟹ every rank arrived ⟹ every rank
            # published: patch the gathered manifest (one dir-get),
            # commit. The keys outlive the commit until the post
            # barrier proves every rank has read them.
            if self._late_checksums is not None:
                self._late_checksums.apply(self._metadata.manifest)
            if self._tele_commit is not None:
                self._tele_commit.apply(self._metadata)
            if self._abort_ctx is not None:
                self._abort_ctx.mark_commit_started()
            _write_metadata(self._storage, self._metadata, self._event_loop)
        self._barrier.depart()
        # depart() returned ⟹ the leader observed every arrival ⟹
        # every rank published: non-leaders patch their local manifest
        # copies too (one dir-get each), so every rank's handle carries
        # cached, fully-patched metadata instead of paying a metadata
        # GET on first access (ADVICE r5 #4). Best-effort — a failed
        # patch falls back to the lazy committed-file read.
        meta_cached = True
        if self._comm.rank != 0:
            try:
                if self._late_checksums is not None:
                    self._late_checksums.apply(self._metadata.manifest)
                if self._tele_commit is not None and not self._tele_commit.apply(
                    self._metadata
                ):
                    # Incomplete KV read: don't cache a rollup that
                    # diverges from the committed file.
                    meta_cached = False
            except Exception:
                logger.warning(
                    "Non-leader late-checksum patch failed; falling back "
                    "to reading committed metadata (non-fatal)",
                    exc_info=True,
                )
                meta_cached = False
        if self._post_barrier is not None:
            # Every rank arriving here has read the take-scoped KV
            # blobs; rank 0's arrive() returns once all have, gating
            # the deletes.
            self._post_barrier.arrive()
            if self._comm.rank == 0:
                if self._late_checksums is not None:
                    self._late_checksums.cleanup()
                if self._tele_commit is not None:
                    self._tele_commit.cleanup()
            self._post_barrier.depart()
        if self._comm.rank == 0:
            # Commit done (see the sync take's identical step): clear
            # the take journal, strictly after the metadata write.
            from .knobs import is_journal_disabled
            from .lifecycle import clear_journal

            if not is_journal_disabled():
                clear_journal(
                    self._storage,
                    self._event_loop,
                    getattr(
                        self._storage,
                        "clear_world_size",
                        self._comm.world_size,
                    ),
                )
            if (
                self._abort_ctx is not None
                and self._abort_ctx.monitor is not None
            ):
                self._abort_ctx.monitor.clear()
            if (
                self._abort_ctx is not None
                and self._abort_ctx.lease is not None
            ):
                self._abort_ctx.lease.cleanup()
        # Every rank departing proves it consumed the take's gathers
        # and the barrier-prefix broadcast; release their KV keys now
        # — no further barrier will run on this communicator, so the
        # lazy GC would otherwise never fire (and per-iteration
        # manifests would accumulate in the coordination service
        # forever). Bounded by the epoch captured at construction so
        # a newer take's in-flight keys are never touched. KV deletes
        # only — still no collectives off the main thread.
        try:
            self._comm.gc_consumed_keys(self._gc_epoch)
        except Exception:
            pass
        if self._tele_commit is not None:
            if self._tele_commit.tele is not None:
                # Commit done: eligible for the cross-run history when
                # _cleanup's end_take publishes the summary, and the
                # SLO tracker's RPO clock re-anchors here.
                self._tele_commit.tele.meta["completed"] = True
            _record_slo_commit(
                self._tele_commit.tele,
                self._metadata,
                self._tele_commit.take_id,
                self.path,
                self._comm.rank,
            )
            self._tele_commit.finish_progress()
        from . import flight as _flight_mod

        _flight_mod.recorder().end_take("committed")
        snapshot = Snapshot(self.path, self._storage_options, self._comm)
        if meta_cached:
            # Fully patched on every rank (late checksums + telemetry
            # rollup applied locally) — cache it; the rare failed
            # non-leader patch lazily reads the committed file instead.
            snapshot._metadata = self._metadata
        self._snapshot = snapshot

    def _on_error(self, exc: BaseException) -> None:
        # Publish this rank's abort record (peers' barrier watchers then
        # raise TakeAbortedError) and best-effort delete its staged
        # blobs; the metadata is never written. Without a monitor
        # (single-process, or explicit comm without abort context), fall
        # back to poisoning the barrier the classic way.
        ctx = self._abort_ctx
        if ctx is not None:
            ctx.on_failure(exc)
            if ctx.monitor is not None:
                return
        self._barrier.report_error(exc)

    def _cleanup(self) -> None:
        if self._tele_commit is not None:
            # Failure paths stopped it with an "aborted" record already
            # (abort_ctx.on_failure); this is the idempotent safety net.
            self._tele_commit.stop_progress()
        self._storage.sync_close(self._event_loop)
        self._event_loop.close()
        if self._tele_commit is not None and self._tele_commit.tele is not None:
            telemetry.end_take(self._tele_commit.tele)

    def staged(self) -> bool:
        """Whether the snapshot content is frozen — safe for the caller
        to mutate host-aliasing state IN PLACE (raw numpy buffers,
        pinned_host donation). Functional JAX updates never need this —
        the stagers hold references, and staging a donated-and-deleted
        device array fails loudly.

        Ordinarily this is staging-complete (no buffer aliases live
        arrays any more): true at construction for non-pipelined takes;
        pipelined takes (state larger than
        TPUSNAP_ASYNC_STAGE_WINDOW_BYTES) stage their residual windows
        on the background drain. Under TPUSNAP_ASYNC_COW the live bytes
        stay aliased until each blob's write+verify lands, so this
        reports THIS RANK's write-drain boundary instead (strictly
        earlier than the cross-rank commit barrier) — the rendezvous
        CONTRACT (staged() ⟹ safe to mutate) holds either way."""
        if self._cow_rendezvous:
            return self._pending_io_work.drained()
        return self._pending_io_work.staging_complete()

    def wait_staged(self, timeout: Optional[float] = None) -> bool:
        """Block until :meth:`staged` is True (or ``timeout`` elapses;
        returns whether the content froze). Re-raises the background
        failure if the drain died before staging finished — otherwise a
        crashed drain would turn this into a silent infinite wait."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            step = 0.05
            if deadline is not None:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return self.staged()
                step = min(step, remaining)
            settled = (
                self._pending_io_work.wait_drained(step)
                if self._cow_rendezvous
                else self._pending_io_work.wait_staged(step)
            )
            if settled:
                return True
            if self.done():
                self._join_and_reraise()
                return self.staged()

    def wait(self) -> Snapshot:
        self._join_and_reraise()
        assert self._snapshot is not None
        return self._snapshot


class PendingRestore(_BackgroundWork):
    """Handle for an in-flight background restore (``async_restore``).

    ``wait()`` joins the thread and re-raises any failure; the restored
    ``app_state`` must not be read before it returns. The snapshot
    handle's ``_op_lock`` serializes against concurrent
    restore/read_object/verify calls on the same handle."""

    _thread_name = "tpusnap-restore"

    def __init__(
        self,
        snapshot: Snapshot,
        app_state: AppState,
        comm: Communicator,
        memory_budget: int,
    ) -> None:
        self._snapshot = snapshot
        self._app_state = app_state
        self._comm = comm
        self._memory_budget = memory_budget
        self._start()

    def _body(self) -> None:
        with self._snapshot._op_lock:
            self._snapshot._restore_locked(
                self._app_state,
                self._comm,
                per_key_barrier=False,
                memory_budget=self._memory_budget,
            )

    def wait(self) -> None:
        self._join_and_reraise()


def _get_kv_store(comm: Communicator) -> KVStore:
    if comm.world_size == 1:
        return MemoryKVStore()
    return CoordinationKVStore()
