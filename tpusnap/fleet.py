"""Cross-job fleet observability: per-job status mirror + aggregation.

Every observability surface before this module (telemetry, heartbeats,
history, SLO sidecars, the Prometheus textfiles) is scoped to ONE job.
The north star is N concurrent jobs sharing one storage substrate, and
a shared substrate fails at fleet scale in ways no single job can see:
one job's upload backlog starves another's drain, a wedged stream
quietly grows the whole fleet's recovery point, concurrent writers
contend for the same tail latency. This module makes the fleet a
first-class, gateable observability domain:

- **Publisher** (:class:`FleetPublisher`): when ``TPUSNAP_FLEET_DIR``
  is set, rank 0 of every instrumented job mirrors its EXISTING
  heartbeat/SLO/tier publications into one compact per-job record
  (``<fleet_dir>/<job_id>.json``, atomic temp+rename) — riding the
  :meth:`~tpusnap.progress.ProgressMonitor.add_tick_hook` pump like
  the flight flush and the SLO publisher, so the fleet layer owns no
  thread and costs nothing when the knob is unset. A clean process
  exit stamps the record ``final`` (same contract as the SLO sidecar:
  a finished job is not an incident; a SIGKILLed one keeps screaming).

- **Aggregator** (:func:`read_fleet_records` → :func:`fold_fleet`):
  folds all jobs' records into fleet rollups — worst-case RPO and
  data-at-risk across jobs (exposure recomputed live from each
  record's wall anchors, with the SLO record-age treatment: ``final``
  records freeze at their write time, everything else grows), the
  aggregate upload lag behind the shared tier (bytes summed — each
  job's undrained bytes are distinct exposure — and the oldest
  commit's age), cross-job merged storage-latency histograms (the
  log2 buckets are mergeable by design: one job's p99 survives the
  fold), and concurrent-writer / degraded / paused / dead-rank
  counts. "Paused" reuses the SLO stream-cadence rule: a live stream
  that declared a cadence but has not committed for
  ``TPUSNAP_SLO_STREAM_CADENCE_X`` times it has silently stopped.

- **Gate** (:func:`evaluate_fleet`): the ``python -m tpusnap fleet
  --check`` verdict over the rollup, with the established exit
  contract — 0 healthy, 2 breach (worst RPO / aggregate lag / storage
  p99-over-p50 tail ratio past a threshold), 3 no data. The rollup
  also renders as ``scope="fleet"`` Prometheus families
  (:func:`render_fleet_prom`) for the same collectors that scrape the
  per-job textfiles.

File-based, not a server, on purpose (same argument as the Prometheus
textfile sink): checkpoint jobs are short-lived batch processes behind
schedulers and NATs. A shared directory on the substrate the jobs
already share needs no discovery, no port, no daemon, and a crashed
job's last record is exactly the evidence the fold needs.

Monotonic-only invariant (TPS002, same scope as telemetry/progress/
slo): the cross-job computations here (record staleness, exposure
since a possibly-dead job's commit anchor) are wall-timestamp
differences by necessity — cross-process, there is no shared monotonic
clock — and go through the module's injectable ``_wall`` seam.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .knobs import (
    get_fleet_dir,
    get_job_id,
    get_slo_rpo_threshold_s,
    get_slo_stream_cadence_x,
)

logger = logging.getLogger(__name__)

# Wall-clock seam: timestamps and cross-process staleness only; only
# this bare reference is allowed (TPS002).
_wall = time.time

# Heartbeat-record fields the per-job mirror copies verbatim (the
# compact subset `fleet`/`watch --fleet` render; the full record stays
# in the per-take sidecars).
_BEAT_FIELDS = (
    "rank",
    "world_size",
    "take_id",
    "state",
    "phase",
    "percent",
    "mbps",
    "bytes_written",
    "dead_ranks",
    "left_ranks",
)


class FleetPublisher:
    """Mirror of THIS job's live status into ``<fleet_dir>/<job_id>.json``
    (atomic rewrite). One per process; driven by the heartbeat pump's
    tick hook — no thread of its own. Never raises to the caller."""

    def __init__(self, fleet_dir: str, job_id: Optional[str] = None) -> None:
        self.fleet_dir = fleet_dir
        self.job_id = job_id or get_job_id()
        self._lock = threading.Lock()
        self._last_beat: Optional[Dict[str, Any]] = None

    def record_path(self) -> str:
        return os.path.join(self.fleet_dir, f"{self.job_id}.json")

    def build_record(
        self, beat: Optional[Dict[str, Any]] = None, final: bool = False
    ) -> Dict[str, Any]:
        """One compact per-job status record from the publications that
        already exist: the latest heartbeat record, the SLO tracker's
        exposure anchors, the write-back uploader's status, and the
        process-global storage-latency histograms (log2 buckets —
        mergeable across jobs by design)."""
        rec: Dict[str, Any] = {
            "v": 1,
            "job_id": self.job_id,
            "pid": os.getpid(),
            "ts": _wall(),
        }
        if beat:
            for k in _BEAT_FIELDS:
                if beat.get(k) is not None:
                    rec[k] = beat[k]
        try:
            from . import slo as _slo

            s = _slo.tracker().snapshot_state()
            rec["slo"] = {
                k: s.get(k)
                for k in (
                    "rpo_s",
                    "data_at_risk_bytes",
                    "estimated_rto_s",
                    "last_commit_ts",
                    "started_ts",
                    "commit_interval_s",
                    "stream_cadence_s",
                )
            }
        except Exception:
            logger.debug("fleet slo fold failed", exc_info=True)
        try:
            from .tiering import current_status

            t = current_status()
            if t and t.get("state") != "idle":
                rec["tier"] = {
                    k: t[k]
                    for k in ("state", "lag_bytes", "lag_seconds", "degraded")
                    if t.get(k) is not None
                }
        except Exception:
            logger.debug("fleet tier fold failed", exc_info=True)
        try:
            from .telemetry import global_io_histograms_snapshot

            hists = global_io_histograms_snapshot()
            if hists:
                rec["io_histograms"] = hists
        except Exception:
            logger.debug("fleet histogram snapshot failed", exc_info=True)
        rs = reader_stats_snapshot()
        if rs:
            rec["reader"] = {
                "bytes_read": sum(s["bytes_read"] for s in rs.values()),
                "reads": sum(s["reads"] for s in rs.values()),
                "snapshots": rs,
            }
        if final:
            rec["final"] = True
        return rec

    def publish(
        self, beat: Optional[Dict[str, Any]] = None, final: bool = False
    ) -> None:
        """Rebuild and atomically rewrite this job's record. ``beat`` is
        the freshly published heartbeat record (kept as the last-known
        progress state for beat-less publishes like the exit stamp)."""
        try:
            with self._lock:
                if beat is not None:
                    self._last_beat = dict(beat)
                rec = self.build_record(beat=self._last_beat, final=final)
                os.makedirs(self.fleet_dir, exist_ok=True)
                path = self.record_path()
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump(rec, f)
                os.replace(tmp, path)
        except Exception:
            logger.debug("fleet status publish failed", exc_info=True)


# ------------------------------------------------- process-global wiring

_publisher: Optional[FleetPublisher] = None
_pub_lock = threading.Lock()
_atexit_armed = False
_crashed = False


def publisher() -> Optional[FleetPublisher]:
    """The process-global publisher for the current ``TPUSNAP_FLEET_DIR``
    / ``TPUSNAP_JOB_ID``, or None when the layer is off. Re-created when
    either knob changes (tests flip them between takes)."""
    d = get_fleet_dir()
    if not d:
        return None
    job = get_job_id()
    global _publisher
    with _pub_lock:
        if (
            _publisher is None
            or _publisher.fleet_dir != d
            or _publisher.job_id != job
        ):
            _publisher = FleetPublisher(d, job)
        return _publisher


def reset_publisher() -> None:
    """Test aid; production code never resets."""
    global _publisher
    with _pub_lock:
        _publisher = None


def make_tick_hook():
    """The :meth:`ProgressMonitor.add_tick_hook` piggyback: republish
    this job's fleet record at the pump's own publish cadence (``record
    is not None`` — the same delta-throttle + keep-alive the heartbeat
    uses)."""

    def hook(record: Optional[Dict[str, Any]]) -> None:
        if record is None:
            return
        p = publisher()
        if p is not None:
            p.publish(beat=record)

    return hook


def attach_to_take(monitor) -> None:
    """Wire the fleet mirror onto one take's heartbeat pump. Rank 0
    only: all ranks of a job share one job id (one record per job),
    and rank 0's SLO state already carries the worst-case fold of its
    peers. No-op when ``TPUSNAP_FLEET_DIR`` is unset; best-effort like
    everything observability."""
    if monitor.rank != 0 or get_fleet_dir() is None:
        return
    monitor.add_tick_hook(make_tick_hook())
    _arm_atexit_finalizer()


def _arm_atexit_finalizer() -> None:
    """Register the clean-exit record stamp, once, and only for
    processes that actually published fleet state. Mirrors the SLO
    sidecar finalizer: an exception-crashed process must NOT stamp
    ``final`` — its last live record keeps growing exposure in the
    fold, exactly like a SIGKILL."""
    global _atexit_armed
    with _pub_lock:
        if _atexit_armed:
            return
        _atexit_armed = True
    import atexit
    import sys

    prev_hook = sys.excepthook

    def _crash_hook(exc_type, exc, tb):
        global _crashed
        _crashed = True
        prev_hook(exc_type, exc, tb)

    sys.excepthook = _crash_hook
    atexit.register(_finalize_on_exit)


def _finalize_on_exit() -> None:
    if _crashed:
        return
    p = publisher()
    if p is not None:
        p.publish(final=True)


# ---------------------------------------------------- reader attribution
#
# Reader jobs (restore / read_object consumers) have no heartbeat pump
# to ride — their fleet presence is published directly at access-ledger
# scope exit. Stats accumulate per snapshot DIGEST so the fold can
# merge amplification across readers of the same snapshot.

_reader_lock = threading.Lock()
_reader_stats: Dict[str, Dict[str, Any]] = {}


def note_reader_scope(
    snapshot_digest: str,
    snapshot_bytes: int,
    bytes_read: int,
    reads: int,
) -> None:
    """Fold one finished read scope into this process's reader stats
    and republish the job's fleet record. No-op when the fleet layer is
    off; never raises (observability stance)."""
    try:
        p = publisher()
        if p is None:
            return
        with _reader_lock:
            st = _reader_stats.setdefault(
                snapshot_digest,
                {
                    "snapshot_bytes": 0,
                    "bytes_read": 0,
                    "reads": 0,
                    "scopes": 0,
                },
            )
            st["snapshot_bytes"] = max(
                int(st["snapshot_bytes"]), int(snapshot_bytes or 0)
            )
            st["bytes_read"] += int(bytes_read)
            st["reads"] += int(reads)
            st["scopes"] += 1
        _arm_atexit_finalizer()
        p.publish()
    except Exception:
        logger.debug("fleet reader publish failed", exc_info=True)


def reader_stats_snapshot() -> Optional[Dict[str, Any]]:
    """This process's per-digest reader stats, or None when it never
    read through an access-ledger scope."""
    with _reader_lock:
        if not _reader_stats:
            return None
        return {d: dict(s) for d, s in _reader_stats.items()}


def reset_reader_stats() -> None:
    """Test aid; production code never resets."""
    with _reader_lock:
        _reader_stats.clear()


# --------------------------------------------------------------- reading


def read_fleet_records(directory: Optional[str] = None) -> List[Dict[str, Any]]:
    """All parseable per-job status records under the fleet dir, sorted
    by job id. Tolerant of torn/absent files (atomic writers, but jobs
    come and go); ``*.tmp.*`` leftovers are skipped."""
    d = directory or get_fleet_dir()
    out: List[Dict[str, Any]] = []
    if not d:
        return out
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for name in sorted(names):
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(d, name), "r") as f:
                rec = json.load(f)
            if isinstance(rec, dict) and rec.get("job_id"):
                out.append(rec)
        except Exception:
            continue
    return sorted(out, key=lambda r: str(r.get("job_id")))


def fold_fleet(
    records: List[Dict[str, Any]], now: Optional[float] = None
) -> Dict[str, Any]:
    """Fold per-job records into the fleet rollup. Exposure per job is
    recomputed LIVE from the record's wall anchors (the publishing
    process may be long dead — its frozen gauge would understate the
    fleet's recovery point); records marked ``final`` freeze at their
    write time (the SLO record-age treatment). Upload lag: bytes SUM
    (each job's undrained bytes are distinct exposure behind the shared
    tier), seconds MAX (age of the fleet's oldest undurable commit)."""
    now = _wall() if now is None else now
    stream_x = get_slo_stream_cadence_x()
    jobs: List[Dict[str, Any]] = []
    hists: List[Dict[str, Any]] = []
    for rec in records:
        slo = rec.get("slo") or {}
        final = bool(rec.get("final"))
        ts = rec.get("ts") or now
        age = max(now - ts, 0.0)
        anchor = slo.get("last_commit_ts") or slo.get("started_ts") or ts
        ref = ts if final else now
        rpo = max(ref - anchor, 0.0)
        tier = rec.get("tier") or {}
        cadence = slo.get("stream_cadence_s")
        # A LIVE stream that declared a cadence but has not committed
        # for stream_x times it has silently stopped — the fleet's
        # "paused" jobs (same rule as `slo --check`'s stream gate).
        paused = bool(
            not final
            and stream_x
            and isinstance(cadence, (int, float))
            and cadence > 0
            and rpo > stream_x * cadence
        )
        reader = rec.get("reader") or {}
        jobs.append(
            {
                "job_id": rec.get("job_id"),
                "state": "finished" if final else rec.get("state") or "unknown",
                "final": final,
                "reader": bool(reader),
                "bytes_read": int(reader.get("bytes_read") or 0),
                "ts": ts,
                "age_s": round(age, 2),
                "rank": rec.get("rank", 0),
                "world_size": rec.get("world_size", 1),
                "phase": rec.get("phase"),
                "percent": rec.get("percent"),
                "take_id": rec.get("take_id"),
                "rpo_s": round(rpo, 2),
                "data_at_risk_bytes": int(slo.get("data_at_risk_bytes") or 0),
                "estimated_rto_s": slo.get("estimated_rto_s"),
                "lag_bytes": int(tier.get("lag_bytes") or 0),
                "lag_seconds": float(tier.get("lag_seconds") or 0.0),
                "degraded": bool(tier.get("degraded")),
                "paused": paused,
                "dead_ranks": rec.get("dead_ranks") or [],
                "left_ranks": rec.get("left_ranks") or [],
                "stream_cadence_s": cadence,
            }
        )
        if rec.get("io_histograms"):
            hists.append(rec["io_histograms"])
    merged: Dict[str, Any] = {}
    storage: Dict[str, Any] = {}
    if hists:
        try:
            from .telemetry import IOStats, merge_io_histograms

            merged = merge_io_histograms(hists)
            # Per-op fleet aggregate across plugin classes: the tail
            # ratio gate wants ONE write distribution for the shared
            # substrate, not one per backend class per job.
            for op in ("write", "read"):
                agg = IOStats()
                for key, st in merged.items():
                    if key.startswith(op + "."):
                        agg.merge_dict(st)
                if agg.latency.count:
                    storage[op] = agg.to_dict()
        except Exception:
            logger.debug("fleet histogram fold failed", exc_info=True)
    worst = max(jobs, key=lambda j: j["rpo_s"], default=None)
    worst_risk = max(jobs, key=lambda j: j["data_at_risk_bytes"], default=None)
    # Read-side fold: amplification merges ACROSS readers of the same
    # snapshot (aggregate bytes read / stored bytes), keyed by the
    # snapshot-path digest the ledger scope stamped. A single reader at
    # 1.0x is healthy; ten full restores of one snapshot is 10x on the
    # serving substrate — only the cross-reader sum sees that.
    digest_reads: Dict[str, Dict[str, int]] = {}
    for rec in records:
        for digest, st in ((rec.get("reader") or {}).get("snapshots") or {}).items():
            acc = digest_reads.setdefault(
                digest, {"snapshot_bytes": 0, "bytes_read": 0}
            )
            acc["snapshot_bytes"] = max(
                acc["snapshot_bytes"], int(st.get("snapshot_bytes") or 0)
            )
            acc["bytes_read"] += int(st.get("bytes_read") or 0)
    read_amp = None
    read_amp_digest = None
    for digest, acc in digest_reads.items():
        if acc["snapshot_bytes"] <= 0:
            continue
        amp = round(acc["bytes_read"] / acc["snapshot_bytes"], 4)
        if read_amp is None or amp > read_amp:
            read_amp, read_amp_digest = amp, digest
    return {
        "v": 1,
        "ts": now,
        "n_jobs": len(jobs),
        "writers": sum(
            1 for j in jobs if not j["final"] and j["state"] == "running"
        ),
        "degraded_jobs": sum(1 for j in jobs if j["degraded"]),
        "paused_jobs": sum(1 for j in jobs if j["paused"]),
        "dead_ranks": sum(len(j["dead_ranks"]) for j in jobs),
        "worst_rpo_s": worst["rpo_s"] if worst else None,
        "worst_rpo_job": worst["job_id"] if worst else None,
        "worst_data_at_risk_bytes": (
            worst_risk["data_at_risk_bytes"] if worst_risk else None
        ),
        "worst_at_risk_job": worst_risk["job_id"] if worst_risk else None,
        "lag_bytes_total": sum(j["lag_bytes"] for j in jobs),
        "lag_seconds_max": max((j["lag_seconds"] for j in jobs), default=0.0),
        "readers": sum(1 for j in jobs if j["reader"]),
        "bytes_read_total": sum(j["bytes_read"] for j in jobs),
        "read_amplification": read_amp,
        "read_amplification_digest": read_amp_digest,
        "storage": storage,
        "io_histograms": merged or None,
        "jobs": jobs,
    }


# ---------------------------------------------------------------- gating


def evaluate_fleet(
    rollup: Dict[str, Any],
    rpo_threshold_s: Optional[float] = None,
    lag_bytes_threshold: Optional[int] = None,
    lag_seconds_threshold: Optional[float] = None,
    p99_ratio_threshold: Optional[float] = None,
    min_latency_samples: int = 20,
    max_read_amplification: Optional[float] = None,
) -> Dict[str, Any]:
    """The ``fleet --check`` verdict over a rollup: ``breach`` when any
    configured fleet objective is crossed — worst-job RPO, aggregate
    upload lag (bytes or seconds), or the merged storage write
    p99-over-p50 tail ratio (skipped below ``min_latency_samples``
    merged samples: a two-sample "distribution" is noise, not a tail).
    ``insufficient`` when there are no records at all — the same
    no-verdict stance as ``slo``/``history --check``'s exit 3. The RPO
    threshold defaults to ``TPUSNAP_SLO_RPO_S``."""
    if rpo_threshold_s is None:
        rpo_threshold_s = get_slo_rpo_threshold_s() or None
    thresholds = {
        "rpo_s": rpo_threshold_s,
        "lag_bytes": lag_bytes_threshold,
        "lag_seconds": lag_seconds_threshold,
        "p99_ratio": p99_ratio_threshold,
        "read_amplification": max_read_amplification,
    }
    if not rollup.get("n_jobs"):
        return {
            "verdict": "insufficient",
            "reason": (
                "no fleet status records found (is TPUSNAP_FLEET_DIR set "
                "on the jobs?)"
            ),
            "thresholds": thresholds,
            "checks": [],
        }
    checks: List[Dict[str, Any]] = []

    def check(name: str, value, threshold, breach: bool, job=None) -> None:
        row = {
            "check": name,
            "value": value,
            "threshold": threshold,
            "breach": breach,
        }
        if job is not None:
            row["job"] = job
        checks.append(row)

    if rpo_threshold_s:
        v = float(rollup.get("worst_rpo_s") or 0.0)
        check(
            "worst_rpo_s",
            v,
            rpo_threshold_s,
            v > rpo_threshold_s,
            job=rollup.get("worst_rpo_job"),
        )
    if lag_bytes_threshold:
        v = int(rollup.get("lag_bytes_total") or 0)
        check("lag_bytes_total", v, lag_bytes_threshold, v > lag_bytes_threshold)
    if lag_seconds_threshold:
        v = float(rollup.get("lag_seconds_max") or 0.0)
        check(
            "lag_seconds_max", v, lag_seconds_threshold, v > lag_seconds_threshold
        )
    if p99_ratio_threshold:
        st = (rollup.get("storage") or {}).get("write") or {}
        p50, p99 = st.get("p50_s"), st.get("p99_s")
        if (
            (st.get("count") or 0) >= min_latency_samples
            and p50
            and p99 is not None
        ):
            ratio = round(p99 / p50, 2)
            check("storage_write_p99_ratio", ratio, p99_ratio_threshold,
                  ratio > p99_ratio_threshold)
    if max_read_amplification:
        # Skipped when no reader attributed any bytes — absence of
        # readers is not a serving breach.
        amp = rollup.get("read_amplification")
        if amp is not None:
            check(
                "read_amplification",
                amp,
                max_read_amplification,
                amp > max_read_amplification,
                job=rollup.get("read_amplification_digest"),
            )
    breached = [c for c in checks if c["breach"]]
    if breached:
        c = breached[0]
        reason = f"{c['check']} {c['value']} > {c['threshold']}"
        if c.get("job"):
            reason += f" (worst job: {c['job']})"
        verdict = "breach"
    else:
        verdict = "healthy"
        reason = f"{rollup['n_jobs']} job(s) within fleet objectives"
    return {
        "verdict": verdict,
        "reason": reason,
        "thresholds": thresholds,
        "checks": checks,
    }


# ---------------------------------------------------------- prom export


def render_fleet_prom(rollup: Dict[str, Any]) -> str:
    """The rollup as ``scope="fleet"`` Prometheus families (exposition
    format, same strict shape :func:`~tpusnap.metrics_export.
    parse_prometheus_textfile` checks). These aggregate ACROSS jobs —
    the per-job textfiles keep their own ``job``-labeled series."""
    from .metrics_export import _fmt_labels, _fmt_value

    out: List[str] = []

    def metric(name, mtype, help_, samples) -> None:
        out.append(f"# HELP {name} {help_}")
        out.append(f"# TYPE {name} {mtype}")
        for labels, value in samples:
            all_labels = dict(labels)
            all_labels["scope"] = "fleet"
            out.append(f"{name}{_fmt_labels(all_labels)} {_fmt_value(value)}")

    metric(
        "tpusnap_fleet_jobs",
        "gauge",
        "Jobs with a status record in the fleet directory.",
        [({}, rollup.get("n_jobs") or 0)],
    )
    metric(
        "tpusnap_fleet_writers",
        "gauge",
        "Jobs currently inside a running take (concurrent writers on "
        "the shared substrate).",
        [({}, rollup.get("writers") or 0)],
    )
    metric(
        "tpusnap_fleet_degraded_jobs",
        "gauge",
        "Jobs whose write-back uploader circuit is open.",
        [({}, rollup.get("degraded_jobs") or 0)],
    )
    metric(
        "tpusnap_fleet_paused_jobs",
        "gauge",
        "Live delta streams that stopped committing past their own "
        "declared cadence.",
        [({}, rollup.get("paused_jobs") or 0)],
    )
    metric(
        "tpusnap_fleet_dead_ranks",
        "gauge",
        "Lease-expired DEAD ranks across all jobs.",
        [({}, rollup.get("dead_ranks") or 0)],
    )
    if rollup.get("worst_rpo_s") is not None:
        metric(
            "tpusnap_fleet_worst_rpo_seconds",
            "gauge",
            "Worst-job seconds since last committed take (staleness-"
            "corrected; final records frozen at exit).",
            [({"job": str(rollup.get("worst_rpo_job"))}, rollup["worst_rpo_s"])],
        )
    if rollup.get("worst_data_at_risk_bytes") is not None:
        metric(
            "tpusnap_fleet_data_at_risk_bytes",
            "gauge",
            "Worst-job bytes a crash right now would lose.",
            [(
                {"job": str(rollup.get("worst_at_risk_job"))},
                rollup["worst_data_at_risk_bytes"],
            )],
        )
    metric(
        "tpusnap_fleet_readers",
        "gauge",
        "Jobs that attributed snapshot reads through the access ledger.",
        [({}, rollup.get("readers") or 0)],
    )
    if rollup.get("read_amplification") is not None:
        metric(
            "tpusnap_fleet_read_amplification",
            "gauge",
            "Worst-snapshot aggregate bytes read across all readers "
            "over the snapshot's stored bytes.",
            [(
                {"digest": str(rollup.get("read_amplification_digest"))},
                rollup["read_amplification"],
            )],
        )
    metric(
        "tpusnap_fleet_upload_lag_bytes",
        "gauge",
        "Sum of local-committed bytes not yet remote-durable across "
        "all jobs behind the shared tier.",
        [({}, rollup.get("lag_bytes_total") or 0)],
    )
    metric(
        "tpusnap_fleet_upload_lag_seconds",
        "gauge",
        "Age of the fleet's oldest local commit still awaiting remote "
        "durability.",
        [({}, rollup.get("lag_seconds_max") or 0.0)],
    )
    for op in ("write", "read"):
        st = (rollup.get("storage") or {}).get(op) or {}
        samples = [
            ({"quantile": q}, st[k])
            for q, k in (("0.5", "p50_s"), ("0.95", "p95_s"), ("0.99", "p99_s"))
            if st.get(k) is not None
        ]
        if samples:
            metric(
                f"tpusnap_fleet_storage_{op}_seconds",
                "summary",
                f"Cross-job merged storage-plugin {op} latency quantiles "
                "(log2 histograms folded across all jobs).",
                samples,
            )
    metric(
        "tpusnap_fleet_last_fold_timestamp_seconds",
        "gauge",
        "Unix time this rollup was folded (staleness probe).",
        [({}, rollup.get("ts") or _wall())],
    )
    return "\n".join(out) + "\n"


def write_fleet_prom(rollup: Dict[str, Any], path: str) -> None:
    """Atomically write the rollup's ``scope="fleet"`` families to
    ``path`` (point it into the node collector's textfile directory)."""
    text = render_fleet_prom(rollup)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
