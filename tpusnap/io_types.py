"""Core I/O abstractions: write/read requests, stagers/consumers, and the
StoragePlugin ABC.

TPU-native counterpart of /root/reference/torchsnapshot/io_types.py:
same pipeline roles —

- ``WriteReq``  = logical path + ``BufferStager`` (produces bytes, e.g. by
  device→host DMA + zero-copy serialization).
- ``ReadReq``   = logical path + optional byte range + ``BufferConsumer``
  (deserializes into the restore target in place).
- ``WriteIO``/``ReadIO`` = the physical request handed to a storage plugin.
- ``StoragePlugin`` = async write/read/delete/close + sync shims.

Staging/consuming cost models drive the scheduler's memory budget
(reference io_types.py:30-72).
"""

from __future__ import annotations

import abc
import asyncio
import contextlib as _contextlib
import io
import threading as _threading
from concurrent.futures import Executor
from dataclasses import dataclass, field
from typing import Generic, List, Optional, Tuple, TypeVar, Union

BufferType = Union[bytes, bytearray, memoryview]

# The snapshot-internal sidecar namespace: telemetry traces, progress
# heartbeats, journal records, roofline probe streams. The ONE
# definition of the namespace root, shared by the layers that exempt
# whole-namespace traffic — journaling and histogram sampling — so it
# cannot silently drift apart. (fsck classifies per FAMILY under this
# root: lifecycle._is_legit_sidecar and the empty/foreign exemptions
# name specific subdirectories, deliberately narrower than the root.)
SIDECAR_PREFIX = ".tpusnap/"

# Canonical sidecar paths under the namespace root. Every layer that
# writes or classifies sidecar traffic imports these — hardcoding the
# string anywhere else is a lint violation (TPS003): a namespace that
# exists in five private copies is five chances for fsck's
# classification and the writers to drift apart.
JOURNAL_PATH = SIDECAR_PREFIX + "journal"  # rank 0's take marker
JOURNAL_RECORDS_DIR = SIDECAR_PREFIX + "journal.d"  # per-rank evidence
PROGRESS_DIR = SIDECAR_PREFIX + "progress"  # heartbeat records
TELEMETRY_DIR = SIDECAR_PREFIX + "telemetry"  # per-rank Chrome traces
PROBE_DIR = SIDECAR_PREFIX + "probe"  # roofline probe streams
FLIGHT_DIR = SIDECAR_PREFIX + "flight"  # flight-recorder event logs
# Write-back tiering (tpusnap.tiering): the crash-safe upload journal a
# tiered take keeps in its LOCAL tier — per-blob CRC32C+XXH64 evidence
# of what has been proven remote, plus the durability state marker
# (state "pending" = local-committed, "durable" = remote-durable).
UPLOAD_JOURNAL_PATH = SIDECAR_PREFIX + "upload_journal"
# Content-addressed store (tpusnap.cas): per-rank ref record files a
# CAS-composed snapshot keeps instead of private payload copies — each
# entry maps a manifest location to the (nbytes, CRC32C, XXH64) triple
# that keys the shared blob. The refs ARE the store's gc liveness
# roots, so they are journaled like PR 3 evidence (atomic per-rank
# rewrites) and flushed strictly before the metadata commit.
CAS_REFS_DIR = SIDECAR_PREFIX + "cas_refs"  # per-rank ref records

T = TypeVar("T")


class Future(Generic[T]):
    """Tiny completion cell for values materialized during read execution
    (reference io_preparer returns ``Future`` for inflated objects)."""

    def __init__(self, obj: Optional[T] = None) -> None:
        self.obj = obj


@dataclass
class WriteIO:
    path: str
    buf: BufferType


@dataclass
class ReadIO:
    path: str
    byte_range: Optional[Tuple[int, int]] = None
    buf: io.BytesIO = field(default_factory=io.BytesIO)
    # In-place read support: when ``into`` is set, a capable plugin may
    # land the bytes directly in this writable buffer (the restore
    # target's own memory) instead of allocating a scratch buffer, and
    # set ``in_place=True``. With ``want_crc``, the plugin also reports
    # the checksum of the bytes it delivered (computed inside the native
    # read, fused with the copy-out) via ``crc32c``/``crc_algo`` so the
    # consumer verifies a 4-byte value instead of re-hashing gigabytes.
    # Plugins without in-place support simply ignore these fields.
    into: Optional[memoryview] = None
    want_crc: bool = False
    in_place: bool = False
    crc32c: Optional[int] = None
    crc_algo: Optional[str] = None
    # Access-ledger provenance: plugins that redirect the read away from
    # the plain local path stamp where the bytes actually came from
    # ("cas" for a ref-translated store read, "evicted-read-through" for
    # a tiered local miss served by the remote). Left None for ordinary
    # reads; the scheduler's recorder then attributes the read to the
    # ambient storage tier (local/remote).
    source: Optional[str] = None


class _SkipWrite:
    """Sentinel a stager may return instead of bytes: the blob's content
    is already persisted (incremental snapshot dedup — the stager
    rewrote its entry to reference the previous snapshot's blob), so the
    pipeline completes this request without any storage I/O."""

    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "SKIP_WRITE"


SKIP_WRITE = _SkipWrite()


class BufferStager(abc.ABC):
    @abc.abstractmethod
    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        """Produce the bytes to persist (may run DtoH copies in
        ``executor``), or ``SKIP_WRITE`` when the content is already
        persisted and this request needs no storage I/O."""

    @abc.abstractmethod
    def get_staging_cost_bytes(self) -> int:
        """Peak host memory consumed while this buffer is staged."""

    def get_planned_bytes(self) -> int:
        """Payload bytes this request will actually stage/write — the
        progress denominator. Defaults to the staging cost; stagers
        whose cost model charges MORE than the payload (async array
        clones hold a second host copy, so their cost is 2x) override
        this so heartbeat percentages can reach 100."""
        return self.get_staging_cost_bytes()


@dataclass
class WriteReq:
    path: str
    buffer_stager: BufferStager


class BufferConsumer(abc.ABC):
    @abc.abstractmethod
    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        """Deserialize ``buf`` into the restore target."""

    @abc.abstractmethod
    def get_consuming_cost_bytes(self) -> int:
        """Peak host memory consumed while this buffer is being consumed."""

    async def consume_read_io(
        self, read_io: ReadIO, executor: Optional[Executor] = None
    ) -> None:
        """Consume a completed ReadIO. The default path hands the read
        buffer to ``consume_buffer``; consumers whose reads may land
        in place override this to skip the deserialize+copy pass when
        ``read_io.in_place`` is set."""
        await self.consume_buffer(read_io.buf.getbuffer(), executor)


@dataclass
class ReadReq:
    path: str
    buffer_consumer: BufferConsumer
    byte_range: Optional[Tuple[int, int]] = None
    # Writable destination for plugins that support in-place reads (the
    # restore target's memory when the consumer knows landing there is
    # correct); see ReadIO.into. ``want_crc`` requests the fused
    # read-time checksum of the delivered bytes.
    into: Optional[memoryview] = None
    want_crc: bool = False
    # Access-ledger attribution: the MANIFEST path this physical read
    # serves ("<rank>/<logical_path>" — the storage ``path`` is a blob
    # location, shared across leaves and meaningless to a reader).
    # Empty string = unattributed (manifest/metadata traffic).
    logical_path: str = ""
    # When the batcher merges several byte-ranged requests on one
    # location into a single spanning read, per-member attribution
    # survives here: [(logical_path, start, end), ...] in storage-blob
    # coordinates. None = the read serves exactly ``logical_path``
    # over ``byte_range``.
    access_parts: Optional[List[Tuple[str, int, int]]] = None


class StoragePlugin(abc.ABC):
    """Storage backend. Implementations must be safe for many concurrent
    coroutines (the scheduler keeps up to 16 requests in flight)."""

    # Plugins that honor ReadIO.into (bytes land in the consumer-provided
    # destination) set this True; the scheduler then charges such reads
    # only the plugin's transient overhead instead of the blob size.
    supports_in_place_reads: bool = False

    # Middleware markers consulted by the scheme registry
    # (storage_plugin.url_to_storage_plugin): ``wants_retry_middleware``
    # opts the plugin into the unified whole-op retry wrapper
    # (tpusnap.retry); ``handles_own_retries`` marks plugins with
    # internal, finer-grained retry logic (gcs retries per chunk) that
    # must not be double-wrapped.
    wants_retry_middleware: bool = False
    handles_own_retries: bool = False

    def classify_transient(self, exc: BaseException) -> bool:
        """Whether ``exc`` from this backend is worth retrying. The
        retry middleware consults this; plugins override to recognize
        backend-specific throttle/timeout shapes."""
        from .retry import default_classify_transient

        return default_classify_transient(exc)

    def in_place_read_overhead_bytes(self, nbytes: int) -> int:
        """Peak transient scratch memory an in-place read of ``nbytes``
        allocates inside this plugin (drives the scheduler's consuming
        budget). The conservative default assumes a full-size buffer;
        plugins that stream into the destination override with their
        actual bounce/chunk footprint."""
        return nbytes

    def _submit_tracked(self, executor, fn):
        """Run ``fn`` on ``executor``, tracked for ``drain_in_flight``.
        Plugins route any thread-offloaded work that writes into
        caller-owned buffers through this."""
        inflight = self.__dict__.setdefault("_tracked_inflight", set())
        future = executor.submit(fn)
        inflight.add(future)
        future.add_done_callback(inflight.discard)
        return asyncio.wrap_future(future)

    def drain_in_flight(self) -> None:
        """Block until worker-thread I/O this plugin offloaded via
        ``_submit_tracked`` has finished. Cancelling an asyncio task
        does NOT interrupt its executor work — after an aborted read, a
        plugin thread may still be writing into a caller-owned in-place
        destination. The scheduler's abort path calls this before
        re-raising so no stale write races the caller's error
        handling."""
        import concurrent.futures

        pending = list(self.__dict__.get("_tracked_inflight", ()))
        if pending:
            concurrent.futures.wait(pending)

    @abc.abstractmethod
    async def write(self, write_io: WriteIO) -> None: ...

    async def write_atomic(self, write_io: WriteIO, durable: bool = False) -> None:
        """Write that either fully lands or leaves any existing object
        untouched. Object stores are per-PUT atomic already, so the
        default delegates to ``write``; filesystem plugins override with
        temp-file + rename (a plain truncate-then-write would destroy a
        previously valid file on a mid-write crash — this matters when
        REWRITING committed metadata, e.g. ``materialize``).

        ``durable=True`` additionally makes the committed object survive
        POWER LOSS before returning (fs: fsync the temp file, rename,
        then fsync every directory the plugin created — so blob dirents
        written before the commit become durable with it; object stores
        are durable per PUT already). Callers rewriting
        already-committed metadata pass True (cheap there and the
        downside is destroying good state); the take commit passes the
        TPUSNAP_DURABLE_COMMIT knob, which ALSO fsyncs each blob file
        at write time — fsyncs right after a multi-GB take force a
        storage-cache flush of everything just written (~seconds), a
        cost the baselines it is benchmarked against (torch.save, the
        reference) never pay."""
        await self.write(write_io)

    @abc.abstractmethod
    async def read(self, read_io: ReadIO) -> None: ...

    @abc.abstractmethod
    async def delete(self, path: str) -> None: ...

    async def list_with_sizes(self) -> Optional[dict]:
        """Enumerate every object under this plugin's root as
        ``{relative_path: size_bytes}``, or ``None`` when the backend
        cannot list (the default). Powers offline lifecycle tooling —
        ``fsck``'s orphan-blob enumeration and ``gc``'s reclamation —
        which degrade gracefully (no orphan scan) on backends without
        it. Filesystem plugins implement it with a directory walk."""
        return None

    def sync_list_with_sizes(
        self, event_loop: Optional[asyncio.AbstractEventLoop] = None
    ) -> Optional[dict]:
        return _run(self.list_with_sizes(), event_loop)

    async def flush_created_dirs(self) -> None:
        """Make the dirents of everything this plugin instance created
        durable (fs: fsync each created directory). Called by EVERY rank
        after its writes drain, before the commit barrier, when
        TPUSNAP_DURABLE_COMMIT is on — the committing rank's
        ``write_atomic(durable=True)`` can only fsync its OWN
        directories, not the ones other ranks' plugin instances made.
        Default no-op (object stores have no dirents)."""
        return None

    def sync_flush_created_dirs(
        self, event_loop: Optional[asyncio.AbstractEventLoop] = None
    ) -> None:
        _run(self.flush_created_dirs(), event_loop)

    async def close(self) -> None:  # optional override
        return None

    # Sync shims (reference io_types.py:96-111): convenience wrappers used
    # outside the scheduler's event loop (metadata read/write).
    def sync_write(
        self, write_io: WriteIO, event_loop: Optional[asyncio.AbstractEventLoop] = None
    ) -> None:
        _run(self.write(write_io), event_loop)

    def sync_write_atomic(
        self,
        write_io: WriteIO,
        event_loop: Optional[asyncio.AbstractEventLoop] = None,
        durable: bool = False,
    ) -> None:
        _run(self.write_atomic(write_io, durable=durable), event_loop)

    def sync_read(
        self, read_io: ReadIO, event_loop: Optional[asyncio.AbstractEventLoop] = None
    ) -> None:
        _run(self.read(read_io), event_loop)

    def sync_delete(
        self, path: str, event_loop: Optional[asyncio.AbstractEventLoop] = None
    ) -> None:
        _run(self.delete(path), event_loop)

    def sync_close(
        self, event_loop: Optional[asyncio.AbstractEventLoop] = None
    ) -> None:
        _run(self.close(), event_loop)


# --- finalizer-safe close -------------------------------------------------
#
# Joining a thread from a GC finalizer can deadlock the process: if the
# collection that runs ``Snapshot.__del__`` fires inside a STARTING
# thread's ``Thread._set_tstate_lock`` (which holds
# ``threading._shutdown_locks_lock``), the join's ``Thread._stop``
# re-acquires that same lock and the thread waits on itself forever
# (observed killing a tier-1 run). Explicit closes KEEP joining — the
# take-abort path relies on close as its quiescence point for in-flight
# I/O threads (a straggler write surviving close could recreate a
# just-deleted blob of an aborted take). Only the finalizer path opts
# out, via this thread-local guard consulted by the executor-owning
# plugins' ``close()``.

_finalizer_close = _threading.local()


@_contextlib.contextmanager
def finalizer_close_scope():
    """Mark plugin ``close()`` calls on this thread as GC-finalizer
    driven: executor shutdowns skip their thread joins (queued work
    still runs; the interpreter joins workers at exit)."""
    # Save/restore (not set/clear): a nested finalizer — close()
    # dropping the last reference to another Snapshot — must not
    # re-enable joins for the OUTER finalizer still unwinding.
    prior = getattr(_finalizer_close, "active", False)
    _finalizer_close.active = True
    try:
        yield
    finally:
        _finalizer_close.active = prior


def close_may_join() -> bool:
    """Whether a plugin ``close()`` may join threads (False only inside
    :func:`finalizer_close_scope`)."""
    return not getattr(_finalizer_close, "active", False)


def shutdown_plugin_executor(executor) -> None:
    """The one place the join-on-close policy lives: explicit closes
    JOIN (abort-path quiescence — a straggler write thread surviving
    close could recreate a just-deleted blob of an aborted take);
    GC-finalizer closes must NOT (see the deadlock note above) — and
    must not even WAIT on the executor's shutdown lock:
    ``ThreadPoolExecutor.shutdown`` blocks on ``_shutdown_lock``, while
    ``submit`` holds its own ``_shutdown_lock`` and then the module's
    ``_global_shutdown_lock``. GC can fire this finalizer on a thread
    that is inside executor B's ``submit`` (holding the global lock)
    while another thread is inside executor A's ``submit`` (holding
    A's lock, waiting for the global one) — a blocking shutdown of A
    here completes the AB/BA deadlock. The runtime lock-order watchdog
    (tpusnap.devtools.lockwatch) caught exactly this interleaving in a
    tier-1 run. So the finalizer path replicates
    ``shutdown(wait=False)``'s body under a TRYLOCK and simply leaves
    the executor to the interpreter's exit reaper when the lock is
    contended (or the stdlib internals have moved).
    Executor-owning plugins call this from ``close()``."""
    if close_may_join():
        executor.shutdown(wait=True)
        return
    lock = getattr(executor, "_shutdown_lock", None)
    try:
        if lock is None or not lock.acquire(False):
            return
        try:
            executor._shutdown = True
            # Wake idle workers blocked in _work_queue.get so they exit
            # instead of parking until interpreter shutdown.
            executor._work_queue.put(None)
        finally:
            lock.release()
    except Exception:
        # Unknown executor shape: taking no lock beats taking a risk —
        # the interpreter joins surviving workers at exit.
        return


def run_on_loop(event_loop: asyncio.AbstractEventLoop, coro):
    """``run_until_complete`` that cannot strand tasks on the loop.

    A BaseException delivered inside the loop machinery (Ctrl-C between
    callbacks) escapes ``run_until_complete`` without unwinding the
    top-level coroutine; on a per-call loop the subsequent close()
    destroyed the orphan, but on a REUSED loop (cached Snapshot
    resources) the next ``run_until_complete`` would resume it —
    writing into the previous call's buffers. Cancel and drain the
    top-level task before re-raising."""
    task = event_loop.create_task(coro) if asyncio.iscoroutine(coro) else coro
    try:
        return event_loop.run_until_complete(task)
    except BaseException:
        task.cancel()
        try:
            event_loop.run_until_complete(task)
        except BaseException:
            pass
        raise


def _run(coro, event_loop: Optional[asyncio.AbstractEventLoop]):
    if event_loop is not None:
        return run_on_loop(event_loop, coro)
    return asyncio.run(coro)


def read_io_bytes(read_io: ReadIO) -> memoryview:
    """The bytes a plugin filled into a ReadIO."""
    return read_io.buf.getbuffer()


def total_write_bytes(write_ios: List[WriteIO]) -> int:
    return sum(len(w.buf) for w in write_ios)
