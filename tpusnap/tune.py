"""`tpusnap tune` — a deterministic knob planner driven by `analyze`'s
own evidence.

The observability column ends in a loop-closer: history.jsonl events
(what past takes/restores of this cell achieved), the probe ceiling
registry (what the pipe can do, write and read lane), and the bound
verdict (what the slowest rank actually spent its wall-clock on) go in;
a knob plan comes out — one proposed env value per knob, each with a
one-line rationale naming the evidence. The planner is a PURE function
of its inputs: same events, same ceilings, same verdict → same plan,
same ``plan_id``. No wall-clock, no randomness, no I/O.

A plan cell is ``(backend, kind, world_size)``: knobs tuned from local
NVMe history must never apply to a cloud-tier restore, and a 2-process
cell's budget medians must never price a 16-process job.

Application (``TPUSNAP_AUTOTUNE=1``) goes through
:func:`knobs.apply_tuned_plan` — a fallback layer BELOW the
environment, so an explicitly-set env var always beats the tuner, per
lookup. The knobs a run actually applied are stamped into its history
event as ``tuned: {plan_id, knobs}``; `history --check` then gates any
regression the tuner causes, attributably.
"""

from __future__ import annotations

import hashlib
import json
import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

# Comparable-evidence floor: below this many events for the cell the
# planner refuses (exit 3 at the CLI) rather than tune from noise —
# the same bar the SLO RTO estimator uses for its history baseline.
MIN_EVENTS = 3

# Events older than this many entries (per cell) are ignored: the plan
# should track the CURRENT machine, not a disk that was replaced.
DEFAULT_WINDOW = 50

_MIN_ASYNC_WINDOW_BYTES = 256 * 1024 * 1024
_MAX_STAGE_THREADS = 8
_PROBE_INTERVAL_FLOOR = 16 * 1024 * 1024
_PROBE_INTERVAL_CAP = 2 * 1024 * 1024 * 1024
_MIN_RESTORE_BUDGET_BYTES = 16 * 1024 * 1024


@dataclass
class KnobChange:
    """One proposed knob: the env var, the value the plan would set,
    the current effective value, and the evidence one-liner."""

    env: str
    value: str
    current: Optional[str]
    rationale: str

    def to_json(self) -> Dict[str, Any]:
        return {
            "env": self.env,
            "value": self.value,
            "current": self.current,
            "rationale": self.rationale,
        }


@dataclass
class TunePlan:
    ok: bool
    reason: str
    kind: Optional[str] = None
    backend: Optional[str] = None
    world_size: Optional[int] = None
    n_events: int = 0
    verdict: Optional[str] = None
    knobs: List[KnobChange] = field(default_factory=list)
    plan_id: Optional[str] = None

    def to_json(self) -> Dict[str, Any]:
        return {
            "ok": self.ok,
            "reason": self.reason,
            "cell": {
                "backend": self.backend,
                "kind": self.kind,
                "world_size": self.world_size,
            },
            "n_events": self.n_events,
            "verdict": self.verdict,
            "plan_id": self.plan_id,
            "knobs": [k.to_json() for k in self.knobs],
        }

    def env_exports(self) -> List[str]:
        """Shell-exportable lines (`tune --env`)."""
        return [f"export {k.env}={k.value}" for k in self.knobs]


def _median(vals: List[float]) -> Optional[float]:
    vals = sorted(vals)
    return vals[len(vals) // 2] if vals else None


def _metric_median(
    events: List[Dict[str, Any]], key: str
) -> Optional[float]:
    return _median(
        [float(e[key]) for e in events if isinstance(e.get(key), (int, float))]
    )


def _plan_id(
    kind: Optional[str],
    backend: Optional[str],
    world_size: Optional[int],
    knobs: List[KnobChange],
) -> str:
    """Deterministic content id: same cell + same knob values → same
    id, so `history --check` can group runs by the plan they ran."""
    doc = {
        "cell": [backend, kind, world_size],
        "knobs": {k.env: k.value for k in knobs},
    }
    return hashlib.sha1(
        json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()[:12]


def ceiling_for(
    ceilings: Optional[Dict[Tuple[str, str], float]],
    backend: Optional[str],
    lane: str,
    events: List[Dict[str, Any]],
) -> Optional[float]:
    """Pipe ceiling for one (backend, lane): the live in-process probe
    registry when this process has run probes (registry keys are
    ``label@device``; the event's backend is the bare label, so prefix
    match), else the median probe ceiling past events recorded —
    a fresh CLI process has an empty registry but the history remembers
    what the probes measured."""
    if ceilings:
        cands = [
            v
            for (label, ln), v in sorted(ceilings.items())
            if ln == lane
            and (
                backend is None
                or label == backend
                or label.startswith(f"{backend}@")
            )
        ]
        med = _median(cands)
        if med:
            return med
    fallback = "probe_write_gbps" if lane == "write" else "probe_read_gbps"
    return _metric_median(events, fallback)


def select_events(
    events: List[Dict[str, Any]],
    kind: str,
    backend: Optional[str] = None,
    world_size: Optional[int] = None,
    window: int = DEFAULT_WINDOW,
) -> List[Dict[str, Any]]:
    """The cell's comparable evidence: newest ``window`` events of
    ``kind``, filtered to the backend and world size when given."""
    out = [
        e
        for e in events
        if e.get("kind") == kind
        and (backend is None or e.get("plugin") == backend)
        and (world_size is None or e.get("world_size") == world_size)
    ]
    return out[-window:]


def build_plan(
    events: List[Dict[str, Any]],
    kind: str,
    backend: Optional[str] = None,
    world_size: Optional[int] = None,
    ceilings: Optional[Dict[Tuple[str, str], float]] = None,
    verdict: Optional[str] = None,
    codec_gbps: Optional[float] = None,
    min_events: int = MIN_EVENTS,
    window: int = DEFAULT_WINDOW,
) -> TunePlan:
    """The planner. ``events`` is the full history (oldest first);
    ``ceilings`` a :func:`compress.pipe_ceilings_snapshot`; ``verdict``
    the analyze bound category when the caller computed one;
    ``codec_gbps`` the measured codec throughput (None → read it from
    :func:`compress.codec_throughput_gbps`). Current knob values come
    from :mod:`tpusnap.knobs` (env + any applied plan)."""
    from . import knobs

    cell = select_events(
        events, kind, backend=backend, world_size=world_size, window=window
    )
    if backend is None and cell:
        # Pin the cell to the newest event's backend so the medians
        # below never mix tiers.
        backend = cell[-1].get("plugin")
        if backend is not None:
            cell = [e for e in cell if e.get("plugin") == backend]
    if world_size is None and cell:
        world_size = cell[-1].get("world_size")
        if world_size is not None:
            cell = [e for e in cell if e.get("world_size") == world_size]

    plan = TunePlan(
        ok=False,
        reason="",
        kind=kind,
        backend=backend,
        world_size=world_size,
        n_events=len(cell),
        verdict=verdict,
    )
    if len(cell) < max(1, min_events):
        plan.reason = (
            f"only {len(cell)} comparable {kind} event(s) for backend="
            f"{backend or 'any'} world_size={world_size or 'any'} — "
            f"need {max(1, min_events)}; run more {kind}s (with "
            "TPUSNAP_PROBE=1 for ceilings) and retry"
        )
        return plan

    if codec_gbps is None:
        from .compress import codec_throughput_gbps

        try:
            codec_gbps = codec_throughput_gbps()
        except Exception:
            codec_gbps = 0.0

    med_bytes = _metric_median(cell, "bytes")
    med_wall = _metric_median(cell, "wall_s")
    knob_list: List[KnobChange] = []

    # --- staging executor width (takes; verdict-driven) ---------------
    if kind == "take" and verdict == "stage":
        cur = knobs.get_stage_threads()
        target = min(_MAX_STAGE_THREADS, cur * 2)
        if target > cur:
            knob_list.append(
                KnobChange(
                    env="TPUSNAP_STAGE_THREADS",
                    value=str(target),
                    current=str(cur),
                    rationale=(
                        "bound verdict is 'stage' — widen the staging "
                        f"executor {cur}→{target} (the native "
                        "copy-thread budget stays constant, so this "
                        "shifts grain, not oversubscription)"
                    ),
                )
            )

    # --- async blocked window (takes; history-driven) ------------------
    if kind == "take" and med_wall:
        med_blocked = _metric_median(cell, "async_blocked_s")
        cur_win = knobs.get_async_stage_window_bytes()
        if (
            med_blocked is not None
            and med_blocked > 0.25 * med_wall
            and cur_win
            and cur_win // 2 >= _MIN_ASYNC_WINDOW_BYTES
        ):
            target = cur_win // 2
            knob_list.append(
                KnobChange(
                    env="TPUSNAP_ASYNC_STAGE_WINDOW_BYTES",
                    value=str(target),
                    current=str(cur_win),
                    rationale=(
                        f"median blocked window {med_blocked:.2f}s is >25% "
                        f"of the {med_wall:.2f}s median take — halve the "
                        "staging window so control returns to training "
                        "sooner (the drain overlaps the rest)"
                    ),
                )
            )

    # --- restore memory budget (restores; verdict-driven) --------------
    if kind == "restore" and verdict == "storage_read":
        med_hw = _metric_median(cell, "budget_high_water_bytes")
        cur_override = knobs.get_memory_budget_override_bytes()
        if med_hw:
            target = int(med_hw * 2)
            if cur_override is None or cur_override < target:
                knob_list.append(
                    KnobChange(
                        env="TPUSNAP_MAX_PER_RANK_MEMORY_BUDGET_BYTES",
                        value=str(target),
                        current=(
                            str(cur_override)
                            if cur_override is not None
                            else None
                        ),
                        rationale=(
                            "bound verdict is 'storage_read' — double the "
                            "median budget high-water "
                            f"({int(med_hw)}→{target} bytes) so more "
                            "tiled reads stay in flight"
                        ),
                    )
                )

    # --- restore budget from the access working set (history-driven) ----
    # The ledger's distinct-byte union rides history as
    # access_working_set_bytes. Lazy/partial readers touch a working
    # set far below the restore payload — a budget sized for the whole
    # payload reserves memory the reads can never fill. 2x the median
    # working set keeps double-buffering headroom. Skipped on a
    # 'storage_read' verdict: a read-bound restore wants MORE in
    # flight, and the rule above already raises the budget.
    if kind == "restore" and verdict != "storage_read":
        med_ws = _metric_median(cell, "access_working_set_bytes")
        med_read = _metric_median(cell, "access_bytes_read")
        cur_override = knobs.get_memory_budget_override_bytes()
        if (
            med_ws
            and med_bytes
            and med_ws < 0.5 * med_bytes
            and (med_read or 0) <= 2 * med_ws
        ):
            target = max(int(med_ws * 2), _MIN_RESTORE_BUDGET_BYTES)
            if cur_override is None or cur_override > 2 * target:
                knob_list.append(
                    KnobChange(
                        env="TPUSNAP_MAX_PER_RANK_MEMORY_BUDGET_BYTES",
                        value=str(target),
                        current=(
                            str(cur_override)
                            if cur_override is not None
                            else None
                        ),
                        rationale=(
                            "median access working set is "
                            f"{int(med_ws)} bytes against a "
                            f"{int(med_bytes)}-byte median payload — "
                            "partial readers; size the restore budget "
                            "to 2x the hot working set instead of the "
                            "full payload"
                        ),
                    )
                )

    # --- compression policy (ceiling vs codec) --------------------------
    lane = "read" if kind == "restore" else "write"
    pipe = ceiling_for(ceilings, backend, lane, cell)
    cur_mode = knobs.get_compress_mode()
    if verdict == "decode" and cur_mode != "off":
        knob_list.append(
            KnobChange(
                env="TPUSNAP_COMPRESS",
                value="off",
                current=cur_mode,
                rationale=(
                    "bound verdict is 'decode' — the read pipe outruns "
                    "the decompressor; write the next snapshot "
                    "uncompressed for this tier"
                ),
            )
        )
    elif pipe and codec_gbps:
        if pipe >= 2.0 * codec_gbps and cur_mode not in ("off",):
            knob_list.append(
                KnobChange(
                    env="TPUSNAP_COMPRESS",
                    value="off",
                    current=cur_mode,
                    rationale=(
                        f"probe {lane} ceiling {pipe:.2f} GB/s is ≥2x the "
                        f"codec's {codec_gbps:.2f} GB/s — the pipe wins; "
                        "pin bypass so no take pays the codec"
                    ),
                )
            )
        elif codec_gbps >= 2.0 * pipe and cur_mode not in ("on", "lz4"):
            knob_list.append(
                KnobChange(
                    env="TPUSNAP_COMPRESS",
                    value="on",
                    current=cur_mode,
                    rationale=(
                        f"codec {codec_gbps:.2f} GB/s is ≥2x the probe "
                        f"{lane} ceiling {pipe:.2f} GB/s — the codec "
                        "wins; pin compression on for this tier"
                    ),
                )
            )

    # --- probe cadence (both kinds; payload-driven) ---------------------
    if med_bytes:
        target = int(
            min(
                _PROBE_INTERVAL_CAP,
                max(_PROBE_INTERVAL_FLOOR, med_bytes // 8),
            )
        )
        cur_int = knobs.get_probe_interval_bytes()
        # Only repoint the cadence when it is off by ≥2x — a probe
        # count of 6 vs 8 is not worth a knob churn.
        if max(target, cur_int) >= 2 * min(target, cur_int):
            knob_list.append(
                KnobChange(
                    env="TPUSNAP_PROBE_INTERVAL_BYTES",
                    value=str(target),
                    current=str(cur_int),
                    rationale=(
                        f"median {kind} payload is {int(med_bytes)} bytes "
                        f"— one probe per ~1/8th of it ({target} bytes) "
                        "yields ~8 in-run ceiling samples instead of "
                        f"{max(1, int(med_bytes // cur_int))}"
                    ),
                )
            )

    plan.ok = True
    plan.knobs = knob_list
    plan.plan_id = _plan_id(kind, backend, world_size, knob_list)
    plan.reason = (
        f"{len(knob_list)} knob(s) proposed from {len(cell)} {kind} "
        "event(s)"
        if knob_list
        else f"all knobs already match the evidence from {len(cell)} "
        f"{kind} event(s) — nothing to change"
    )
    return plan


def maybe_apply(
    kind: str, storage: Any = None, world_size: Optional[int] = None
) -> Optional[Dict[str, Any]]:
    """Take/restore-begin reconcile (TPUSNAP_AUTOTUNE=1): build this
    cell's plan from the local history and install it through the
    tuned-plan overlay. Returns ``{plan_id, knobs}`` for the knobs
    ACTUALLY applied (explicit env vars win and are skipped), or None
    when autotune is off, history is insufficient, or the plan is
    empty. Never raises — a broken tuner must not fail a restore."""
    from . import knobs

    if not knobs.is_autotune_enabled():
        return None
    try:
        from . import compress
        from .history import load_history
        from .storage_plugin import storage_plugin_label

        backend = None
        if storage is not None:
            try:
                backend = storage_plugin_label(storage)
            except Exception:
                backend = None
        plan = build_plan(
            load_history(),
            kind,
            backend=backend,
            world_size=world_size,
            ceilings=compress.pipe_ceilings_snapshot(),
        )
        if not plan.ok or not plan.knobs:
            knobs.clear_tuned_plan()
            return None
        applied = knobs.apply_tuned_plan(
            plan.plan_id, {k.env: k.value for k in plan.knobs}
        )
        if not applied:
            return None
        logger.info(
            "autotune: applied plan %s to this %s (%s)",
            plan.plan_id,
            kind,
            ", ".join(f"{k}={v}" for k, v in sorted(applied.items())),
        )
        return {"plan_id": plan.plan_id, "knobs": applied}
    except Exception:
        logger.warning(
            "autotune: reconcile failed (non-fatal; running untuned)",
            exc_info=True,
        )
        knobs.clear_tuned_plan()
        return None
