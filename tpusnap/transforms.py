"""Ready-made save-time array transforms for ``_custom_array_prepare_func``.

The hook (reference ``_custom_tensor_prepare_func``,
/root/reference/torchsnapshot/snapshot.py:170-196) is powerful but raw:
a callable of ``(logical_path, arr, tracing)``. These helpers build the
common cases so users don't hand-roll glob matching:

    from tpusnap.transforms import cast_on_save

    Snapshot.take(
        path, app_state,
        _custom_array_prepare_func=cast_on_save({"**/params/**": jnp.bfloat16}),
    )

Transforms run under ``jax.eval_shape`` at prepare time (so they must be
traceable — ``astype`` is) and for real at stage time.
"""

from __future__ import annotations

import fnmatch
from typing import Any, Callable, Dict

__all__ = ["cast_on_save"]


def cast_on_save(
    dtype_by_glob: Dict[str, Any],
) -> Callable[[str, Any, bool], Any]:
    """Save-time dtype cast by logical-path glob — checkpoint at reduced
    precision (e.g. bf16 weights) while training at full precision.

    ``dtype_by_glob`` maps glob patterns (matched against the flattened
    logical path, e.g. ``"model/params/dense/kernel"``) to target
    dtypes; first match wins, unmatched arrays pass through unchanged.
    Restoring into a full-precision target upcasts into the target's
    dtype (on device for jax targets).

    Applies to dense, chunked AND sharded arrays: multi-device
    ``NamedSharding`` arrays (DP/FSDP/TP/SP/EP training state — the
    transform's primary audience on TPU) are cast per local shard at
    stage time, and restore upcasts into the target's sharding on
    device."""
    patterns = list(dtype_by_glob.items())

    def transform(logical_path: str, arr: Any, tracing: bool) -> Any:
        for pattern, dtype in patterns:
            if fnmatch.fnmatch(logical_path, pattern):
                return arr.astype(dtype)
        return arr

    return transform
