"""Zero-copy serialization between host arrays and byte buffers.

TPU-native counterpart of the reference's serialization module
(/root/reference/torchsnapshot/serialization.py:32-254). Differences by
design:

- dtypes are JAX/numpy dtypes (incl. ``bfloat16`` and the fp8 family via
  ``ml_dtypes``) instead of torch dtypes; there is no quantized-tensor
  format because XLA has no quantized tensor objects — int4/int8 arrays
  cover that ground.
- Every fixed-width dtype takes the zero-copy buffer-protocol path. numpy
  has no native bf16/fp8 buffer format, so those are byte-reinterpreted
  through a same-itemsize unsigned-int view (the same idea as the
  reference's untyped-storage workaround, serialization.py:186-233) —
  no value conversion ever happens, so restores are bit-identical.
- The fallback serializer for arbitrary Python objects is stdlib pickle
  (the reference's ``torch.save`` is pickle underneath too).
"""

from __future__ import annotations

import pickle
from enum import Enum
from typing import Any, Sequence, Tuple

import ml_dtypes
import numpy as np


class Serializer(Enum):
    BUFFER_PROTOCOL = "buffer_protocol"
    PICKLE = "pickle"


# Canonical dtype-string table. Keys are what lands in TensorEntry.dtype.
SUPPORTED_DTYPES = {
    "float64": np.dtype("float64"),
    "float32": np.dtype("float32"),
    "float16": np.dtype("float16"),
    "bfloat16": np.dtype(ml_dtypes.bfloat16),
    "float8_e4m3fn": np.dtype(ml_dtypes.float8_e4m3fn),
    "float8_e5m2": np.dtype(ml_dtypes.float8_e5m2),
    "complex128": np.dtype("complex128"),
    "complex64": np.dtype("complex64"),
    "int64": np.dtype("int64"),
    "int32": np.dtype("int32"),
    "int16": np.dtype("int16"),
    "int8": np.dtype("int8"),
    "uint64": np.dtype("uint64"),
    "uint32": np.dtype("uint32"),
    "uint16": np.dtype("uint16"),
    "uint8": np.dtype("uint8"),
    "bool": np.dtype("bool"),
}

_DTYPE_TO_STRING = {v: k for k, v in SUPPORTED_DTYPES.items()}

# dtypes numpy's buffer protocol can't describe; bytes are reinterpreted
# through a same-itemsize unsigned view instead (never converted).
_BYTE_VIEW_DTYPES = {
    "bfloat16": np.dtype("uint16"),
    "float8_e4m3fn": np.dtype("uint8"),
    "float8_e5m2": np.dtype("uint8"),
}


def dtype_to_string(dtype: Any) -> str:
    """Canonical string for a numpy/jax dtype (e.g. ``"bfloat16"``)."""
    np_dtype = np.dtype(dtype)
    try:
        return _DTYPE_TO_STRING[np_dtype]
    except KeyError:
        raise ValueError(f"Unsupported dtype: {dtype}") from None


def string_to_dtype(s: str) -> np.dtype:
    try:
        return SUPPORTED_DTYPES[s]
    except KeyError:
        raise ValueError(f"Unsupported dtype string: {s}") from None


def dtype_itemsize(s: str) -> int:
    return string_to_dtype(s).itemsize


def tensor_nbytes(dtype_str: str, shape: Sequence[int]) -> int:
    n = dtype_itemsize(dtype_str)
    for dim in shape:
        n *= dim
    return n


def _byte_compatible_view(arr: np.ndarray) -> np.ndarray:
    """Reinterpret custom dtypes as buffer-protocol-compatible ones."""
    dtype_str = _DTYPE_TO_STRING.get(arr.dtype)
    if dtype_str in _BYTE_VIEW_DTYPES:
        return arr.view(_BYTE_VIEW_DTYPES[dtype_str])
    return arr


def array_as_memoryview(arr: np.ndarray) -> memoryview:
    """Zero-copy flat byte view of a host array (contiguous; no conversion).

    Counterpart of reference ``tensor_as_memoryview``
    (serialization.py:162-233). Non-contiguous inputs are copied once.
    """
    if arr.dtype not in _DTYPE_TO_STRING:
        raise ValueError(f"Unsupported dtype: {arr.dtype}")
    if not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    arr = _byte_compatible_view(arr)
    return memoryview(arr).cast("B", (arr.nbytes,)) if arr.nbytes else memoryview(b"")


def array_from_memoryview(
    mv: memoryview, dtype: str, shape: Sequence[int]
) -> np.ndarray:
    """Zero-copy array over a byte buffer (counterpart of reference
    ``tensor_from_memoryview``, serialization.py:236-244). The result
    aliases ``mv`` and is read-only if ``mv`` is."""
    np_dtype = string_to_dtype(dtype)
    view_dtype = _BYTE_VIEW_DTYPES.get(dtype, np_dtype)
    arr = np.frombuffer(mv, dtype=view_dtype)
    if view_dtype is not np_dtype:
        arr = arr.view(np_dtype)
    return arr.reshape(tuple(shape))


def pickle_as_bytes(obj: Any) -> bytes:
    """Object fallback serializer (reference torch_save_as_bytes,
    serialization.py:247-250)."""
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def pickle_from_bytes(buf: bytes) -> Any:
    return pickle.loads(buf)


def per_element_sizes() -> Tuple[str, ...]:
    return tuple(SUPPORTED_DTYPES)
