"""Ring attention: causal attention over a sequence sharded on a mesh axis.

Each device holds one contiguous block of the sequence. K/V blocks rotate
around the ring via ``lax.ppermute`` while every device accumulates its
queries' attention output with an online (flash-style) softmax, so the
full sequence is never materialized on any chip and the per-step
``ppermute`` rides the ICI ring concurrently with the block matmuls.

Designed for use inside ``jax.shard_map`` with the sequence dimension
sharded over ``axis_name``. Pure ``lax`` control flow (``fori_loop`` +
``ppermute``) — traces once, compiles to a static XLA loop.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30  # finite mask value: keeps exp() arithmetic NaN-free


def _block_attend(q, k, v, m, l, o, q_pos, k_pos, scale, causal):
    """One online-softmax accumulation step against a single K/V block.

    q: [b, sq, h, d]; k/v: [b, sk, h, d]; m/l: [b, h, sq]; o like q.
    q_pos/k_pos: global token positions of the local q block and the
    currently-held k block — needed for causal masking across the ring.
    """
    # [b, h, sq, sk]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]  # [sq, sk]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    else:
        mask = None

    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    alpha = jnp.exp(m - m_new)  # rescale of previous accumulators
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        # A fully-masked row leaves m_new == m == _NEG_INF and p == 1;
        # zeroing by the mask keeps such rows contributing nothing.
        p = jnp.where(mask[None, None], p, 0.0)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: Optional[str] = None,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Causal attention with the sequence sharded over ``axis_name``.

    Args:
      q, k, v: local blocks ``[batch, seq_local, heads, head_dim]`` of a
        globally ``[batch, seq, heads, head_dim]`` array sharded on dim 1
        over ``axis_name``. With ``axis_name=None`` this degrades to plain
        (single-block flash) attention — the single-device path.
      causal: apply a causal mask in *global* positions.
      scale: softmax scale; defaults to ``head_dim ** -0.5``.

    Returns:
      Local attention output block, same shape/dtype as ``q``.
    """
    b, sq, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    in_dtype = q.dtype
    qf = q.astype(jnp.float32)

    if axis_name is None:
        ring_size, my_idx = 1, 0
    else:
        ring_size = lax.psum(1, axis_name)
        my_idx = lax.axis_index(axis_name)

    q_pos = my_idx * sq + jnp.arange(sq)
    # Accumulators derive from q (×0) so they carry q's varying-manual-axes
    # type under shard_map — a plain jnp.zeros carry would be rejected by
    # lax.fori_loop as unvarying-in / varying-out.
    zero_bhs = qf[..., 0].transpose(0, 2, 1) * 0.0  # [b, h, sq]
    m0 = zero_bhs + _NEG_INF
    l0 = zero_bhs
    o0 = qf * 0.0

    def body(step, carry):
        k_blk, v_blk, m, l, o = carry
        # After `step` rotations this device holds the block originally
        # owned by ring neighbor (my_idx - step) mod ring_size.
        src_idx = (my_idx - step) % ring_size
        k_pos = src_idx * sq + jnp.arange(sq)
        m, l, o = _block_attend(qf, k_blk, v_blk, m, l, o, q_pos, k_pos, scale, causal)
        if axis_name is not None:
            perm = [(i, (i + 1) % ring_size) for i in range(ring_size)]
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m, l, o

    if axis_name is None:
        _, _, m, l, o = body(0, (k, v, m0, l0, o0))
    else:
        _, _, m, l, o = lax.fori_loop(0, ring_size, body, (k, v, m0, l0, o0))

    # l is strictly positive for causal (diagonal always attends) and for
    # non-causal (every block attends); guard anyway for masked variants.
    l = jnp.maximum(l, 1e-20)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(in_dtype)
