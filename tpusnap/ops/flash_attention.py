"""Pallas TPU flash attention (forward) for the flagship model.

The reference framework ships no kernels of its own (it is a
checkpointing library — SURVEY.md §2); this kernel exists because our
flagship model is a real TPU training workload and attention is its hot
op. Design follows the canonical TPU flash-attention shape:

- Grid ``(batch, heads, q_blocks, k_blocks)`` — the k-block axis is
  innermost and TPU grids execute sequentially, so the f32 accumulators
  (``acc``, running max ``m``, running sum ``l``) live in VMEM scratch
  and persist across k-steps of one q-block.
- Online softmax in f32 (MXU matmuls via ``jnp.dot`` with
  ``preferred_element_type``), output cast back to the input dtype.
- Causal masking at two granularities: whole k-blocks strictly above
  the diagonal are skipped with ``pl.when`` (no FLOPs, no VMEM traffic
  beyond the prefetch), and the diagonal blocks apply an elementwise
  ``broadcasted_iota`` mask.
- Head dim and sequence length are zero-padded to lane/tile multiples
  in the wrapper; padded *keys* are masked via a validity mask, padded
  *query* rows are sliced off on return.

Backward runs as a recomputing VJP on the reference formulation (XLA
fuses it well); a dedicated Pallas backward is a known follow-up.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_NEG_INF = -1e30


def _flash_fwd_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    acc_ref,
    m_ref,
    l_ref,
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    s_valid: int,
):
    """One (batch, head, q_block, k_block) grid step."""
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def body():
        q = q_ref[0, 0].astype(jnp.float32)  # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)  # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)  # [bk, d]

        s = jax.lax.dot_general(
            q,
            k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        s = s * scale

        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_pos < s_valid  # padded keys contribute nothing
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, 0:1]  # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)  # [bq, 1]
        p = jnp.exp(s - m_new)  # [bq, bk]
        # Fully-masked rows: m_new == _NEG_INF and p == 1 — zero them.
        p = jnp.where(mask, p, 0.0)

        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p,
            v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # Skip k-blocks strictly above the diagonal.
        @pl.when(ik * block_k <= iq * block_q + block_q - 1)
        def _run():
            body()

    else:
        body()

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_ref[:, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)  # fully-masked q rows → 0 output
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    block_q: int,
    block_k: int,
    interpret: bool,
) -> jax.Array:
    b, s, h, d = q.shape
    scale = d**-0.5

    # [b, s, h, d] → [b, h, s, d]; pad head dim to the 128-lane width and
    # the sequence to a block multiple. Zero-padded head lanes add 0 to
    # q·k and produce zero output columns (sliced off below).
    seq_multiple = math.lcm(block_q, block_k)

    def prep(x):
        x = jnp.moveaxis(x, 1, 2)
        x = _pad_to(x, 3, _LANES)
        return _pad_to(x, 2, seq_multiple)

    qp, kp, vp = prep(q), prep(k), prep(v)
    s_pad, d_pad = qp.shape[2], qp.shape[3]
    block_q = min(block_q, s_pad)
    block_k = min(block_k, s_pad)
    assert s_pad % block_q == 0 and s_pad % block_k == 0
    nq, nk = s_pad // block_q, s_pad // block_k

    kernel = functools.partial(
        _flash_fwd_kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        s_valid=s,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, d_pad), lambda ib, ih, iq, ik: (ib, ih, iq, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, d_pad), lambda ib, ih, iq, ik: (ib, ih, ik, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, d_pad), lambda ib, ih, iq, ik: (ib, ih, ik, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d_pad), lambda ib, ih, iq, ik: (ib, ih, iq, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, s_pad, d_pad), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d_pad), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return jnp.moveaxis(out[:, :, :s, :d], 2, 1)  # → [b, s, h, d]


def _attention_reference(q, k, v, causal):
    """Plain-XLA attention used for the recomputing backward pass."""
    b, s, h, d = q.shape
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * (d**-0.5)
    if causal:
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, causal, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q, k, v: _attention_reference(q, k, v, causal), q, k, v
    )
    return vjp(g)


_flash_attention.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention over ``[batch, seq, heads, head_dim]`` inputs.

    ``interpret=None`` auto-selects: compiled on TPU backends, Pallas
    interpreter elsewhere (CPU test meshes).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_attention(q, k, v, causal, block_q, block_k, interpret)
