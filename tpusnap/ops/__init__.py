"""TPU-native ops: collective attention kernels for long-context models.

The reference (torchsnapshot) ships no model ops — checkpointing of
SP/CP-sharded state reduces to sharded arrays (SURVEY.md §5,
"Long-context/sequence parallelism"). tpusnap ships the ops anyway so its
flagship model exercises every sharding the preparers must round-trip:
ring attention gives sequence/context parallelism over a mesh axis.
"""

from .flash_attention import flash_attention  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401

__all__ = ["flash_attention", "ring_attention"]
