"""Checkpoint SLOs: continuous RPO/RTO tracking + data-at-risk accounting.

A checkpointing system exists to bound two numbers — how much work a
crash loses (the recovery-point objective, RPO) and how long recovery
takes (the recovery-time objective, RTO) — yet PRs 2–9 measured
everything *except* them. This module is that instrument, fed entirely
from seams that already exist:

- **Commit anchor** — both commit paths (``Snapshot.take`` and the
  async drain's ``_body_impl``) call :meth:`SLOTracker.record_commit`
  strictly after the metadata write, anchoring
  ``last_commit`` (monotonic + wall), the committed snapshot's payload
  bytes and take_id, and the commit interval (the realized RPO of the
  interval that just closed).
- **Data-at-risk accumulator** — bytes mutated since that anchor.
  Three evidence tiers, best available wins: an explicit
  :func:`record_step` call from the training loop (exact), the
  incremental take's dual-hash change stats (planned bytes minus
  ``scheduler.dedup_skipped_bytes`` — what the CRC32C+XXH64 pass proved
  unchanged costs nothing to lose), or the take's planned payload bytes
  (full takes: everything staged is at risk until committed).
- **RTO estimator** — committed snapshot bytes over the trailing-median
  restore READ throughput from ``history.jsonl`` (same trailing-window
  shape as ``history --check``; fewer than ``min_baseline`` comparable
  restore events → no verdict, exit 3 at the CLI), plus the trailing
  median of the restores' non-read overhead (plan/targets/load/metadata
  phases). No cold filtering on purpose: a real crash recovery IS a
  cold process.

Publication rides the PR 9 pump — no new threads:
:func:`attach_to_take` registers a :meth:`ProgressMonitor.add_tick_hook`
that, at heartbeat cadence, refreshes the state, rewrites a local
sidecar (``TPUSNAP_TELEMETRY_DIR/slo/rank_<k>.json``, atomic
temp+rename — what ``python -m tpusnap slo`` reads), pushes the
``tpusnap_rpo_seconds`` / ``tpusnap_data_at_risk_bytes`` /
``tpusnap_estimated_rto_seconds`` / ``tpusnap_commit_interval_seconds``
gauges through the registered metrics sinks
(:class:`~tpusnap.metrics_export.PrometheusTextfileSink` implements
``on_slo_update``), and — on rank 0 of a multi-process take — folds a
fleet worst-case view from the heartbeat records every rank already
publishes to the coordination KV (one ``try_get_dir`` per beat, no new
keys, no new lifecycle). The same hook feeds the per-rank heartbeat
record (``rec["slo"]``) so ``tpusnap watch`` shows exposure, not just
progress. Each commit records an ``slo`` section into the take's
history event, and threshold crossings (``TPUSNAP_SLO_RPO_S`` /
``TPUSNAP_SLO_RTO_S``, 0 = unset) emit one edge-triggered
``slo_breach`` flight event + ``slo.breaches`` counter per episode.

Everything here is best-effort observability: a tracker failure can
never fail a take, and the CLI treats absent records as evidence gaps
(exit 3), not errors.

Monotonic-only invariant (TPS002, same scope as telemetry/progress/
history/flight): in-process durations run on the injectable monotonic
``clock``; wall-clock TIMESTAMPS go through the module's injectable
``_wall`` seam — the one cross-process computation (the CLI's
time-since-commit against a possibly-dead process's record) is a wall
timestamp difference by necessity, and says so.
"""

from __future__ import annotations

import json
import logging
import os
import statistics
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from .knobs import (
    get_heartbeat_interval_s,
    get_job_id,
    get_slo_rpo_threshold_s,
    get_slo_rto_threshold_s,
    get_slo_stream_cadence_x,
    get_telemetry_dir,
)

logger = logging.getLogger(__name__)

# Wall-clock seam: timestamps only (record anchors, sidecar staleness);
# in-process duration math runs on the monotonic clock — direct
# wall-clock CALLS are lint-forbidden here (TPS002); only this bare
# reference is allowed.
_wall = time.time

SLO_DIRNAME = "slo"


def slo_dir(base: Optional[str] = None) -> str:
    """Local directory holding the per-rank SLO state sidecars (under
    the telemetry dir — per-host, like ``history.jsonl``)."""
    return os.path.join(base or get_telemetry_dir(), SLO_DIRNAME)


def slo_rank_path(rank: int, base: Optional[str] = None) -> str:
    return os.path.join(slo_dir(base), f"rank_{rank}.json")


# --------------------------------------------------------- RTO estimator


@dataclass
class RTOEstimate:
    """One restore-time estimate. ``ok`` is False when there was not
    enough comparable restore history to form one at all (the CLI's
    exit-3 leg, mirroring ``history --check``)."""

    ok: bool
    reason: str
    seconds: Optional[float] = None
    read_gbps: Optional[float] = None
    overhead_s: Optional[float] = None
    n_baseline: int = 0
    # What priced the estimate: "history" (trailing restore medians) or
    # "probe" (the read-lane pipe ceiling — the cold-start fallback when
    # no comparable restore has run on this host yet).
    source: str = "history"

    def to_json(self) -> Dict[str, Any]:
        return {
            "ok": self.ok,
            "reason": self.reason,
            "seconds": self.seconds,
            "read_gbps": self.read_gbps,
            "overhead_s": self.overhead_s,
            "n_baseline": self.n_baseline,
            "source": self.source,
        }


def _load_recent_restore_events(
    max_bytes: int = 256 * 1024,
) -> List[Dict[str, Any]]:
    """The newest restore-shaped history events, parsed from only the
    file's TAIL: the estimator needs a 20-event trailing window, and a
    per-take parse of the whole (multi-MB-bounded) history.jsonl is
    exactly the kind of cost the ≤10% take-overhead guard exists to
    forbid. A partial first line (mid-file seek) is dropped like any
    torn line."""
    from .history import history_path

    path = history_path()
    out: List[Dict[str, Any]] = []
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            start = max(size - max_bytes, 0)
            f.seek(start)
            data = f.read()
    except OSError:
        return out
    lines = data.split(b"\n")
    if start > 0 and len(lines) > 1:
        lines = lines[1:]  # almost surely partial: mid-file seek
    for ln in lines:
        # Cheap pre-filter: the estimator only consumes restore events,
        # and json-parsing thousands of take lines per refresh is the
        # bulk of the tail cost (a take whose PATH contains "restore"
        # parses too — harmless, the kind filter drops it).
        if b"restore" not in ln:
            continue
        try:
            ev = json.loads(ln)
        except Exception:
            continue
        if isinstance(ev, dict):
            out.append(ev)
    return out


def _probe_read_ceiling(
    backend: Optional[str], events: List[Dict[str, Any]]
) -> Optional[float]:
    """Best read-lane throughput ceiling available without restore
    history: the in-process probe registry first (populated when
    TPUSNAP_PROBE ran in this process — the sidecar path), else the
    median ``probe_read_gbps`` of whatever history events exist (the
    CLI path, where the registry is empty). Backend labels in the
    registry may carry a tier suffix (``Plugin@tier``), hence the
    prefix match."""
    try:
        from .compress import pipe_ceilings_snapshot

        matches = [
            gbps
            for (label, lane), gbps in pipe_ceilings_snapshot().items()
            if lane == "read"
            and (
                backend is None
                or label == backend
                or label.startswith(backend + "@")
            )
        ]
        if matches:
            return max(matches)
    except Exception:
        pass
    vals = [
        float(e["probe_read_gbps"])
        for e in events
        if isinstance(e.get("probe_read_gbps"), (int, float))
        and (backend is None or e.get("plugin") == backend)
    ]
    if vals:
        return statistics.median(vals)
    return None


def estimate_rto(
    snapshot_bytes: int,
    events: Optional[List[Dict[str, Any]]] = None,
    *,
    window: int = 20,
    min_baseline: int = 3,
    rank: Optional[int] = 0,
    backend: Optional[str] = None,
) -> RTOEstimate:
    """Estimate the wall-clock of restoring ``snapshot_bytes`` from the
    trailing restore history: bytes over the median restore READ
    throughput (the ``restore.read`` phase when recorded, else the
    whole wall) plus the median non-read overhead (plan/targets/
    prepare/load — the part that does not scale with bytes). Comparable
    = ``kind == "restore"``, matching rank (default 0), positive
    bytes and wall. Cold restores are NOT filtered out: crash recovery
    is a cold process, and an estimator that only saw warm restores
    would flatter the fleet.

    ``backend`` (a storage-plugin class label, e.g. ``S3StoragePlugin``)
    restricts the baseline to restores that read from that backend —
    the tier-aware leg: a write-back-tiered snapshot whose local cache
    was evicted restores from the REMOTE tier, and pricing it with
    local-disk history would understate the RTO by the disk/cloud
    throughput ratio. Events recorded before the backend label existed
    carry none and are excluded by the filter (no verdict beats a wrong
    one)."""
    if events is None:
        events = _load_recent_restore_events()
    cand = [
        e
        for e in events
        if e.get("kind") == "restore"
        and (rank is None or e.get("rank", 0) == rank)
        and (backend is None or e.get("plugin") == backend)
        and (e.get("bytes") or 0) > 0
        and (e.get("wall_s") or 0) > 0
    ][-window:]
    if len(cand) < max(1, min_baseline):
        # Cold-start fallback: a host that has never restored still has a
        # read-lane probe ceiling if TPUSNAP_PROBE ran during any take or
        # restore — the probe streams through the same composed plugin
        # stack, so bytes/ceiling is an honest (overhead-free, hence
        # optimistic) RTO floor. Better labelled "probe" than exit-3.
        ceiling = _probe_read_ceiling(backend, events)
        if ceiling is not None and ceiling > 0:
            return RTOEstimate(
                ok=True,
                reason=(
                    f"probe read ceiling {ceiling:.2f} GB/s"
                    + (f" for backend {backend}" if backend else "")
                    + f" (only {len(cand)} comparable restore event(s); "
                    "no per-restore overhead term)"
                ),
                seconds=round(snapshot_bytes / 1e9 / ceiling, 3),
                read_gbps=round(ceiling, 4),
                overhead_s=0.0,
                n_baseline=len(cand),
                source="probe",
            )
        return RTOEstimate(
            ok=False,
            reason=(
                f"only {len(cand)} comparable restore event(s) in history"
                + (f" for backend {backend}" if backend else "")
                + f"; need {min_baseline} to estimate RTO"
            ),
            n_baseline=len(cand),
        )
    gbps_vals: List[float] = []
    overhead_vals: List[float] = []
    for e in cand:
        wall = float(e["wall_s"])
        nbytes = float(e["bytes"])
        read_s = (e.get("phases_s") or {}).get("restore.read")
        if not isinstance(read_s, (int, float)) or read_s <= 0:
            read_s = wall
        gbps_vals.append(nbytes / read_s / 1e9)
        overhead_vals.append(max(wall - read_s, 0.0))
    read_gbps = statistics.median(gbps_vals)
    overhead_s = statistics.median(overhead_vals)
    if read_gbps <= 0:
        return RTOEstimate(
            ok=False,
            reason="restore history carries zero read throughput",
            n_baseline=len(cand),
        )
    seconds = snapshot_bytes / 1e9 / read_gbps + overhead_s
    return RTOEstimate(
        ok=True,
        reason=(
            f"{len(cand)}-event trailing median: "
            f"{read_gbps:.2f} GB/s read + {overhead_s:.2f}s overhead"
        ),
        seconds=round(seconds, 3),
        read_gbps=round(read_gbps, 4),
        overhead_s=round(overhead_s, 4),
        n_baseline=len(cand),
    )


# -------------------------------------------------------------- tracker


class SLOTracker:
    """Per-process SLO state machine. One instance per process (see
    :func:`tracker`); every method is thread-safe (the pump's tick hook
    runs on the heartbeat thread, ``record_commit`` on the main or the
    async commit thread, ``record_step`` on the training loop).

    ``clock``/``wall`` are injectable so the unit tests drive RPO/
    interval math on fake clocks with zero sleeps."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
    ) -> None:
        self._clock = clock
        self._wall_fn = wall
        self._lock = threading.Lock()
        self._start_mono = clock()
        self._start_wall = wall()
        self.rank = 0
        self.world_size = 1
        # Commit anchor.
        self._commit_mono: Optional[float] = None
        self._commit_wall: Optional[float] = None
        self._commit_take_id: Optional[str] = None
        self._commit_path: Optional[str] = None
        self._commit_interval_s: Optional[float] = None
        self._snapshot_bytes: int = 0
        # Data-at-risk evidence tiers (reset per commit).
        self._explicit_bytes: int = 0
        self._planned_bytes: int = 0
        self._planned_incremental = False
        self._last_change_bytes: Optional[int] = None
        # Capture slot of the in-flight take (take_id-guarded): a
        # committed snapshot holds state as of its CAPTURE (staging),
        # not its commit — an async take's drain can run minutes, and
        # anchoring RPO at commit time would zero out exposure the
        # snapshot does not actually cover. note_planned fills it;
        # record_commit consumes it when the ids match.
        self._capture: Optional[Dict[str, Any]] = None
        # Live counter feed of the in-flight take (dual-hash change
        # stats for incremental takes); None between takes.
        self._live_counters: Optional[Callable[[], Dict[str, int]]] = None
        # Cached RTO estimate (refreshed at attach/commit, never per
        # tick — the estimator reads history.jsonl; the stat key skips
        # even that read when the file hasn't changed).
        self._rto = RTOEstimate(ok=False, reason="no commit yet")
        self._rto_key: Optional[tuple] = None
        # Edge-triggered breach episodes.
        self._breached: Dict[str, bool] = {"rpo": False, "rto": False}
        # Active delta stream's cadence (tpusnap.delta): micro-commits
        # anchor the RPO like any commit; the cadence published here is
        # the CONFIGURED bound a healthy stream keeps rpo_s under, so
        # readers can tell "seconds-scale by design" from "minutes-scale
        # between takes". None = no stream active.
        self._stream_cadence_s: Optional[float] = None
        # Sidecar write throttle (monotonic) + write serialization: the
        # pump's tick hook and a commit thread's forced publish share
        # one per-pid temp filename — unserialized, the second open
        # truncates the first's partial write and the "atomic" rename
        # installs torn JSON (the same race metrics_export._absorb
        # holds its lock for).
        self._last_sidecar_t: Optional[float] = None
        self._publish_lock = threading.Lock()
        self._fleet: Optional[Dict[str, Any]] = None
        # Peer ranks declared dead by the liveness layer during the
        # current take (tpusnap.liveness.LivenessMonitor feeds this);
        # cleared when a take commits or aborts — a dead peer from a
        # finished take is history, not live exposure.
        self._dead_ranks: set = set()

    # --- inputs ---------------------------------------------------------

    def configure(self, rank: int, world_size: int) -> None:
        with self._lock:
            self.rank = rank
            self.world_size = world_size

    def note_rank_dead(self, ranks) -> None:
        """Liveness feed: ``ranks``' leases expired during the current
        take. Rides the sidecar/gauges until the take settles."""
        with self._lock:
            self._dead_ranks.update(int(r) for r in ranks)

    def record_step(self, bytes_changed: int) -> None:
        """Training-loop API: declare that ``bytes_changed`` bytes of
        checkpointable state were mutated since the last call — the
        exact evidence tier of the data-at-risk accumulator."""
        if bytes_changed > 0:
            with self._lock:
                self._explicit_bytes += int(bytes_changed)

    def note_planned(
        self,
        nbytes: int,
        incremental: bool,
        live_counters: Optional[Callable[[], Dict[str, int]]] = None,
        take_id: Optional[str] = None,
    ) -> None:
        """Take-path seam (where the heartbeat's ``set_bytes_planned``
        already sits): the in-flight take's payload bytes become the
        data-at-risk floor until its commit clears them. For
        incremental takes the live dual-hash change stats
        (``live_counters`` → ``scheduler.dedup_skipped_bytes``) refine
        the figure as staging proves tiles unchanged. Also records the
        take's CAPTURE anchor — the instant whose state the eventual
        commit makes durable — so the RPO clock and the explicit-step
        accumulator stay honest across a long async drain."""
        with self._lock:
            self._planned_bytes = max(self._planned_bytes, int(nbytes))
            self._planned_incremental = incremental
            self._live_counters = live_counters
            self._capture = {
                "take_id": take_id,
                "mono": self._clock(),
                "wall": self._wall_fn(),
                "explicit_before": self._explicit_bytes,
            }
            have_estimate = self._rto.ok
        if not have_estimate:
            # First take of the process: no commit has sized the
            # estimator yet, but a crash DURING this take restores
            # roughly these bytes — price them now so the pre-crash
            # gauge is live (the crash-matrix acceptance reads it).
            self.refresh_rto()

    def note_stream(self, cadence_s: Optional[float]) -> None:
        """A delta stream opened (``cadence_s`` set) or closed (None):
        the configured micro-commit cadence rides the published state so
        dashboards can grade ``rpo_s`` against the stream's own bound
        rather than a fleet-wide threshold."""
        with self._lock:
            self._stream_cadence_s = (
                float(cadence_s) if cadence_s else None
            )

    def note_take_aborted(self) -> None:
        """Abort-path bookkeeping (the take's ``on_failure``): release
        the dead take's telemetry record — its counters must not stay
        referenced for the process lifetime — WITHOUT clearing the
        exposure: nothing committed, so the planned bytes are still at
        risk. The incremental refinement is frozen at its last observed
        value (the dual-hash skip evidence stays valid: the base holds
        those unchanged bytes regardless of the abort)."""
        with self._lock:
            if self._live_counters is not None:
                try:
                    skipped = self._live_counters().get(
                        "scheduler.dedup_skipped_bytes", 0
                    )
                except Exception:
                    skipped = 0
                if self._planned_incremental:
                    self._planned_bytes = max(self._planned_bytes - skipped, 0)
            self._live_counters = None
            self._planned_incremental = False
            # The aborted take's capture anchor is dead — a later
            # commit must not mistake its slot for pending evidence
            # (which would keep exposure standing forever). If a newer
            # overlapping take had overwritten the slot, its commit
            # merely falls back to commit-time anchoring: conservative.
            self._capture = None
            self._dead_ranks.clear()

    def record_commit(
        self,
        take_id: str,
        path: str,
        snapshot_bytes: int,
        incremental: bool = False,
        counters: Optional[Dict[str, int]] = None,
    ) -> Dict[str, Any]:
        """Commit anchor (both commit paths, strictly after the
        metadata write). Closes the interval, clears the at-risk
        accumulators, refreshes the RTO estimate against the bytes just
        committed, force-publishes, and returns the compact ``slo``
        section the take's summary/history event carries."""
        counters = counters or {}
        now = self._clock()
        now_wall = self._wall_fn()
        with self._lock:
            # Anchor at the take's CAPTURE, not its commit: the
            # committed snapshot holds state as of staging, and an
            # async drain between the two can run minutes — work done
            # in that window is NOT in the snapshot and must survive
            # as exposure (explicit steps recorded after capture keep
            # accumulating; RPO restarts from capture time).
            cap = self._capture
            # An unscoped capture (take_id None) matches any commit —
            # the single-slot semantics callers outside the take path
            # get by default.
            matched = cap is not None and cap.get("take_id") in (
                None,
                take_id,
            )
            change = self._interval_change_bytes_locked(
                counters,
                incremental,
                # Drain-window record_step bytes are NOT in this
                # snapshot: the interval's realized change bounds the
                # explicit tier at the capture-time figure, and the
                # remainder stays live exposure for the NEXT event —
                # counted once, not twice.
                explicit_cap=cap["explicit_before"] if matched else None,
            )
            anchor_mono = cap["mono"] if matched else now
            anchor_wall = cap["wall"] if matched else now_wall
            interval = max(
                anchor_mono - self._commit_mono
                if self._commit_mono is not None
                else anchor_mono - self._start_mono,
                0.0,
            )
            self._commit_mono = anchor_mono
            self._commit_wall = anchor_wall
            self._commit_take_id = take_id
            self._commit_path = path
            self._commit_interval_s = interval
            self._snapshot_bytes = int(snapshot_bytes)
            self._last_change_bytes = change
            if matched:
                # Steps recorded before the capture are durable now;
                # drain-window steps remain at risk.
                self._explicit_bytes = max(
                    self._explicit_bytes - cap["explicit_before"], 0
                )
                self._capture = None
                self._planned_bytes = 0
                self._planned_incremental = False
                self._live_counters = None
            else:
                # A newer take's registration is in the slot (or none
                # was made): leave the pending take's evidence alone —
                # clearing it would understate what ITS crash loses —
                # and only reset the explicit tier conservatively if no
                # newer capture exists.
                if cap is None:
                    self._explicit_bytes = 0
                    self._planned_bytes = 0
                    self._planned_incremental = False
                    self._live_counters = None
            self._fleet = None
            self._dead_ranks.clear()
        self.refresh_rto()
        section = {
            "commit_interval_s": round(interval, 3),
            "change_bytes": change,
            "snapshot_bytes": int(snapshot_bytes),
            "estimated_rto_s": self._rto.seconds if self._rto.ok else None,
        }
        self.publish(force=True)
        return section

    def _interval_change_bytes_locked(
        self,
        counters: Dict[str, int],
        incremental: bool,
        explicit_cap: Optional[int] = None,
    ) -> int:
        """Bytes mutated in the interval that just closed — the realized
        data-at-risk the commit cleared. Incremental takes have the
        exact dual-hash answer: at commit, the take-local written
        payload IS the changed set (whole-blob skips never write, slab
        compaction keeps only changed members, tile-grain dedup writes
        only changed tiles); planned-minus-skipped is only the LIVE
        mid-take approximation (it cannot see member/tile grain).
        ``explicit_cap`` bounds the explicit tier at the committed
        take's capture-time value — post-capture steps belong to the
        NEXT interval."""
        explicit = self._explicit_bytes
        if explicit_cap is not None:
            explicit = min(explicit, explicit_cap)
        if incremental:
            written = counters.get("storage.bytes_written", 0)
            if written <= 0:
                skipped = counters.get("scheduler.dedup_skipped_bytes", 0)
                written = max(self._planned_bytes - skipped, 0)
            return max(written, explicit)
        return max(self._planned_bytes, explicit)

    def refresh_rto(self) -> None:
        """Recompute the cached RTO estimate from history (called at
        attach and commit time — never per tick; the estimator reads
        only the history file's tail, and a stat-key cache skips even
        that when nothing changed — the ≤10% take-overhead guard
        budget). Best-effort."""
        with self._lock:
            nbytes = self._snapshot_bytes or self._planned_bytes
        if not nbytes:
            return
        try:
            from .history import history_path

            try:
                st = os.stat(history_path())
                key = (st.st_mtime_ns, st.st_size, nbytes)
            except OSError:
                key = (0, 0, nbytes)
            # Tier-aware pricing: for a write-back-tiered snapshot the
            # estimator must use the history of the tier a restore
            # would ACTUALLY read from — local while the cache is
            # intact, remote once any blob was evicted. None for
            # non-tiered paths (no filter, today's behavior).
            backend = None
            with self._lock:
                path = self._commit_path
            if path:
                try:
                    from .tiering import restore_source_label

                    backend = restore_source_label(path)
                except Exception:
                    backend = None
            with self._lock:
                key = key + (backend,)
                if key == self._rto_key:
                    return
                rank = self.rank
            # THIS rank's restore history: a host running ranks 8-15
            # has no rank-0 events, and its recovery restores its own
            # view under the same disk sharing its peers impose.
            est = estimate_rto(nbytes, rank=rank, backend=backend)
            with self._lock:
                self._rto = est
                self._rto_key = key
        except Exception:
            logger.debug("RTO estimate failed", exc_info=True)

    # --- state ----------------------------------------------------------

    def data_at_risk_bytes(self) -> int:
        """Current worst-case bytes a crash would lose: the best
        available evidence tier (explicit steps / incremental change
        stats / planned payload), conservative max across them."""
        with self._lock:
            planned = self._planned_bytes
            if self._planned_incremental and self._live_counters is not None:
                try:
                    skipped = self._live_counters().get(
                        "scheduler.dedup_skipped_bytes", 0
                    )
                except Exception:
                    skipped = 0
                planned = max(planned - skipped, 0)
            return max(self._explicit_bytes, planned)

    def rpo_s(self, now: Optional[float] = None) -> float:
        """Seconds since the last commit anchor (since tracker start
        when nothing ever committed — everything is at risk)."""
        now = self._clock() if now is None else now
        with self._lock:
            anchor = (
                self._commit_mono
                if self._commit_mono is not None
                else self._start_mono
            )
        return max(now - anchor, 0.0)

    def snapshot_state(self) -> Dict[str, Any]:
        """One consistent, JSON-ready view of the tracker — the sidecar
        record, the sink payload, and the heartbeat sub-dict all derive
        from this."""
        rpo = self.rpo_s()
        at_risk = self.data_at_risk_bytes()
        rpo_thresh = get_slo_rpo_threshold_s()
        rto_thresh = get_slo_rto_threshold_s()
        with self._lock:
            rto = self._rto
            state: Dict[str, Any] = {
                "v": 1,
                "rank": self.rank,
                "world_size": self.world_size,
                "job_id": get_job_id(),
                "pid": os.getpid(),
                "ts": self._wall_fn(),
                "started_ts": self._start_wall,
                "last_commit_ts": self._commit_wall,
                "last_commit_take_id": self._commit_take_id,
                "path": self._commit_path,
                "commit_interval_s": (
                    round(self._commit_interval_s, 3)
                    if self._commit_interval_s is not None
                    else None
                ),
                "rpo_s": round(rpo, 3),
                "data_at_risk_bytes": int(at_risk),
                "last_change_bytes": self._last_change_bytes,
                "snapshot_bytes": self._snapshot_bytes,
                "estimated_rto_s": rto.seconds if rto.ok else None,
                "rto_read_gbps": rto.read_gbps if rto.ok else None,
                "rto_n_baseline": rto.n_baseline,
                "rto_source": rto.source if rto.ok else None,
                "stream_cadence_s": self._stream_cadence_s,
                # Peer ranks the liveness layer declared dead during
                # the current take (tpusnap.liveness) — the slo CLI's
                # `dead` column: an RPO breach with a dead peer is a
                # rank failure, not a slow checkpoint cadence.
                "dead_ranks": sorted(self._dead_ranks) or None,
                "thresholds": {
                    "rpo_s": rpo_thresh or None,
                    "rto_s": rto_thresh or None,
                },
            }
            if self._fleet:
                state["fleet"] = dict(self._fleet)
        state["breach"] = {
            "rpo": bool(rpo_thresh and rpo > rpo_thresh),
            "rto": bool(
                rto_thresh and rto.ok and rto.seconds is not None
                and rto.seconds > rto_thresh
            ),
        }
        return state

    def heartbeat_fields(self) -> Dict[str, Any]:
        """The compact sub-dict the per-rank heartbeat record carries
        (``rec["slo"]``) — what ``tpusnap watch``'s exposure columns
        and rank 0's fleet fold read."""
        with self._lock:
            rto = self._rto
        return {
            "rpo_s": round(self.rpo_s(), 2),
            "data_at_risk_bytes": int(self.data_at_risk_bytes()),
            "estimated_rto_s": rto.seconds if rto.ok else None,
        }

    # --- publication ----------------------------------------------------

    def publish(self, force: bool = False, final: bool = False) -> None:
        """Refresh → breach check → sidecar write (throttled to the
        heartbeat interval unless forced) → sink notify. Never raises.
        ``final`` marks the sidecar as a clean process exit: readers
        then FREEZE the exposure at the record's write time instead of
        growing it live — a finished run is not an incident, while a
        SIGKILLed one (which never writes the marker) correctly keeps
        screaming until someone recovers."""
        try:
            state = self.snapshot_state()
            if final:
                state["final"] = True
        except Exception:
            logger.debug("slo state build failed", exc_info=True)
            return
        self._check_breaches(state)
        now = self._clock()
        with self._lock:
            due = (
                force
                or self._last_sidecar_t is None
                or now - self._last_sidecar_t >= get_heartbeat_interval_s()
            )
            if due:
                self._last_sidecar_t = now
        if due:
            with self._publish_lock:
                try:
                    self._write_sidecar(state)
                    _arm_atexit_finalizer()
                except Exception:
                    logger.debug("slo sidecar write failed", exc_info=True)
                try:
                    from . import telemetry

                    telemetry.notify_slo_update(state)
                except Exception:
                    logger.debug("slo sink notify failed", exc_info=True)

    def _write_sidecar(self, state: Dict[str, Any]) -> None:
        d = slo_dir()
        os.makedirs(d, exist_ok=True)
        path = slo_rank_path(state["rank"])
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, path)

    def _check_breaches(self, state: Dict[str, Any]) -> None:
        """Edge-triggered: ONE flight event + counter per breach
        episode per objective; recovery re-arms."""
        for key in ("rpo", "rto"):
            breached = state["breach"][key]
            with self._lock:
                fire = breached and not self._breached[key]
                self._breached[key] = breached
            if fire:
                try:
                    from . import flight, telemetry

                    telemetry.incr("slo.breaches")
                    flight.record(
                        "slo_breach",
                        op=key,
                        rpo_s=state["rpo_s"],
                        data_at_risk_bytes=state["data_at_risk_bytes"],
                        estimated_rto_s=state["estimated_rto_s"],
                        threshold_s=state["thresholds"][f"{key}_s"],
                    )
                except Exception:
                    logger.debug("slo breach record failed", exc_info=True)
                logger.warning(
                    "tpusnap SLO breach: %s — rpo %.1fs, %d bytes at risk, "
                    "estimated RTO %s (thresholds rpo=%s rto=%s)",
                    key.upper(),
                    state["rpo_s"],
                    state["data_at_risk_bytes"],
                    state["estimated_rto_s"],
                    state["thresholds"]["rpo_s"],
                    state["thresholds"]["rto_s"],
                )

    def make_tick_hook(self, take_id: str, kv=None):
        """The :meth:`ProgressMonitor.add_tick_hook` piggyback: publish
        at the pump's own publish cadence (``record is not None`` — the
        same delta-throttle + keep-alive the heartbeat uses), and on
        rank 0 of a multi-process take fold the fleet worst-case view
        from the heartbeat records every rank already published to the
        KV (no new keys: the slo sub-dict rides ``rec["slo"]``)."""

        def hook(record: Optional[Dict[str, Any]]) -> None:
            if record is None:
                return
            if kv is not None and self.rank == 0 and self.world_size > 1:
                self._fold_fleet(take_id, kv)
            self.publish()

        return hook

    def _fold_fleet(self, take_id: str, kv) -> None:
        try:
            blobs = kv.try_get_dir(f"tpusnap_progress/{take_id}/")
        except Exception:
            blobs = None
        if not blobs:
            return
        rpo, at_risk, rto, ranks = 0.0, 0, None, 0
        now_wall = self._wall_fn()
        for raw in blobs.values():
            try:
                rec = json.loads(raw)
                s = rec.get("slo")
            except Exception:
                continue
            if not isinstance(s, dict):
                continue
            ranks += 1
            # A hung rank's frozen heartbeat must not freeze the fleet
            # gauge: its true exposure is the published figure PLUS how
            # stale the record is (same correction the watch table
            # applies per row).
            staleness = max(now_wall - (rec.get("ts") or now_wall), 0.0)
            rpo = max(rpo, float(s.get("rpo_s") or 0.0) + staleness)
            at_risk = max(at_risk, int(s.get("data_at_risk_bytes") or 0))
            r = s.get("estimated_rto_s")
            if isinstance(r, (int, float)):
                rto = max(rto, float(r)) if rto is not None else float(r)
        if not ranks:
            return
        with self._lock:
            self._fleet = {
                "ranks": ranks,
                "rpo_s": round(rpo, 2),
                "data_at_risk_bytes": at_risk,
                "estimated_rto_s": rto,
            }


# ------------------------------------------------- process-global wiring

_tracker: Optional[SLOTracker] = None
_tracker_lock = threading.Lock()
_atexit_armed = False
_crashed = False


def _arm_atexit_finalizer() -> None:
    """Register the clean-exit sidecar finalizer, once, and only for
    processes that actually published SLO state (an importing process
    that never took a snapshot must not grow a sidecar at exit). An
    unhandled exception ALSO runs atexit, so the chained excepthook
    below is what keeps a crashed-by-exception process from being
    stamped as a clean exit — the gate must keep screaming about it."""
    global _atexit_armed
    with _tracker_lock:
        if _atexit_armed:
            return
        _atexit_armed = True
    import atexit
    import sys

    prev_hook = sys.excepthook

    def _crash_hook(exc_type, exc, tb):
        global _crashed
        _crashed = True
        prev_hook(exc_type, exc, tb)

    sys.excepthook = _crash_hook
    atexit.register(_finalize_on_exit)


def _finalize_on_exit() -> None:
    with _tracker_lock:
        t = _tracker
    if t is None or _crashed:
        # Crashed-by-exception: leave the last live record standing so
        # readers keep growing its exposure, exactly like a SIGKILL.
        return
    try:
        t.publish(force=True, final=True)
    except Exception:
        pass


def tracker() -> SLOTracker:
    """The process-global tracker (created on first use)."""
    global _tracker
    with _tracker_lock:
        if _tracker is None:
            _tracker = SLOTracker()
        return _tracker


def reset_tracker() -> None:
    """Test aid; production code never resets."""
    global _tracker
    with _tracker_lock:
        _tracker = None


def record_step(bytes_changed: int) -> None:
    """Training-loop API: ``tpusnap.slo.record_step(bytes_changed=N)``
    after each optimizer step makes the data-at-risk gauge exact
    instead of take-granular."""
    tracker().record_step(bytes_changed)


def attach_to_take(monitor, take_id: str, rank: int, world_size: int) -> None:
    """Wire the tracker into one take's heartbeat pump: the slo
    sub-dict rides every published heartbeat record, and the tick hook
    publishes the gauges/sidecar at the pump's cadence. Called from
    ``_take_impl`` right after the monitor starts; best-effort."""
    t = tracker()
    t.configure(rank, world_size)
    monitor.set_slo_provider(t.heartbeat_fields)
    monitor.add_tick_hook(t.make_tick_hook(take_id, kv=monitor.kv))


# --------------------------------------------------------------- reading


def read_slo_records(directory: Optional[str] = None) -> List[Dict[str, Any]]:
    """All parseable per-rank SLO sidecars under the slo dir, sorted by
    rank. Tolerant of torn/absent files (atomic writers, but the dir
    may not exist yet)."""
    d = directory or slo_dir()
    out: List[Dict[str, Any]] = []
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for name in sorted(names):
        if not (name.startswith("rank_") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(d, name), "r") as f:
                rec = json.load(f)
            if isinstance(rec, dict):
                out.append(rec)
        except Exception:
            continue
    return sorted(out, key=lambda r: r.get("rank", 0))


def evaluate_records(
    records: List[Dict[str, Any]],
    rpo_threshold_s: Optional[float] = None,
    rto_threshold_s: Optional[float] = None,
    now: Optional[float] = None,
) -> Dict[str, Any]:
    """The ``slo --check`` verdict over per-rank records, thresholds
    defaulting to the knobs. Per rank, the LIVE time-since-commit is
    recomputed from the record's wall anchors (the publishing process
    may be long dead — its frozen ``rpo_s`` would understate exposure;
    a wall-timestamp difference is the only cross-process clock there
    is). Verdict: ``breach`` when any rank crosses a set threshold;
    ``insufficient`` when there are no records at all, or when an RTO
    threshold is set but no rank has an estimate (same no-verdict
    stance as ``history --check``'s exit 3); else ``healthy``."""
    now = _wall() if now is None else now
    if rpo_threshold_s is None:
        rpo_threshold_s = get_slo_rpo_threshold_s() or None
    if rto_threshold_s is None:
        rto_threshold_s = get_slo_rto_threshold_s() or None
    stream_x = get_slo_stream_cadence_x()
    rows: List[Dict[str, Any]] = []
    any_rto = False
    breach = False
    for rec in records:
        anchor = rec.get("last_commit_ts") or rec.get("started_ts") or now
        # A record marked `final` is a CLEAN process exit: exposure is
        # frozen at the write time (a finished run is not an incident).
        # Records without the marker — a live process, or one that was
        # SIGKILLed before it could write it — grow live from the wall
        # anchor, so a dead-but-unrecovered job keeps breaching.
        final = bool(rec.get("final"))
        ref = rec.get("ts") if final and rec.get("ts") else now
        since_commit = max(ref - anchor, 0.0)
        rto = rec.get("estimated_rto_s")
        fleet = rec.get("fleet") or {}
        row = {
            "rank": rec.get("rank", 0),
            "world_size": rec.get("world_size", 1),
            "path": rec.get("path"),
            "final": final,
            "since_commit_s": round(since_commit, 2),
            "data_at_risk_bytes": int(rec.get("data_at_risk_bytes") or 0),
            "estimated_rto_s": rto,
            # "history" (restore-event medians) or "probe" (read-lane
            # ceiling cold-start fallback — no overhead term, optimistic).
            "rto_source": rec.get("rto_source"),
            "record_age_s": round(max(now - (rec.get("ts") or now), 0.0), 2),
            "committed": rec.get("last_commit_ts") is not None,
            "fleet": fleet or None,
            # A live delta stream's configured micro-commit cadence
            # (tpusnap.delta) — the bound a healthy stream keeps
            # since_commit under; None when no stream was active.
            "stream_cadence_s": rec.get("stream_cadence_s"),
            # Peer ranks this rank's liveness layer declared dead
            # during its current take — exposure with a dead peer is a
            # rank failure, not a slow cadence.
            "dead_ranks": rec.get("dead_ranks"),
        }
        row["breach_rpo"] = bool(
            rpo_threshold_s and since_commit > rpo_threshold_s
        )
        row["breach_rto"] = bool(
            rto_threshold_s
            and isinstance(rto, (int, float))
            and rto > rto_threshold_s
        )
        # The stream-cadence gate: a LIVE delta stream (non-final record
        # advertising a cadence) that has not committed for more than
        # N x its declared cadence has silently stalled — that is a
        # breach even with no absolute RPO threshold configured (the
        # declared cadence IS the operator's objective).
        cadence = row["stream_cadence_s"]
        row["breach_stream"] = bool(
            stream_x
            and not final
            and isinstance(cadence, (int, float))
            and cadence > 0
            and since_commit > stream_x * cadence
        )
        if isinstance(rto, (int, float)):
            any_rto = True
        breach = (
            breach
            or row["breach_rpo"]
            or row["breach_rto"]
            or row["breach_stream"]
        )
        rows.append(row)
    if not rows:
        verdict = "insufficient"
        reason = "no SLO records found (no instrumented process ran here)"
    elif breach:
        verdict = "breach"
        worst = max(rows, key=lambda r: r["since_commit_s"])
        reason = (
            f"rank {worst['rank']}: {worst['since_commit_s']:.1f}s since "
            f"last commit, {worst['data_at_risk_bytes']} bytes at risk"
        )
        if worst.get("breach_stream"):
            reason += (
                f" (live stream declared a {worst['stream_cadence_s']}s "
                f"cadence; observed RPO exceeds {stream_x:g}x it)"
            )
    elif rto_threshold_s and not any_rto:
        verdict = "insufficient"
        reason = (
            "RTO threshold set but no rank has an estimate (needs ≥3 "
            "comparable restore events in history.jsonl)"
        )
    else:
        verdict = "healthy"
        reason = f"{len(rows)} rank(s) within thresholds"
    return {
        "verdict": verdict,
        "reason": reason,
        "thresholds": {
            "rpo_s": rpo_threshold_s,
            "rto_s": rto_threshold_s,
            "stream_cadence_x": stream_x or None,
        },
        "ranks": rows,
    }
