"""URL scheme → StoragePlugin registry.

Counterpart of /root/reference/torchsnapshot/storage_plugin.py:18-70.
Built-ins: fs (default), s3, gs/gcs, and a generic fsspec bridge
(``fsspec+<protocol>://``). Third-party plugins register through the
``tpusnap.storage_plugins`` entry-point group.

Two middleware layers compose around the raw plugin:

- ``chaos+<scheme>://`` wraps the resolved plugin in deterministic fault
  injection (``tpusnap.faults``) — any test, example or benchmark runs
  against a misbehaving backend with only a URL change
  (``storage_options["fault_plan"]`` or TPUSNAP_FAULT_SPEC tune it).
- Retry middleware (``tpusnap.retry``) wraps built-in plugins that do
  not handle their own retries (fs, s3, fsspec — gcs retries internally
  at chunk grain, which is strictly finer). Disable per call with
  ``storage_options={"retry": False}``; tune via the ``retry_*`` keys.
  Runtime-registered and entry-point plugins are returned as built —
  their factories opt in by wrapping with ``RetryingStoragePlugin``
  themselves.
"""

import asyncio
import time
from importlib.metadata import entry_points
from typing import Any, Dict, Optional

from .devtools import lockwatch as _lockwatch
from .io_types import SIDECAR_PREFIX, ReadIO, StoragePlugin, WriteIO

_ENTRY_POINT_GROUP = "tpusnap.storage_plugins"

_CHAOS_PREFIX = "chaos+"

# scheme → factory(path, storage_options) registered at runtime; consulted
# before entry points so tests/apps can inject plugins without packaging.
_RUNTIME_REGISTRY: Dict[str, Any] = {}


def register_storage_plugin(scheme: str, factory: Any) -> None:
    """Register ``factory(path, storage_options) -> StoragePlugin`` for a
    URL scheme at runtime (complements the ``tpusnap.storage_plugins``
    entry-point group, reference storage_plugin.py:53-65)."""
    _RUNTIME_REGISTRY[scheme.lower()] = factory


def unregister_storage_plugin(scheme: str) -> None:
    """Remove a runtime-registered scheme (no-op if absent). Runtime
    registrations shadow the built-in schemes, so scoped users (tests,
    fault injection) must clean up to avoid redirecting default paths."""
    _RUNTIME_REGISTRY.pop(scheme.lower(), None)


def _resolve_raw_plugin(
    scheme: str, path: str, storage_options: Optional[Dict[str, Any]]
) -> StoragePlugin:
    """Map a (chaos-stripped) scheme to a plugin instance, middleware-free."""
    if scheme in _RUNTIME_REGISTRY:
        return _RUNTIME_REGISTRY[scheme](path, storage_options)
    if scheme in ("", "fs", "file"):
        from .storage_plugins.fs import FSStoragePlugin

        return FSStoragePlugin(root=path, storage_options=storage_options)
    if scheme == "s3":
        from .storage_plugins.s3 import S3StoragePlugin

        return S3StoragePlugin(root=path, storage_options=storage_options)
    if scheme in ("gs", "gcs"):
        from .storage_plugins.gcs import GCSStoragePlugin

        return GCSStoragePlugin(root=path, storage_options=storage_options)
    if scheme.startswith("fsspec+"):
        from .storage_plugins.fsspec import FsspecStoragePlugin

        return FsspecStoragePlugin(
            protocol=scheme[len("fsspec+") :],
            root=path,
            storage_options=storage_options,
        )

    # Third-party plugins via entry points (reference storage_plugin.py:53-65).
    eps = entry_points()
    group = eps.select(group=_ENTRY_POINT_GROUP) if hasattr(eps, "select") else []
    for ep in group:
        if ep.name == scheme:
            factory = ep.load()
            return factory(path, storage_options)
    raise RuntimeError(f"Unsupported storage scheme: {scheme}:// ({path})")


class InstrumentedStoragePlugin(StoragePlugin):
    """Latency × size histogram instrumentation at the storage-plugin
    boundary (:func:`tpusnap.telemetry.observe_io`): every successful
    write/read/delete/list is timed on the monotonic clock and recorded
    into the process-global AND in-flight take's log2 histograms, keyed
    by ``<op>.<PluginClass>`` of the plugin it measures (the innermost
    raw backend, unwrapped through middleware). Composed INSIDE the
    retry middleware so each attempt is one sample — p99 means "p99 of
    actual backend ops", not "p99 including backoff sleeps" — and
    OUTSIDE the chaos layer so injected latency/stalls show up as the
    fat tails they are. Failures are not sampled (a raised write has no
    defensible latency). Everything else delegates to the wrapped
    plugin; unknown attributes pass through."""

    def __init__(self, inner: StoragePlugin) -> None:
        self.inner = inner
        base = inner
        while hasattr(base, "inner") and isinstance(
            getattr(base, "inner"), StoragePlugin
        ):
            base = base.inner
        self.label = type(base).__name__

    # --- attribute passthrough ----------------------------------------
    # ABC-defined attrs/methods never reach __getattr__; delegate them
    # explicitly so registry logic and the scheduler see the inner
    # plugin's capabilities.

    def __getattr__(self, name: str) -> Any:
        if name == "inner":
            # Only reachable when self.inner was never set (e.g. during
            # copy/unpickle protocols) — delegating would recurse.
            raise AttributeError(name)
        return getattr(self.inner, name)

    @property
    def supports_in_place_reads(self) -> bool:  # type: ignore[override]
        return self.inner.supports_in_place_reads

    @property
    def wants_retry_middleware(self) -> bool:  # type: ignore[override]
        return self.inner.wants_retry_middleware

    @property
    def handles_own_retries(self) -> bool:  # type: ignore[override]
        return self.inner.handles_own_retries

    def classify_transient(self, exc: BaseException) -> bool:
        return self.inner.classify_transient(exc)

    def in_place_read_overhead_bytes(self, nbytes: int) -> int:
        return self.inner.in_place_read_overhead_bytes(nbytes)

    def drain_in_flight(self) -> None:
        self.inner.drain_in_flight()

    # --- instrumented ops ---------------------------------------------

    # Sidecar/probe traffic (telemetry traces, heartbeats, journal
    # records, roofline probe streams) is NOT sampled: the histograms
    # gate PAYLOAD I/O tails (analyze --check, history's
    # storage_write_p99_s), and a stream of small fast sidecar writes —
    # or 16 MiB probe segments 32x faster than 512 MiB blob writes —
    # would drag p50 down and fire the p99/p50 gate on a healthy disk.
    _UNSAMPLED_PREFIX = SIDECAR_PREFIX

    @staticmethod
    def _note_blocking(op: str) -> None:
        """Lock-order watchdog hook (TPUSNAP_LOCKCHECK=1): record any
        tracked lock the calling thread holds across this storage op —
        a lock held for a disk/network round-trip is a starvation
        hazard worth a name in the report. Disabled (the default) this
        is one call + one None check; lockwatch itself is import-light
        (threading/atexit only)."""
        _lockwatch.note_blocking(f"storage_{op}")

    def _observe(self, op: str, path: str, t0: float, nbytes: int) -> None:
        if path.startswith(self._UNSAMPLED_PREFIX):
            return
        from . import telemetry

        try:
            telemetry.observe_io(
                op, self.label, time.monotonic() - t0, nbytes
            )
        except Exception:
            pass  # telemetry never fails an op

    async def write(self, write_io: WriteIO) -> None:
        t0 = time.monotonic()
        self._note_blocking("write")
        await self.inner.write(write_io)
        self._observe("write", write_io.path, t0, len(write_io.buf))

    async def write_atomic(self, write_io: WriteIO, durable: bool = False) -> None:
        t0 = time.monotonic()
        self._note_blocking("write")
        await self.inner.write_atomic(write_io, durable=durable)
        self._observe("write", write_io.path, t0, len(write_io.buf))

    @staticmethod
    def _read_nbytes(read_io: ReadIO) -> int:
        if read_io.byte_range is not None:
            return int(read_io.byte_range[1] - read_io.byte_range[0])
        if read_io.in_place and read_io.into is not None:
            return memoryview(read_io.into).nbytes
        try:
            return read_io.buf.getbuffer().nbytes
        except Exception:
            return 0

    async def read(self, read_io: ReadIO) -> None:
        t0 = time.monotonic()
        self._note_blocking("read")
        await self.inner.read(read_io)
        self._observe("read", read_io.path, t0, self._read_nbytes(read_io))

    async def delete(self, path: str) -> None:
        t0 = time.monotonic()
        await self.inner.delete(path)
        self._observe("delete", path, t0, 0)

    async def list_with_sizes(self) -> Optional[dict]:
        t0 = time.monotonic()
        out = await self.inner.list_with_sizes()
        self._observe("list", "", t0, 0)
        return out

    async def flush_created_dirs(self) -> None:
        await self.inner.flush_created_dirs()

    async def close(self) -> None:
        await self.inner.close()


def storage_plugin_label(plugin: StoragePlugin) -> str:
    """The innermost backend class name of a composed plugin — the same
    label the I/O histograms key on — used to tag restore history
    events with the backend they actually read from. A write-back
    tiered plugin is labeled by the tier a restore WOULD read
    (:func:`tpusnap.tiering.restore_source_label`): local while the
    cache is intact, remote once evicted."""
    from .tiering import TieredStoragePlugin, restore_source_label

    base = plugin
    while True:
        if isinstance(base, TieredStoragePlugin):
            try:
                label = restore_source_label(base.spec.url)
            except Exception:
                label = None
            return label or type(base).__name__
        inner = getattr(base, "inner", None)
        if isinstance(inner, StoragePlugin):
            base = inner
            continue
        return type(base).__name__


def url_to_storage_plugin(
    url_path: str, storage_options: Optional[Dict[str, Any]] = None
) -> StoragePlugin:
    """Map ``[scheme://]path`` to a storage plugin instance, composing the
    chaos and retry middleware layers as the scheme/options direct."""
    if "://" in url_path:
        scheme, path = url_path.split("://", 1)
    else:
        scheme, path = "fs", url_path

    # Write-back tiering composes BEFORE the lowercase/chaos handling:
    # the scheme embeds a case-sensitive local path, and both tiers
    # compose their own middleware internally (chaos belongs on the
    # remote sub-scheme: tier+local=...+remote=chaos+s3://...).
    if scheme.lower().startswith("tier+"):
        from .tiering import build_tiered_plugin

        return build_tiered_plugin(url_path, storage_options)

    # Content-addressed composition, explicit form: ``cas+<base>://``
    # wraps the base (with its ordinary middleware) in the CAS layer;
    # the shared store comes from storage_options['cas_dir'] /
    # TPUSNAP_CAS_DIR. Checked before lowercase (the path may embed a
    # case-sensitive directory name).
    if scheme.lower().startswith("cas+"):
        from .cas import build_cas_plugin

        return build_cas_plugin(url_path, storage_options)
    scheme = scheme.lower()

    chaos = scheme.startswith(_CHAOS_PREFIX)
    if chaos:
        scheme = scheme[len(_CHAOS_PREFIX) :] or "fs"
        if scheme.startswith("tier+"):
            raise RuntimeError(
                "chaos cannot wrap a whole tier URL — compose it on the "
                "remote sub-scheme instead "
                "(tier+local=...+remote=chaos+<scheme>://...), so faults "
                "hit the cloud tier the drain tolerates, not the local "
                "commit-of-record"
            )

    # Runtime-registered factories own their composition: what they
    # return is what callers get (tests register exact plugin doubles).
    # The chaos layer still composes around them — that is its point.
    from_runtime_registry = scheme in _RUNTIME_REGISTRY
    plugin = _resolve_raw_plugin(scheme, path, storage_options)

    if chaos:
        from .faults import FaultInjectionStoragePlugin, FaultPlan

        plan = FaultPlan.coerce((storage_options or {}).get("fault_plan"))
        plugin = FaultInjectionStoragePlugin(plugin, plan)

    # I/O histogram instrumentation: INSIDE retry (per-attempt samples,
    # no backoff sleeps in the latency), OUTSIDE chaos (injected
    # latency/stalls are exactly the tails the histograms exist to
    # expose). Runtime-registered plugins are returned as built — same
    # stance as the retry middleware — unless chaos composed around
    # them (the composition is then already not "as built").
    if chaos or not from_runtime_registry:
        plugin = InstrumentedStoragePlugin(plugin)

    wants_retry = chaos or (
        not from_runtime_registry
        and getattr(plugin, "wants_retry_middleware", False)
    )
    retry_enabled = (storage_options or {}).get("retry", True)
    if (
        wants_retry
        and retry_enabled
        and not getattr(plugin, "handles_own_retries", False)
    ):
        from .retry import RetryPolicy, RetryingStoragePlugin

        plugin = RetryingStoragePlugin(
            plugin, RetryPolicy.from_storage_options(storage_options)
        )

    # Auto-compose the content-addressed layer when TPUSNAP_CAS_DIR is
    # set: fs-family snapshots transparently publish payload blobs to
    # the shared store and keep refs instead of private copies. Only
    # fs-family schemes (CAS ref records + the store's mark phase need
    # a listable, local snapshot dir); internal plugin builds (the
    # store's own plugin, fsck/gc probes, the tiering drain's local
    # re-root) opt out with storage_options={'cas': False} — without
    # that guard the store plugin would CAS-compose around itself
    # forever.
    if (storage_options or {}).get("cas", True) and scheme in ("fs", "file"):
        from .cas import CASStoragePlugin, resolve_store_url

        store_url = resolve_store_url(None, storage_options)
        if store_url:
            plugin = CASStoragePlugin(
                plugin,
                base_url=f"fs://{path}",
                store_url=store_url,
                storage_options=dict(
                    storage_options or {}, cas=False
                ),
            )
    return plugin


def url_to_storage_plugin_in_event_loop(
    url_path: str,
    event_loop: asyncio.AbstractEventLoop,
    storage_options: Optional[Dict[str, Any]] = None,
) -> StoragePlugin:
    from .io_types import run_on_loop

    async def _create() -> StoragePlugin:
        return url_to_storage_plugin(url_path, storage_options)

    # run_on_loop: the loop may be a cached, reused one (Snapshot
    # resources) — an interrupt must not strand the creation task.
    return run_on_loop(event_loop, _create())
