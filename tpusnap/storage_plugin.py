"""URL scheme → StoragePlugin registry.

Counterpart of /root/reference/torchsnapshot/storage_plugin.py:18-70.
Built-ins: fs (default), s3, gs/gcs, and a generic fsspec bridge
(``fsspec+<protocol>://``). Third-party plugins register through the
``tpusnap.storage_plugins`` entry-point group.

Two middleware layers compose around the raw plugin:

- ``chaos+<scheme>://`` wraps the resolved plugin in deterministic fault
  injection (``tpusnap.faults``) — any test, example or benchmark runs
  against a misbehaving backend with only a URL change
  (``storage_options["fault_plan"]`` or TPUSNAP_FAULT_SPEC tune it).
- Retry middleware (``tpusnap.retry``) wraps built-in plugins that do
  not handle their own retries (fs, s3, fsspec — gcs retries internally
  at chunk grain, which is strictly finer). Disable per call with
  ``storage_options={"retry": False}``; tune via the ``retry_*`` keys.
  Runtime-registered and entry-point plugins are returned as built —
  their factories opt in by wrapping with ``RetryingStoragePlugin``
  themselves.
"""

import asyncio
from importlib.metadata import entry_points
from typing import Any, Dict, Optional

from .io_types import StoragePlugin

_ENTRY_POINT_GROUP = "tpusnap.storage_plugins"

_CHAOS_PREFIX = "chaos+"

# scheme → factory(path, storage_options) registered at runtime; consulted
# before entry points so tests/apps can inject plugins without packaging.
_RUNTIME_REGISTRY: Dict[str, Any] = {}


def register_storage_plugin(scheme: str, factory: Any) -> None:
    """Register ``factory(path, storage_options) -> StoragePlugin`` for a
    URL scheme at runtime (complements the ``tpusnap.storage_plugins``
    entry-point group, reference storage_plugin.py:53-65)."""
    _RUNTIME_REGISTRY[scheme.lower()] = factory


def unregister_storage_plugin(scheme: str) -> None:
    """Remove a runtime-registered scheme (no-op if absent). Runtime
    registrations shadow the built-in schemes, so scoped users (tests,
    fault injection) must clean up to avoid redirecting default paths."""
    _RUNTIME_REGISTRY.pop(scheme.lower(), None)


def _resolve_raw_plugin(
    scheme: str, path: str, storage_options: Optional[Dict[str, Any]]
) -> StoragePlugin:
    """Map a (chaos-stripped) scheme to a plugin instance, middleware-free."""
    if scheme in _RUNTIME_REGISTRY:
        return _RUNTIME_REGISTRY[scheme](path, storage_options)
    if scheme in ("", "fs", "file"):
        from .storage_plugins.fs import FSStoragePlugin

        return FSStoragePlugin(root=path, storage_options=storage_options)
    if scheme == "s3":
        from .storage_plugins.s3 import S3StoragePlugin

        return S3StoragePlugin(root=path, storage_options=storage_options)
    if scheme in ("gs", "gcs"):
        from .storage_plugins.gcs import GCSStoragePlugin

        return GCSStoragePlugin(root=path, storage_options=storage_options)
    if scheme.startswith("fsspec+"):
        from .storage_plugins.fsspec import FsspecStoragePlugin

        return FsspecStoragePlugin(
            protocol=scheme[len("fsspec+") :],
            root=path,
            storage_options=storage_options,
        )

    # Third-party plugins via entry points (reference storage_plugin.py:53-65).
    eps = entry_points()
    group = eps.select(group=_ENTRY_POINT_GROUP) if hasattr(eps, "select") else []
    for ep in group:
        if ep.name == scheme:
            factory = ep.load()
            return factory(path, storage_options)
    raise RuntimeError(f"Unsupported storage scheme: {scheme}:// ({path})")


def url_to_storage_plugin(
    url_path: str, storage_options: Optional[Dict[str, Any]] = None
) -> StoragePlugin:
    """Map ``[scheme://]path`` to a storage plugin instance, composing the
    chaos and retry middleware layers as the scheme/options direct."""
    if "://" in url_path:
        scheme, path = url_path.split("://", 1)
    else:
        scheme, path = "fs", url_path
    scheme = scheme.lower()

    chaos = scheme.startswith(_CHAOS_PREFIX)
    if chaos:
        scheme = scheme[len(_CHAOS_PREFIX) :] or "fs"

    # Runtime-registered factories own their composition: what they
    # return is what callers get (tests register exact plugin doubles).
    # The chaos layer still composes around them — that is its point.
    from_runtime_registry = scheme in _RUNTIME_REGISTRY
    plugin = _resolve_raw_plugin(scheme, path, storage_options)

    if chaos:
        from .faults import FaultInjectionStoragePlugin, FaultPlan

        plan = FaultPlan.coerce((storage_options or {}).get("fault_plan"))
        plugin = FaultInjectionStoragePlugin(plugin, plan)

    wants_retry = chaos or (
        not from_runtime_registry
        and getattr(plugin, "wants_retry_middleware", False)
    )
    retry_enabled = (storage_options or {}).get("retry", True)
    if (
        wants_retry
        and retry_enabled
        and not getattr(plugin, "handles_own_retries", False)
    ):
        from .retry import RetryPolicy, RetryingStoragePlugin

        plugin = RetryingStoragePlugin(
            plugin, RetryPolicy.from_storage_options(storage_options)
        )
    return plugin


def url_to_storage_plugin_in_event_loop(
    url_path: str,
    event_loop: asyncio.AbstractEventLoop,
    storage_options: Optional[Dict[str, Any]] = None,
) -> StoragePlugin:
    from .io_types import run_on_loop

    async def _create() -> StoragePlugin:
        return url_to_storage_plugin(url_path, storage_options)

    # run_on_loop: the loop may be a cached, reused one (Snapshot
    # resources) — an interrupt must not strand the creation task.
    return run_on_loop(event_loop, _create())
