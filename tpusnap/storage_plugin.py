"""URL scheme → StoragePlugin registry.

Counterpart of /root/reference/torchsnapshot/storage_plugin.py:18-70.
Built-ins: fs (default), s3, gs/gcs, and a generic fsspec bridge
(``fsspec+<protocol>://``). Third-party plugins register through the
``tpusnap.storage_plugins`` entry-point group.
"""

import asyncio
from importlib.metadata import entry_points
from typing import Any, Dict, Optional

from .io_types import StoragePlugin

_ENTRY_POINT_GROUP = "tpusnap.storage_plugins"

# scheme → factory(path, storage_options) registered at runtime; consulted
# before entry points so tests/apps can inject plugins without packaging.
_RUNTIME_REGISTRY: Dict[str, Any] = {}


def register_storage_plugin(scheme: str, factory: Any) -> None:
    """Register ``factory(path, storage_options) -> StoragePlugin`` for a
    URL scheme at runtime (complements the ``tpusnap.storage_plugins``
    entry-point group, reference storage_plugin.py:53-65)."""
    _RUNTIME_REGISTRY[scheme.lower()] = factory


def unregister_storage_plugin(scheme: str) -> None:
    """Remove a runtime-registered scheme (no-op if absent). Runtime
    registrations shadow the built-in schemes, so scoped users (tests,
    fault injection) must clean up to avoid redirecting default paths."""
    _RUNTIME_REGISTRY.pop(scheme.lower(), None)


def url_to_storage_plugin(
    url_path: str, storage_options: Optional[Dict[str, Any]] = None
) -> StoragePlugin:
    """Map ``[scheme://]path`` to a storage plugin instance."""
    if "://" in url_path:
        scheme, path = url_path.split("://", 1)
    else:
        scheme, path = "fs", url_path
    scheme = scheme.lower()

    if scheme in _RUNTIME_REGISTRY:
        return _RUNTIME_REGISTRY[scheme](path, storage_options)
    if scheme in ("", "fs", "file"):
        from .storage_plugins.fs import FSStoragePlugin

        return FSStoragePlugin(root=path, storage_options=storage_options)
    if scheme == "s3":
        from .storage_plugins.s3 import S3StoragePlugin

        return S3StoragePlugin(root=path, storage_options=storage_options)
    if scheme in ("gs", "gcs"):
        from .storage_plugins.gcs import GCSStoragePlugin

        return GCSStoragePlugin(root=path, storage_options=storage_options)
    if scheme.startswith("fsspec+"):
        from .storage_plugins.fsspec import FsspecStoragePlugin

        return FsspecStoragePlugin(
            protocol=scheme[len("fsspec+") :],
            root=path,
            storage_options=storage_options,
        )

    # Third-party plugins via entry points (reference storage_plugin.py:53-65).
    eps = entry_points()
    group = eps.select(group=_ENTRY_POINT_GROUP) if hasattr(eps, "select") else []
    for ep in group:
        if ep.name == scheme:
            factory = ep.load()
            return factory(path, storage_options)
    raise RuntimeError(f"Unsupported storage scheme: {scheme}:// ({url_path})")


def url_to_storage_plugin_in_event_loop(
    url_path: str,
    event_loop: asyncio.AbstractEventLoop,
    storage_options: Optional[Dict[str, Any]] = None,
) -> StoragePlugin:
    from .io_types import run_on_loop

    async def _create() -> StoragePlugin:
        return url_to_storage_plugin(url_path, storage_options)

    # run_on_loop: the loop may be a cached, reused one (Snapshot
    # resources) — an interrupt must not strand the creation task.
    return run_on_loop(event_loop, _create())
