"""Request batcher: coalesce small writes into slabs; merge adjacent
byte-ranged reads into spanning reads.

Counterpart of /root/reference/torchsnapshot/batcher.py:48-474. Small
(< slab threshold) buffer-protocol array writes are packed into
uuid-named slab objects under ``batched/``; each member's TensorEntry is
rewritten in place to point at ``(slab_location, byte_range)``. Cloud
object stores charge per request and throttle request rates, so slab
packing is what makes thousands-of-small-parameters models fast on
S3/GCS. On read, byte-ranged requests against the same location are
merged into one spanning read and sliced back out.
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from concurrent.futures import Executor
from typing import Dict, List, Optional, Tuple

import numpy as np

from .io_types import (
    BufferConsumer,
    BufferStager,
    BufferType,
    ReadReq,
    WriteReq,
)
from .io_preparers.array import ArrayBufferStager
from .knobs import (
    get_slab_size_threshold_bytes,
    is_batching_disabled,
    is_device_batching_disabled,
)
from .manifest import ChunkedTensorEntry, Entry, TensorEntry

logger = logging.getLogger(__name__)

# Bounds XLA compile time of the per-composition device pack program.
_MAX_DEVICE_SLAB_MEMBERS = 256


def _batchable_tensor_entries(entries: List[Entry]) -> Dict[str, TensorEntry]:
    """location → TensorEntry for every dense tensor blob (incl. chunks)."""
    out: Dict[str, TensorEntry] = {}
    for entry in entries:
        if isinstance(entry, TensorEntry):
            out[entry.location] = entry
        elif isinstance(entry, ChunkedTensorEntry):
            for chunk in entry.chunks:
                out[chunk.tensor.location] = chunk.tensor
    return out


class BatchedBufferStager(BufferStager):
    """Stages all members concurrently into one contiguous bytearray
    (reference BatchedBufferStager, batcher.py:48-98).

    Members carry their own incremental-dedup state: a member whose
    stager reports SKIP_WRITE (bytes match the base snapshot — its entry
    already re-pointed at the base slab's byte range) is EXCLUDED from
    the new slab, and the remaining members are compacted (entries'
    byte ranges reassigned). A fully-deduped slab skips its write
    entirely. Small states therefore stop rewriting 100% on every
    incremental take."""

    def __init__(self, members: List[Tuple[int, int, BufferStager]]) -> None:
        # members: [(offset, nbytes, stager)]
        self.members = members
        self.total = sum(n for _, n, _ in members)

    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        from . import _native
        from .io_types import SKIP_WRITE

        # Aligned so the O_DIRECT writer pwrites straight from the slab.
        # Full-size upfront: members stream into their original offsets
        # as they land (each member's buffer is released immediately —
        # peak memory stays one slab + one member, matching
        # get_staging_cost_bytes); dedup'd members leave holes that one
        # in-place compaction pass closes at the end.
        slab = _native.aligned_empty(self.total)
        skipped = [False] * len(self.members)

        async def fill(i: int, offset: int, nbytes: int, stager: BufferStager) -> None:
            buf = await stager.stage_buffer(executor)
            if buf is SKIP_WRITE:
                skipped[i] = True  # member dedup'd against the base
                return
            mv = memoryview(buf).cast("B")
            if mv.nbytes != nbytes:
                raise RuntimeError(
                    f"Batched member staged {mv.nbytes} bytes, expected {nbytes}"
                )
            slab[offset : offset + nbytes] = np.frombuffer(mv, dtype=np.uint8)
            del mv
            if getattr(stager, "cow_pending", False):
                # COW members return LIVE bytes; the slab copy above is
                # their effective clone. The write pipeline only checks
                # cow_pending on the top-level (slab) stager, so verify
                # HERE — against the private slab copy, immediately —
                # that the bytes still match the checksum recorded from
                # the live array: a mutation between the hash pass and
                # this copy fails the take loudly instead of committing
                # a blob whose checksum mismatches its bytes.
                stager.verify_cow_after_write(slab[offset : offset + nbytes])
                stager.cow_pending = False
            from ._staging_pool import release

            release(buf)  # async member clones reuse warm pages next take

        await asyncio.gather(
            *(fill(i, o, n, s) for i, (o, n, s) in enumerate(self.members))
        )
        if not any(skipped):
            return slab
        # Compact in place around the dedup'd members (memmove — source
        # and destination overlap when moving left; numpy slice
        # assignment does not guarantee overlap safety) and return a
        # view of the kept prefix — no second allocation.
        import ctypes

        new_offset = 0
        for i, (offset, nbytes, stager) in enumerate(self.members):
            if skipped[i]:
                continue
            if new_offset != offset:
                ctypes.memmove(
                    slab.ctypes.data + new_offset,
                    slab.ctypes.data + offset,
                    nbytes,
                )
            entry = getattr(stager, "entry", None)
            if entry is not None:
                entry.byte_range = [new_offset, new_offset + nbytes]
            new_offset += nbytes
        if new_offset == 0:
            return SKIP_WRITE
        return slab[:new_offset]

    def get_staging_cost_bytes(self) -> int:
        # The slab plus transiently one member's own staging cost; the
        # members' buffers are views/DMA targets released as they land.
        return self.total + max((s.get_staging_cost_bytes() for _, _, s in self.members), default=0)

    def get_planned_bytes(self) -> int:
        # The slab payload itself — members stream through transient
        # buffers that never count toward written bytes.
        return self.total


class DeviceBatchedBufferStager(BufferStager):
    """Packs same-device array members into one ``uint8`` buffer *on
    device* (XLA bitcast + fused concatenation), then performs a single
    device→host DMA for the whole slab.

    TPU-native counterpart of the reference's GPUBatchedBufferStager
    (batcher.py:101-159), which packs CUDA tensors into a byte tensor
    and issues one DtoH copy. One large DMA amortizes per-transfer
    dispatch overhead that thousands of small-parameter copies would
    otherwise pay. Falls back to the host-side ``BatchedBufferStager``
    on any failure (the reference falls back on CUDA OOM).

    The packed slab is a fresh XLA computation result, so its host copy
    can never alias live training state — async snapshots need no
    defensive clone here.

    Cost model: the pack program is jit-compiled once per slab
    *composition* (shapes/dtypes) and cached for the process — free for
    the steady-state checkpoint loop, a one-time cost on the first take.
    Slabs are capped at ``_MAX_DEVICE_SLAB_MEMBERS`` members to bound
    that compile time. ``TPUSNAP_DISABLE_DEVICE_BATCHING=1`` opts out
    (e.g. when device→host bandwidth, not per-transfer dispatch, is the
    bottleneck).
    """

    def __init__(self, members: List[Tuple[int, int, ArrayBufferStager]]) -> None:
        self.members = members
        self.total = sum(n for _, n, _ in members)

    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        loop = asyncio.get_running_loop()
        try:
            if executor is not None:
                return await loop.run_in_executor(executor, self._stage_blocking)
            return self._stage_blocking()
        except Exception as e:
            logger.warning(
                "device slab packing failed (%s); falling back to host packing", e
            )
            return await BatchedBufferStager(list(self.members)).stage_buffer(
                executor
            )

    def _stage_blocking(self) -> BufferType:
        import numpy as np

        from .knobs import is_checksum_disabled

        packed = _pack_on_device(tuple(s.arr for _, _, s in self.members))
        host = np.asarray(packed)  # the single DtoH DMA
        if host.nbytes != self.total:
            raise RuntimeError(
                f"device-packed slab is {host.nbytes} bytes, expected {self.total}"
            )
        if is_checksum_disabled():
            return host
        # The members' own stagers are bypassed by the device-side pack,
        # so record their checksums/dedup hashes from the slab slices
        # here — the same _record_checksums the host path runs, so both
        # paths produce identical manifests. Members matching their base
        # entry (incremental dedup) are dropped and the slab compacted,
        # exactly like BatchedBufferStager. (The packed slab is a fresh
        # XLA result, so member bytes are stable — no clone needed.)
        from .io_preparers.array import _record_checksums, dedup_entries_match
        from .io_types import SKIP_WRITE

        keep: List[Tuple[int, int]] = []  # (old_offset, nbytes)
        keep_stagers: List[ArrayBufferStager] = []
        for offset, nbytes, stager in self.members:
            if stager.entry is None:
                keep.append((offset, nbytes))
                keep_stagers.append(stager)
                continue
            mv = memoryview(host[offset : offset + nbytes])
            dedup = getattr(stager, "dedup_entry", None)
            _record_checksums(
                stager.entry, mv, getattr(stager, "record_dedup_hashes", False)
            )
            if dedup is not None and dedup_entries_match(stager.entry, dedup):
                stager.entry.location = dedup.location
                stager.entry.byte_range = (
                    list(dedup.byte_range)
                    if dedup.byte_range is not None
                    else None
                )
                continue
            keep.append((offset, nbytes))
            keep_stagers.append(stager)
        if not keep:
            return SKIP_WRITE
        if len(keep) == len(self.members):
            return host
        from . import _native

        # Aligned so the O_DIRECT writer pwrites straight from it (the
        # host-path slab is allocated the same way).
        out = _native.aligned_empty(sum(n for _, n in keep))
        new_offset = 0
        for (old_offset, nbytes), stager in zip(keep, keep_stagers):
            out[new_offset : new_offset + nbytes] = host[
                old_offset : old_offset + nbytes
            ]
            if stager.entry is not None:
                stager.entry.byte_range = [new_offset, new_offset + nbytes]
            new_offset += nbytes
        return out

    def get_staging_cost_bytes(self) -> int:
        # Partial dedup holds the DMA'd slab AND the compacted copy at
        # once (the DMA result may alias XLA-owned memory, so unlike the
        # host path it cannot compact in place): budget 2x whenever a
        # member might dedup.
        if any(
            getattr(s, "dedup_entry", None) is not None
            for _, _, s in self.members
        ):
            return 2 * self.total
        return self.total

    def get_planned_bytes(self) -> int:
        # The slab payload — never the 2x dedup-compaction budget.
        return self.total


def _pack_on_device(arrs):
    """Bitcast every member to a flat u8 view and concatenate — one fused
    XLA program, jit-cached per slab composition."""
    return _ensure_pack_jit()(arrs)


def _pack_members(arrs):
    import jax
    import jax.numpy as jnp

    flat = []
    for a in arrs:
        if a.dtype == jnp.bool_:
            f = a.astype(jnp.uint8)  # bool is 1 byte, values 0/1
        else:
            f = jax.lax.bitcast_convert_type(a, jnp.uint8)
        flat.append(f.reshape(-1))
    return jnp.concatenate(flat) if len(flat) > 1 else flat[0]


_pack_jit = None


def _ensure_pack_jit():
    global _pack_jit
    if _pack_jit is None:
        import jax

        _pack_jit = jax.jit(_pack_members)
    return _pack_jit


def _device_group_key(stager: BufferStager) -> Optional[str]:
    """Same-device jax.Array members eligible for device packing share a
    key; ``None`` → host packing."""
    if is_device_batching_disabled() or not isinstance(stager, ArrayBufferStager):
        return None
    if stager.array_prepare_func is not None:
        # The device pack bitcasts the ORIGINAL arrays; a save-time
        # transform must run through the member stagers (host packing
        # calls them; the device path would silently skip it).
        return None
    import jax
    import numpy as np

    arr = stager.arr
    if not isinstance(arr, jax.Array):
        return None
    try:
        from .host_offload import is_offloaded_to_host

        if is_offloaded_to_host(arr):
            # Genuinely offloaded (host kind distinct from the device's
            # default memory): packing on device would round-trip the
            # bytes through a DMA for nothing. Default-placed arrays on
            # backends whose default memory IS a host kind (CPU) still
            # device-pack — there the pack is a fused concat, no DMA.
            return None
        devices = arr.devices()
    except Exception:
        return None
    if len(devices) != 1:
        return None
    if np.dtype(arr.dtype).kind == "c":
        return None  # complex: no u8 bitcast path
    return str(next(iter(devices)))


def batch_write_requests(
    entries: List[Entry], write_reqs: List[WriteReq]
) -> Tuple[List[Entry], List[WriteReq]]:
    """Pack small array writes into slabs, rewriting entries in place
    (reference batch_write_requests, batcher.py:201-352)."""
    threshold = get_slab_size_threshold_bytes()
    if is_batching_disabled():
        return entries, write_reqs

    entry_by_location = _batchable_tensor_entries(entries)
    candidates: List[WriteReq] = []
    passthrough: List[WriteReq] = []
    for wr in write_reqs:
        stager = wr.buffer_stager
        if (
            isinstance(stager, ArrayBufferStager)
            and wr.path in entry_by_location
            and stager.get_staging_cost_bytes() < threshold
        ):
            candidates.append(wr)
        else:
            passthrough.append(wr)
    if len(candidates) < 2:
        return entries, write_reqs

    batched_reqs: List[WriteReq] = []
    slab_members: List[Tuple[int, int, BufferStager]] = []
    slab_entries: List[TensorEntry] = []
    slab_device: Optional[str] = None
    offset = 0

    def flush() -> None:
        nonlocal offset, slab_members, slab_entries
        if not slab_members:
            return
        if len(slab_members) == 1:
            # A slab of one is pointless; leave the request as-is.
            passthrough.append(
                WriteReq(path=slab_entries[0].location, buffer_stager=slab_members[0][2])
            )
        else:
            location = f"batched/{uuid.uuid4().hex}"
            for (member_offset, nbytes, stager), tensor_entry in zip(
                slab_members, slab_entries
            ):
                tensor_entry.location = location
                tensor_entry.byte_range = [member_offset, member_offset + nbytes]
                # Members keep their dedup state: one that matches its
                # base entry skips (its entry re-pointed at the base
                # slab's byte range) and the stager compacts the slab
                # around it at stage time.
            stager_cls = (
                DeviceBatchedBufferStager
                if slab_device is not None
                else BatchedBufferStager
            )
            batched_reqs.append(
                WriteReq(
                    path=location,
                    buffer_stager=stager_cls(list(slab_members)),
                )
            )
        offset = 0
        slab_members = []
        slab_entries = []

    from .serialization import tensor_nbytes

    # Stable-sort by device group so same-device members land in the
    # same slab and take the single-DMA device packing path.
    keyed = [(_device_group_key(wr.buffer_stager), wr) for wr in candidates]
    keyed.sort(key=lambda kv: kv[0] or "")
    for device_key, wr in keyed:
        tensor_entry = entry_by_location[wr.path]
        nbytes = tensor_nbytes(tensor_entry.dtype, tensor_entry.shape)
        if slab_members and (
            offset + nbytes > threshold
            or device_key != slab_device
            or (device_key is not None and len(slab_members) >= _MAX_DEVICE_SLAB_MEMBERS)
        ):
            flush()
        slab_device = device_key
        slab_members.append((offset, nbytes, wr.buffer_stager))
        slab_entries.append(tensor_entry)
        offset += nbytes
    flush()

    return entries, passthrough + batched_reqs


class _SpanningConsumer(BufferConsumer):
    """Feeds slices of one spanning read to the member consumers
    (reference read-side merge, batcher.py:384-474)."""

    def __init__(
        self, span_start: int, members: List[Tuple[Tuple[int, int], BufferConsumer]]
    ) -> None:
        self.span_start = span_start
        self.members = members

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        mv = memoryview(buf).cast("B")
        for (start, end), consumer in self.members:
            await consumer.consume_buffer(
                mv[start - self.span_start : end - self.span_start], executor
            )

    def get_consuming_cost_bytes(self) -> int:
        return sum(c.get_consuming_cost_bytes() for _, c in self.members)


def batch_read_requests(read_reqs: List[ReadReq]) -> List[ReadReq]:
    """Merge byte-ranged reads per location into one spanning read when the
    span is dense enough that one request beats many."""
    by_location: Dict[str, List[ReadReq]] = {}
    passthrough: List[ReadReq] = []
    for rr in read_reqs:
        if rr.byte_range is not None:
            by_location.setdefault(rr.path, []).append(rr)
        else:
            passthrough.append(rr)

    out = list(passthrough)
    for location, reqs in by_location.items():
        if len(reqs) == 1:
            out.extend(reqs)
            continue
        reqs.sort(key=lambda r: r.byte_range[0])
        span_start = reqs[0].byte_range[0]
        span_end = max(r.byte_range[1] for r in reqs)
        total = sum(r.byte_range[1] - r.byte_range[0] for r in reqs)
        if total < (span_end - span_start) * 0.5:
            # Sparse: spanning read would over-fetch badly; keep individual.
            out.extend(reqs)
            continue
        out.append(
            ReadReq(
                path=location,
                byte_range=(span_start, span_end),
                buffer_consumer=_SpanningConsumer(
                    span_start,
                    [(tuple(r.byte_range), r.buffer_consumer) for r in reqs],
                ),
                # Per-member attribution survives the merge: the access
                # ledger records each member's own leaf and range, not
                # the opaque spanning read.
                access_parts=[
                    (r.logical_path, r.byte_range[0], r.byte_range[1])
                    for r in reqs
                    if r.logical_path
                ]
                or None,
            )
        )
    return out
