"""Request batcher: coalesce small writes into slabs; merge adjacent
byte-ranged reads into spanning reads.

Counterpart of /root/reference/torchsnapshot/batcher.py:48-474. Small
(< slab threshold) buffer-protocol array writes are packed into
uuid-named slab objects under ``batched/``; each member's TensorEntry is
rewritten in place to point at ``(slab_location, byte_range)``. Cloud
object stores charge per request and throttle request rates, so slab
packing is what makes thousands-of-small-parameters models fast on
S3/GCS. On read, byte-ranged requests against the same location are
merged into one spanning read and sliced back out.
"""

from __future__ import annotations

import asyncio
import uuid
from concurrent.futures import Executor
from typing import Dict, List, Optional, Tuple

from .io_types import (
    BufferConsumer,
    BufferStager,
    BufferType,
    ReadReq,
    WriteReq,
)
from .io_preparers.array import ArrayBufferStager
from .knobs import get_slab_size_threshold_bytes, is_batching_disabled
from .manifest import ChunkedTensorEntry, Entry, TensorEntry


def _batchable_tensor_entries(entries: List[Entry]) -> Dict[str, TensorEntry]:
    """location → TensorEntry for every dense tensor blob (incl. chunks)."""
    out: Dict[str, TensorEntry] = {}
    for entry in entries:
        if isinstance(entry, TensorEntry):
            out[entry.location] = entry
        elif isinstance(entry, ChunkedTensorEntry):
            for chunk in entry.chunks:
                out[chunk.tensor.location] = chunk.tensor
    return out


class BatchedBufferStager(BufferStager):
    """Stages all members concurrently into one contiguous bytearray
    (reference BatchedBufferStager, batcher.py:48-98)."""

    def __init__(self, members: List[Tuple[int, int, BufferStager]]) -> None:
        # members: [(offset, nbytes, stager)]
        self.members = members
        self.total = sum(n for _, n, _ in members)

    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        slab = bytearray(self.total)

        async def fill(offset: int, nbytes: int, stager: BufferStager) -> None:
            buf = await stager.stage_buffer(executor)
            mv = memoryview(buf).cast("B")
            if mv.nbytes != nbytes:
                raise RuntimeError(
                    f"Batched member staged {mv.nbytes} bytes, expected {nbytes}"
                )
            slab[offset : offset + nbytes] = mv

        await asyncio.gather(*(fill(o, n, s) for o, n, s in self.members))
        return slab

    def get_staging_cost_bytes(self) -> int:
        # The slab plus transiently one member's own staging cost; the
        # members' buffers are views/DMA targets released as they land.
        return self.total + max((s.get_staging_cost_bytes() for _, _, s in self.members), default=0)


def batch_write_requests(
    entries: List[Entry], write_reqs: List[WriteReq]
) -> Tuple[List[Entry], List[WriteReq]]:
    """Pack small array writes into slabs, rewriting entries in place
    (reference batch_write_requests, batcher.py:201-352)."""
    threshold = get_slab_size_threshold_bytes()
    if is_batching_disabled():
        return entries, write_reqs

    entry_by_location = _batchable_tensor_entries(entries)
    candidates: List[WriteReq] = []
    passthrough: List[WriteReq] = []
    for wr in write_reqs:
        stager = wr.buffer_stager
        if (
            isinstance(stager, ArrayBufferStager)
            and wr.path in entry_by_location
            and stager.get_staging_cost_bytes() < threshold
        ):
            candidates.append(wr)
        else:
            passthrough.append(wr)
    if len(candidates) < 2:
        return entries, write_reqs

    batched_reqs: List[WriteReq] = []
    slab_members: List[Tuple[int, int, BufferStager]] = []
    slab_entries: List[TensorEntry] = []
    offset = 0

    def flush() -> None:
        nonlocal offset, slab_members, slab_entries
        if not slab_members:
            return
        if len(slab_members) == 1:
            # A slab of one is pointless; leave the request as-is.
            passthrough.append(
                WriteReq(path=slab_entries[0].location, buffer_stager=slab_members[0][2])
            )
        else:
            location = f"batched/{uuid.uuid4().hex}"
            for (member_offset, nbytes, _), tensor_entry in zip(
                slab_members, slab_entries
            ):
                tensor_entry.location = location
                tensor_entry.byte_range = [member_offset, member_offset + nbytes]
            batched_reqs.append(
                WriteReq(
                    path=location,
                    buffer_stager=BatchedBufferStager(list(slab_members)),
                )
            )
        offset = 0
        slab_members = []
        slab_entries = []

    from .serialization import tensor_nbytes

    for wr in candidates:
        tensor_entry = entry_by_location[wr.path]
        nbytes = tensor_nbytes(tensor_entry.dtype, tensor_entry.shape)
        if offset + nbytes > threshold and slab_members:
            flush()
        slab_members.append((offset, nbytes, wr.buffer_stager))
        slab_entries.append(tensor_entry)
        offset += nbytes
    flush()

    return entries, passthrough + batched_reqs


class _SpanningConsumer(BufferConsumer):
    """Feeds slices of one spanning read to the member consumers
    (reference read-side merge, batcher.py:384-474)."""

    def __init__(
        self, span_start: int, members: List[Tuple[Tuple[int, int], BufferConsumer]]
    ) -> None:
        self.span_start = span_start
        self.members = members

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        mv = memoryview(buf).cast("B")
        for (start, end), consumer in self.members:
            await consumer.consume_buffer(
                mv[start - self.span_start : end - self.span_start], executor
            )

    def get_consuming_cost_bytes(self) -> int:
        return sum(c.get_consuming_cost_bytes() for _, c in self.members)


def batch_read_requests(read_reqs: List[ReadReq]) -> List[ReadReq]:
    """Merge byte-ranged reads per location into one spanning read when the
    span is dense enough that one request beats many."""
    by_location: Dict[str, List[ReadReq]] = {}
    passthrough: List[ReadReq] = []
    for rr in read_reqs:
        if rr.byte_range is not None:
            by_location.setdefault(rr.path, []).append(rr)
        else:
            passthrough.append(rr)

    out = list(passthrough)
    for location, reqs in by_location.items():
        if len(reqs) == 1:
            out.extend(reqs)
            continue
        reqs.sort(key=lambda r: r.byte_range[0])
        span_start = reqs[0].byte_range[0]
        span_end = max(r.byte_range[1] for r in reqs)
        total = sum(r.byte_range[1] - r.byte_range[0] for r in reqs)
        if total < (span_end - span_start) * 0.5:
            # Sparse: spanning read would over-fetch badly; keep individual.
            out.extend(reqs)
            continue
        out.append(
            ReadReq(
                path=location,
                byte_range=(span_start, span_end),
                buffer_consumer=_SpanningConsumer(
                    span_start,
                    [(tuple(r.byte_range), r.buffer_consumer) for r in reqs],
                ),
            )
        )
    return out
