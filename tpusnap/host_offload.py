"""Host-offloaded arrays — the TPU analog of CUDA UVM embedding tables.

Counterpart of /root/reference/torchsnapshot/uvm_tensor.py:24-39, which
binds fbgemm_gpu's CUDA unified-virtual-memory ops so huge embedding
tables live in host RAM while remaining addressable from the GPU. On TPU
the same capability is XLA memory kinds: ``pinned_host`` /
``unpinned_host`` arrays live in host memory, are directly usable from
jitted computations (XLA inserts the DMAs), and — exactly like the
reference's ``_uvm_to_cpu`` staging shortcut
(io_preparers/tensor.py:257-259) — serialize without a device→host copy.

All helpers degrade gracefully when a backend lacks host memory kinds
(mirroring the reference's no-op fallbacks when fbgemm is absent).
"""

from __future__ import annotations

import logging
from typing import Optional

import jax

logger = logging.getLogger(__name__)

_HOST_MEMORY_KINDS = frozenset({"pinned_host", "unpinned_host"})


def supports_host_offload(device: Optional[jax.Device] = None) -> bool:
    device = device or jax.devices()[0]
    try:
        kinds = {m.kind for m in device.addressable_memories()}
    except Exception:
        return False
    return bool(kinds & _HOST_MEMORY_KINDS)


def is_host_resident(arr: object) -> bool:
    """True when the array's buffers live in host memory (UVM analog of
    the reference's ``_is_uvm_tensor``)."""
    if not isinstance(arr, jax.Array):
        return True  # numpy et al. are host memory by definition
    try:
        return arr.sharding.memory_kind in _HOST_MEMORY_KINDS
    except Exception:
        return False


def is_offloaded_to_host(arr: object) -> bool:
    """True when the array lives in a host memory kind DISTINCT from its
    device's default memory — i.e. genuinely offloaded. On CPU backends
    whose only/default memory kind is a host kind, default-placed arrays
    are host *resident* (``is_host_resident``) but not *offloaded*; the
    distinction matters to callers deciding whether touching the device
    would be a detour (the batcher's device-pack routing)."""
    if not isinstance(arr, jax.Array):
        return False
    try:
        kind = arr.sharding.memory_kind
        if kind not in _HOST_MEMORY_KINDS:
            return False
        default = next(iter(arr.devices())).default_memory().kind
        return kind != default
    except Exception:
        return False


def to_host_offload(arr: jax.Array, memory_kind: str = "pinned_host") -> jax.Array:
    """Move an array to host memory, preserving its sharding layout
    (reference ``new_managed_tensor``: allocate in UVM). If the device
    does not expose the requested host kind, degrade to one it does
    (CPU backends typically offer only ``unpinned_host``)."""
    if memory_kind not in _HOST_MEMORY_KINDS:
        raise ValueError(f"not a host memory kind: {memory_kind!r}")
    try:
        available = {
            m.kind for m in next(iter(arr.devices())).addressable_memories()
        } & _HOST_MEMORY_KINDS
    except Exception:
        available = set()
    if available and memory_kind not in available:
        fallback = (
            "pinned_host" if "pinned_host" in available else sorted(available)[0]
        )
        logger.debug(
            "Host memory kind %r unavailable on this backend; using %r",
            memory_kind,
            fallback,
        )
        memory_kind = fallback
    sharding = arr.sharding.with_memory_kind(memory_kind)
    from . import telemetry

    # Span covers the dispatch only (device_put is async); the staging
    # path's np.asarray span is where completed-DMA time shows up.
    with telemetry.span(
        "host_offload.dtoh", bytes=arr.nbytes, memory_kind=memory_kind
    ):
        return jax.device_put(arr, sharding)


def to_device(arr: jax.Array) -> jax.Array:
    """Move a host-offloaded array back to the device's DEFAULT memory
    ("device" HBM on TPU/GPU; on CPU backends whose only memory kind is
    unpinned_host, the default IS host memory and this is a no-op —
    hardcoding "device" raises there)."""
    try:
        default_kind = next(iter(arr.devices())).default_memory().kind
    except Exception:
        default_kind = "device"
    sharding = arr.sharding.with_memory_kind(default_kind)
    return jax.device_put(arr, sharding)
