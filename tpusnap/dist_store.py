"""KV stores + LinearBarrier for thread-safe coordination.

Counterpart of /root/reference/torchsnapshot/dist_store.py. The async
snapshot commit runs on a background thread where collectives are
forbidden (reference snapshot.py:902), so it synchronizes through a KV
store instead:

- ``CoordinationKVStore`` — the jax.distributed coordination-service
  client (the TPU-native replacement for c10d TCPStore).
- ``FileKVStore`` — a shared-filesystem store for single-host
  multi-process tests (and a fallback when no coordination service is
  up but ranks share a filesystem).
- ``LinearBarrier`` — the reference's two-phase (arrive/depart) barrier
  with error propagation (dist_store.py:91-196): any rank can
  ``report_error``; every waiter then re-raises it, which is how a
  failed async snapshot aborts the metadata commit on all ranks.
"""

from __future__ import annotations

import abc
import logging
import os
import pickle
import tempfile
import time
from typing import Callable, List, Optional

logger = logging.getLogger(__name__)

_POLL_INTERVAL_SEC = 0.05


def _default_timeout_sec() -> float:
    # Historically a 600.0 literal; now the TPUSNAP_BARRIER_TIMEOUT_S
    # knob, resolved per-wait so test overrides apply without reimports.
    from .knobs import get_barrier_timeout_s

    return get_barrier_timeout_s()


class KVStore(abc.ABC):
    @abc.abstractmethod
    def set(self, key: str, value: bytes) -> None: ...

    @abc.abstractmethod
    def try_get(self, key: str) -> Optional[bytes]: ...

    def try_get_dir(self, prefix: str) -> Optional[dict]:
        """All (key, value) pairs under ``prefix`` in ONE call when the
        backend supports it, else None (caller falls back to per-key
        gets). Keys in the result are relative to the store, like the
        keys passed to ``set``."""
        return None

    def delete_prefix(self, prefix: str) -> None:
        """Best-effort deletion of every key under ``prefix``."""

    def get(self, key: str, timeout_sec: Optional[float] = None) -> bytes:
        if timeout_sec is None:
            timeout_sec = _default_timeout_sec()
        deadline = time.monotonic() + timeout_sec
        while True:
            value = self.try_get(key)
            if value is not None:
                return value
            if time.monotonic() > deadline:
                raise TimeoutError(f"Timed out waiting for key {key!r}")
            time.sleep(_POLL_INTERVAL_SEC)


def _client_try_get(client, full_key: str, probe_timeout_ms: int = 50):
    """Non-blocking-ish single-key get against the coordination client.

    Newer JAX exposes ``key_value_try_get``; older clients (including
    jaxlib 0.4.x) only have ``blocking_key_value_get``, which raises on
    timeout — probe with a short deadline there. Returns None when the
    key is absent (or the service errored)."""
    getter = getattr(client, "key_value_try_get", None)
    try:
        if getter is not None:
            return getter(full_key)
        return client.blocking_key_value_get(full_key, probe_timeout_ms)
    except Exception:
        return None


class CoordinationKVStore(KVStore):
    """Backed by the jax.distributed coordination service client."""

    def __init__(self, prefix: str = "tpusnap_store") -> None:
        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            raise RuntimeError("jax.distributed is not initialized")
        self._client = client
        self._prefix = prefix

    def _k(self, key: str) -> str:
        return f"{self._prefix}/{key}"

    def set(self, key: str, value: bytes) -> None:
        import base64

        payload = base64.b64encode(value).decode()
        try:
            # Overwrite semantics: lease/heartbeat republishes and
            # elastic-stream membership transitions rewrite the SAME
            # key — the coordination service's default insert-only
            # key_value_set rejects the second write (ALREADY_EXISTS).
            self._client.key_value_set(
                self._k(key), payload, allow_overwrite=True
            )
        except TypeError:
            # Older clients lack the kwarg: emulate with delete+insert
            # (non-atomic, but every overwriting caller here tolerates
            # a reader seeing the brief gap as "absent").
            self._client.key_value_delete(self._k(key))
            self._client.key_value_set(self._k(key), payload)

    def try_get(self, key: str) -> Optional[bytes]:
        import base64

        raw = _client_try_get(self._client, self._k(key))
        if raw is None:
            return None
        if isinstance(raw, bytes):
            raw = raw.decode()
        return base64.b64decode(raw)

    def try_get_dir(self, prefix: str) -> Optional[dict]:
        import base64

        try:
            pairs = self._client.key_value_dir_get(self._k(prefix))
        except Exception:
            return None
        out = {}
        want = self._prefix + "/"
        for k, v in pairs:
            if isinstance(v, bytes):
                v = v.decode()
            # Defensive stripping: the coordination service is only
            # OBSERVED to return keys exactly as set; verify the prefix
            # instead of blind slicing (tolerating a leading slash), and
            # report "no dir support" on any unexpected shape so callers
            # take their per-key fallback rather than consuming
            # silently corrupted relative keys.
            rel_key = k.lstrip("/")
            if not rel_key.startswith(want):
                return None
            out[rel_key[len(want) :]] = base64.b64decode(v)
        return out

    def delete_prefix(self, prefix: str) -> None:
        try:
            self._client.key_value_delete(self._k(prefix))
        except Exception:
            # Best-effort cleanup; a leaked key costs service memory only.
            logger.debug(
                "KV delete_prefix(%r) failed", prefix, exc_info=True
            )


class FileKVStore(KVStore):
    """Directory-backed store; atomic via rename. Works wherever ranks
    share a filesystem (incl. the snapshot destination itself)."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key.replace("/", "%2F"))

    def set(self, key: str, value: bytes) -> None:
        path = self._path(key)
        fd, tmp = tempfile.mkstemp(dir=self.root)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(value)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def try_get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def try_get_dir(self, prefix: str) -> Optional[dict]:
        enc = prefix.replace("/", "%2F")
        out = {}
        for name in os.listdir(self.root):
            if name.startswith(enc):
                with open(os.path.join(self.root, name), "rb") as f:
                    out[name.replace("%2F", "/")] = f.read()
        return out

    def delete_prefix(self, prefix: str) -> None:
        enc = prefix.replace("/", "%2F")
        for name in os.listdir(self.root):
            if name.startswith(enc):
                try:
                    os.unlink(os.path.join(self.root, name))
                except OSError:
                    pass


class MemoryKVStore(KVStore):
    """In-process store for single-process operation and unit tests."""

    def __init__(self) -> None:
        self._data = {}

    def set(self, key: str, value: bytes) -> None:
        self._data[key] = value

    def try_get(self, key: str) -> Optional[bytes]:
        return self._data.get(key)

    def try_get_dir(self, prefix: str) -> Optional[dict]:
        return {
            k: v for k, v in self._data.items() if k.startswith(prefix)
        }

    def delete_prefix(self, prefix: str) -> None:
        for k in [k for k in self._data if k.startswith(prefix)]:
            del self._data[k]


class LinearBarrierError(RuntimeError):
    pass


class TakeAbortedError(RuntimeError):
    """Another rank's take failed: its abort record was published through
    the coordination KV store, and this rank's barrier/commit wait raised
    within seconds instead of burning the full barrier timeout. The path
    is reusable — no ``.snapshot_metadata`` was written, and each rank
    best-effort deleted its staged blobs."""


class TakeAbortMonitor:
    """Distributed take-abort propagation over the coordination KV store.

    When any rank's take fails, it ``publish``es an abort record under a
    take-scoped prefix; every other rank's waits (polling commit
    barriers, the background commit's LinearBarrier) run ``check`` as a
    watcher and raise :class:`TakeAbortedError` within
    ``check_interval_sec`` + one poll interval. Records are left behind
    on abort (take-scoped keys, a few bytes; the next take uses a fresh
    take_id) and the prefix is deleted on a successful commit."""

    _PREFIX = "tpusnap_abort"

    def __init__(
        self,
        store: KVStore,
        take_id: str,
        rank: int,
        check_interval_sec: float = 0.25,
    ) -> None:
        self._store = store
        self.take_id = take_id
        self.rank = rank
        self._interval = check_interval_sec
        self._last_check = 0.0
        self._published = False

    def _prefix(self) -> str:
        return f"{self._PREFIX}/{self.take_id}/"

    def publish(self, exc: BaseException) -> None:
        """Record this rank's failure for every peer to observe."""
        if self._published:
            return
        self._published = True
        try:
            payload = pickle.dumps(exc)
        except Exception:
            payload = pickle.dumps(RuntimeError(repr(exc)))
        try:
            self._store.set(f"{self._prefix()}r{self.rank}", payload)
        except Exception:
            logger.warning(
                "Failed to publish take-abort record for take %s",
                self.take_id,
                exc_info=True,
            )

    def mark_commit_started(self) -> None:
        """Committing-rank flag set right before the metadata write.
        Aborting ranks consult it: once the commit may exist, staged
        blobs must NOT be deleted (a committed manifest references
        them — orphan blobs are safe, dangling references are not)."""
        try:
            self._store.set(f"{self._prefix()}commit_started", b"1")
        except Exception:
            # Swallowed deliberately, but not silent: if the flag never
            # lands, aborting peers fall back to commit_may_have_started's
            # conservative True and keep their staged blobs.
            logger.debug(
                "commit_started flag publish failed for take %s",
                self.take_id,
                exc_info=True,
            )

    def commit_may_have_started(self) -> bool:
        try:
            return (
                self._store.try_get(f"{self._prefix()}commit_started")
                is not None
            )
        except Exception:
            # Unknown — be conservative and keep the blobs.
            return True

    def check(self, force: bool = False) -> None:
        """Raise :class:`TakeAbortedError` if any rank published an abort
        record. RPC-throttled to ``check_interval_sec`` unless forced."""
        now = time.monotonic()
        if not force and now - self._last_check < self._interval:
            return
        self._last_check = now
        try:
            records = self._store.try_get_dir(self._prefix())
        except Exception:
            return
        if not records:
            return
        # try_get_dir keys are store-relative (they include the prefix).
        prefix = self._prefix()
        aborts = sorted(
            (k[len(prefix) :], v)
            for k, v in records.items()
            if k.startswith(prefix) and k[len(prefix) :].startswith("r")
        )
        if not aborts:
            return
        rank_key, payload = aborts[0]
        try:
            cause: Optional[BaseException] = pickle.loads(payload)
        except Exception:
            cause = None
        err = TakeAbortedError(
            f"take {self.take_id} aborted by rank {rank_key[1:]}: {cause!r}"
        )
        if cause is not None:
            raise err from cause
        raise err

    def clear(self) -> None:
        """Best-effort deletion of the take's abort prefix (leader calls
        this after a successful commit so the service does not accumulate
        per-take keys)."""
        try:
            self._store.delete_prefix(self._prefix())
        except Exception:
            logger.debug(
                "abort-prefix cleanup failed for take %s",
                self.take_id,
                exc_info=True,
            )


class LinearBarrier:
    """Two-phase barrier with error propagation (reference
    dist_store.py:91-196). Leader waits for every rank to arrive, then
    signals departure. ``report_error`` poisons the barrier: all waiters
    raise. ``watchers`` are callables run every poll iteration that may
    raise to abort the wait early (take-abort propagation). Pure KV
    traffic — safe from non-main threads.

    ``ranks`` restricts membership to a subset of the world (default:
    every rank) — the degraded-commit path synchronizes the SURVIVOR
    set of a take whose dead rank will never arrive; the leader defaults
    to the smallest member."""

    def __init__(
        self,
        store: KVStore,
        prefix: str,
        rank: int,
        world_size: int,
        leader_rank: Optional[int] = None,
        timeout_sec: Optional[float] = None,
        watchers: Optional[List[Callable[[], None]]] = None,
        ranks: Optional[List[int]] = None,
    ) -> None:
        self.store = store
        self.prefix = prefix
        self.rank = rank
        self.world_size = world_size
        self.ranks = (
            sorted(ranks) if ranks is not None else list(range(world_size))
        )
        if rank not in self.ranks:
            raise ValueError(
                f"LinearBarrier {prefix!r}: rank {rank} is not a member of "
                f"{self.ranks}"
            )
        self.leader_rank = (
            leader_rank if leader_rank is not None else min(self.ranks)
        )
        self.timeout_sec = (
            timeout_sec if timeout_sec is not None else _default_timeout_sec()
        )
        self.watchers = list(watchers or [])
        # True while blocked inside a _checked_get poll loop — read by
        # current_missing() from the stall watchdog thread.
        self._in_wait = False

    def _key(self, *parts: str) -> str:
        return "/".join((self.prefix,) + parts)

    def _raise_any_reported_error(self) -> None:
        """One dir-get over the error prefix when the backend supports it
        (coordination clients without a cheap single-key probe pay a
        blocking-get timeout PER missing key — O(world_size) per poll
        iteration scales badly); per-key scan as the fallback."""
        prefix = self._key("error") + "/"
        try:
            errs = self.store.try_get_dir(prefix)
        except Exception:
            errs = None
        if errs is None:
            errs = {}
            for r in self.ranks:
                err = self.store.try_get(self._key("error", str(r)))
                if err is not None:
                    errs[str(r)] = err
        for k, err in sorted(errs.items()):
            rank = k.rsplit("/", 1)[-1]
            raise LinearBarrierError(
                f"Rank {rank} reported error: {pickle.loads(err)}"
            )

    def _checked_get(self, key: str) -> bytes:
        """Wait for a key while also watching for reported errors."""
        deadline = time.monotonic() + self.timeout_sec
        self._in_wait = True
        try:
            while True:
                value = self.store.try_get(key)
                if value is not None:
                    return value
                for watcher in self.watchers:
                    watcher()
                self._raise_any_reported_error()
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"LinearBarrier {self.prefix!r}: timed out waiting for "
                        f"{key!r}"
                    )
                time.sleep(_POLL_INTERVAL_SEC)
        finally:
            self._in_wait = False

    def current_missing(self) -> Optional[List[int]]:
        """While a rank is blocked in this barrier, the sorted rank ids
        that have NOT arrived (stall-watchdog attribution; a non-leader
        stuck in depart() with every rank arrived gets the leader, which
        owns the pending depart signal). None when not waiting. KV reads
        only — safe from the watchdog thread."""
        if not self._in_wait:
            return None
        missing = []
        for r in self.ranks:
            try:
                if self.store.try_get(self._key("arrive", str(r))) is None:
                    missing.append(r)
            except Exception:
                return None
        if not missing:
            return [self.leader_rank] if self.rank != self.leader_rank else None
        return missing

    def arrive(self) -> None:
        from . import flight, telemetry

        flight.record("barrier_enter", op=self.prefix)
        with telemetry.span("kv.barrier_arrive"):
            self.store.set(self._key("arrive", str(self.rank)), b"1")
            if self.rank == self.leader_rank:
                for r in self.ranks:
                    self._checked_get(self._key("arrive", str(r)))

    def depart(self) -> None:
        from . import flight, telemetry

        with telemetry.span("kv.barrier_depart"):
            if self.rank == self.leader_rank:
                self.store.set(self._key("depart"), b"1")
            else:
                self._checked_get(self._key("depart"))
        # Release observed: the cross-rank skew anchor (every rank logs
        # the same prefix within one poll interval of the leader's
        # depart signal).
        flight.record("barrier_exit", op=self.prefix)

    def report_error(self, exc: BaseException) -> None:
        try:
            payload = pickle.dumps(exc)
        except Exception:
            payload = pickle.dumps(RuntimeError(repr(exc)))
        self.store.set(self._key("error", str(self.rank)), payload)
