"""KV stores + LinearBarrier for thread-safe coordination.

Counterpart of /root/reference/torchsnapshot/dist_store.py. The async
snapshot commit runs on a background thread where collectives are
forbidden (reference snapshot.py:902), so it synchronizes through a KV
store instead:

- ``CoordinationKVStore`` — the jax.distributed coordination-service
  client (the TPU-native replacement for c10d TCPStore).
- ``FileKVStore`` — a shared-filesystem store for single-host
  multi-process tests (and a fallback when no coordination service is
  up but ranks share a filesystem).
- ``LinearBarrier`` — the reference's two-phase (arrive/depart) barrier
  with error propagation (dist_store.py:91-196): any rank can
  ``report_error``; every waiter then re-raises it, which is how a
  failed async snapshot aborts the metadata commit on all ranks.
"""

from __future__ import annotations

import abc
import os
import pickle
import tempfile
import time
from typing import Optional

_DEFAULT_TIMEOUT_SEC = 600.0
_POLL_INTERVAL_SEC = 0.05


class KVStore(abc.ABC):
    @abc.abstractmethod
    def set(self, key: str, value: bytes) -> None: ...

    @abc.abstractmethod
    def try_get(self, key: str) -> Optional[bytes]: ...

    def try_get_dir(self, prefix: str) -> Optional[dict]:
        """All (key, value) pairs under ``prefix`` in ONE call when the
        backend supports it, else None (caller falls back to per-key
        gets). Keys in the result are relative to the store, like the
        keys passed to ``set``."""
        return None

    def delete_prefix(self, prefix: str) -> None:
        """Best-effort deletion of every key under ``prefix``."""

    def get(self, key: str, timeout_sec: float = _DEFAULT_TIMEOUT_SEC) -> bytes:
        deadline = time.monotonic() + timeout_sec
        while True:
            value = self.try_get(key)
            if value is not None:
                return value
            if time.monotonic() > deadline:
                raise TimeoutError(f"Timed out waiting for key {key!r}")
            time.sleep(_POLL_INTERVAL_SEC)


class CoordinationKVStore(KVStore):
    """Backed by the jax.distributed coordination service client."""

    def __init__(self, prefix: str = "tpusnap_store") -> None:
        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            raise RuntimeError("jax.distributed is not initialized")
        self._client = client
        self._prefix = prefix

    def _k(self, key: str) -> str:
        return f"{self._prefix}/{key}"

    def set(self, key: str, value: bytes) -> None:
        import base64

        self._client.key_value_set(self._k(key), base64.b64encode(value).decode())

    def try_get(self, key: str) -> Optional[bytes]:
        import base64

        try:
            raw = self._client.key_value_try_get(self._k(key))
        except Exception:
            return None
        if raw is None:
            return None
        if isinstance(raw, bytes):
            raw = raw.decode()
        return base64.b64decode(raw)

    def try_get_dir(self, prefix: str) -> Optional[dict]:
        import base64

        try:
            pairs = self._client.key_value_dir_get(self._k(prefix))
        except Exception:
            return None
        out = {}
        strip = len(self._prefix) + 1
        for k, v in pairs:
            if isinstance(v, bytes):
                v = v.decode()
            out[k[strip:]] = base64.b64decode(v)
        return out

    def delete_prefix(self, prefix: str) -> None:
        try:
            self._client.key_value_delete(self._k(prefix))
        except Exception:
            pass


class FileKVStore(KVStore):
    """Directory-backed store; atomic via rename. Works wherever ranks
    share a filesystem (incl. the snapshot destination itself)."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key.replace("/", "%2F"))

    def set(self, key: str, value: bytes) -> None:
        path = self._path(key)
        fd, tmp = tempfile.mkstemp(dir=self.root)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(value)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def try_get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def try_get_dir(self, prefix: str) -> Optional[dict]:
        enc = prefix.replace("/", "%2F")
        out = {}
        for name in os.listdir(self.root):
            if name.startswith(enc):
                with open(os.path.join(self.root, name), "rb") as f:
                    out[name.replace("%2F", "/")] = f.read()
        return out

    def delete_prefix(self, prefix: str) -> None:
        enc = prefix.replace("/", "%2F")
        for name in os.listdir(self.root):
            if name.startswith(enc):
                try:
                    os.unlink(os.path.join(self.root, name))
                except OSError:
                    pass


class MemoryKVStore(KVStore):
    """In-process store for single-process operation and unit tests."""

    def __init__(self) -> None:
        self._data = {}

    def set(self, key: str, value: bytes) -> None:
        self._data[key] = value

    def try_get(self, key: str) -> Optional[bytes]:
        return self._data.get(key)

    def try_get_dir(self, prefix: str) -> Optional[dict]:
        return {
            k: v for k, v in self._data.items() if k.startswith(prefix)
        }

    def delete_prefix(self, prefix: str) -> None:
        for k in [k for k in self._data if k.startswith(prefix)]:
            del self._data[k]


class LinearBarrierError(RuntimeError):
    pass


class LinearBarrier:
    """Two-phase barrier with error propagation (reference
    dist_store.py:91-196). Leader waits for every rank to arrive, then
    signals departure. ``report_error`` poisons the barrier: all waiters
    raise. Pure KV traffic — safe from non-main threads."""

    def __init__(
        self,
        store: KVStore,
        prefix: str,
        rank: int,
        world_size: int,
        leader_rank: int = 0,
        timeout_sec: float = _DEFAULT_TIMEOUT_SEC,
    ) -> None:
        self.store = store
        self.prefix = prefix
        self.rank = rank
        self.world_size = world_size
        self.leader_rank = leader_rank
        self.timeout_sec = timeout_sec

    def _key(self, *parts: str) -> str:
        return "/".join((self.prefix,) + parts)

    def _checked_get(self, key: str) -> bytes:
        """Wait for a key while also watching for reported errors."""
        deadline = time.monotonic() + self.timeout_sec
        while True:
            value = self.store.try_get(key)
            if value is not None:
                return value
            for r in range(self.world_size):
                err = self.store.try_get(self._key("error", str(r)))
                if err is not None:
                    raise LinearBarrierError(
                        f"Rank {r} reported error: {pickle.loads(err)}"
                    )
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"LinearBarrier {self.prefix!r}: timed out waiting for "
                    f"{key!r}"
                )
            time.sleep(_POLL_INTERVAL_SEC)

    def arrive(self) -> None:
        self.store.set(self._key("arrive", str(self.rank)), b"1")
        if self.rank == self.leader_rank:
            for r in range(self.world_size):
                self._checked_get(self._key("arrive", str(r)))

    def depart(self) -> None:
        if self.rank == self.leader_rank:
            self.store.set(self._key("depart"), b"1")
        else:
            self._checked_get(self._key("depart"))

    def report_error(self, exc: BaseException) -> None:
        try:
            payload = pickle.dumps(exc)
        except Exception:
            payload = pickle.dumps(RuntimeError(repr(exc)))
        self.store.set(self._key("error", str(self.rank)), payload)
