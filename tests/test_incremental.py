"""Incremental snapshots: ``Snapshot.take(..., incremental_from=prev)``
skips writing blobs whose stage-time checksums match the base snapshot and
references the base's blobs by relative location (no reference
counterpart — torchsnapshot rewrites every byte every take).

Covers: unchanged state writes no data blobs; a changed leaf rewrites
only itself; restore/read_object/scrub resolve cross-snapshot references;
chained increments collapse to the oldest base; sharded/chunked/object
dedup; async incremental takes; deleting the base breaks the increment
loudly; per-member slab dedup with compaction; tile-grain dedup (one
changed row rewrites one checksum tile); and the >32-bit dedup-evidence
rule (CRC + independent 64-bit hash per skip decision).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusnap import PytreeState, Snapshot, StateDict, verify_snapshot
from tpusnap.knobs import (
    override_async_cow,
    override_batching_disabled,
    override_max_chunk_size_bytes,
    override_max_shard_size_bytes,
    override_record_dedup_hashes,
    override_tile_checksum_bytes,
)


def _blob_files(root: str):
    """All PAYLOAD files under a snapshot dir: everything except the
    metadata and the .tpusnap/ sidecar (telemetry traces)."""
    out = []
    for dirpath, _, files in os.walk(root):
        if ".tpusnap" in dirpath.split(os.sep):
            continue
        for f in files:
            if f != ".snapshot_metadata":
                out.append(os.path.relpath(os.path.join(dirpath, f), root))
    return sorted(out)


def _state(seed=0):
    rng = np.random.default_rng(seed)
    return StateDict(
        w=rng.standard_normal((512, 128)).astype(np.float32),
        b=rng.standard_normal((256,)).astype(np.float32),
        cfg={"lr": 0.1, "layers": [1, 2]},
        step=1,
    )


def test_unchanged_take_writes_no_data(tmp_path):
    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    with override_batching_disabled(True):
        Snapshot.take(base, {"app": _state()})
        Snapshot.take(inc, {"app": _state()}, incremental_from=base)
    assert _blob_files(inc) == [], "unchanged take must write no data blobs"
    # Restore from the increment resolves into the base's blobs.
    target = {"app": StateDict(w=np.zeros((512, 128), np.float32),
                               b=np.zeros((256,), np.float32),
                               cfg={}, step=0)}
    Snapshot(inc).restore(target)
    src = _state()
    assert np.array_equal(target["app"]["w"], src["w"])
    assert np.array_equal(target["app"]["b"], src["b"])
    assert target["app"]["cfg"] == {"lr": 0.1, "layers": [1, 2]}
    assert target["app"]["step"] == 1
    # Scrub follows cross-snapshot references.
    assert verify_snapshot(inc).clean
    # read_object too.
    out = Snapshot(inc).read_object("0/app/w")
    assert np.array_equal(out, src["w"])


def test_changed_leaf_rewrites_only_itself(tmp_path):
    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    st = _state()
    with override_batching_disabled(True):
        Snapshot.take(base, {"app": st})
        st["b"] = st["b"] + 1.0
        Snapshot.take(inc, {"app": st}, incremental_from=base)
    files = _blob_files(inc)
    assert files == ["0/app/b"], files
    target = {"app": StateDict(w=np.zeros((512, 128), np.float32),
                               b=np.zeros((256,), np.float32),
                               cfg={}, step=0)}
    Snapshot(inc).restore(target)
    assert np.array_equal(target["app"]["b"], st["b"])
    assert np.array_equal(target["app"]["w"], st["w"])


def test_chained_increments_collapse_to_oldest_base(tmp_path):
    s0, s1, s2 = (str(tmp_path / f"s{i}") for i in range(3))
    st = _state()
    with override_batching_disabled(True):
        Snapshot.take(s0, {"app": st})
        st["b"] = st["b"] * 2
        Snapshot.take(s1, {"app": st}, incremental_from=s0)
        Snapshot.take(s2, {"app": st}, incremental_from=s1)
    assert _blob_files(s2) == []
    # s2's unchanged-since-s0 entries must point STRAIGHT at s0 (chains
    # collapse; lookups never hop through s1).
    md = Snapshot(s2).metadata
    w_loc = md.manifest["0/app/w"].location
    assert w_loc == "../s0/0/app/w", w_loc
    b_loc = md.manifest["0/app/b"].location
    assert b_loc == "../s1/0/app/b", b_loc
    assert verify_snapshot(s2).clean
    target = {"app": StateDict(w=np.zeros((512, 128), np.float32),
                               b=np.zeros((256,), np.float32),
                               cfg={}, step=0)}
    Snapshot(s2).restore(target)
    assert np.array_equal(target["app"]["b"], st["b"])


def test_sharded_incremental(tmp_path):
    mesh = jax.sharding.Mesh(np.array(jax.devices()).reshape(4, 2), ("x", "y"))
    sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("x", "y"))
    w = jax.device_put(
        jnp.arange(64 * 64, dtype=jnp.float32).reshape(64, 64), sh
    )
    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    Snapshot.take(base, {"m": PytreeState({"w": w})})
    Snapshot.take(inc, {"m": PytreeState({"w": w})}, incremental_from=base)
    assert _blob_files(inc) == []
    assert verify_snapshot(inc).clean
    # Change the array: all shards rewrite.
    w2 = jax.device_put(w + 1, sh)
    inc2 = str(tmp_path / "s2")
    Snapshot.take(inc2, {"m": PytreeState({"w": w2})}, incremental_from=inc)
    assert len(_blob_files(inc2)) == 8  # one blob per shard
    target = {"m": PytreeState({"w": jax.device_put(jnp.zeros((64, 64), jnp.float32), sh)})}
    Snapshot(inc2).restore(target)
    assert np.array_equal(np.asarray(target["m"].tree["w"]), np.asarray(w2))


def test_chunked_incremental_partial_change(tmp_path):
    """Only the chunks whose rows changed rewrite."""
    arr = np.random.default_rng(3).standard_normal((64, 256)).astype(np.float32)
    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    with override_max_chunk_size_bytes(16 * 1024), override_batching_disabled(True):
        Snapshot.take(base, {"app": StateDict(big=arr)})
        arr2 = arr.copy()
        arr2[-1, :] += 1.0  # touch only the last chunk's rows
        Snapshot.take(inc, {"app": StateDict(big=arr2)}, incremental_from=base)
    files = _blob_files(inc)
    assert len(files) == 1 and files[0].startswith("0/app/big_"), files
    target = {"app": StateDict(big=np.zeros_like(arr))}
    Snapshot(inc).restore(target)
    assert np.array_equal(target["app"]["big"], arr2)
    assert verify_snapshot(inc).clean


def test_async_incremental_take(tmp_path):
    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    st = _state()
    with override_batching_disabled(True):
        Snapshot.take(base, {"app": st})
        pending = Snapshot.async_take(
            inc, {"app": st}, incremental_from=base
        )
        snap = pending.wait()
    assert _blob_files(inc) == []
    assert snap.verify().clean


def test_deleted_base_breaks_increment_loudly(tmp_path):
    import shutil

    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    with override_batching_disabled(True):
        Snapshot.take(base, {"app": _state()})
        Snapshot.take(inc, {"app": _state()}, incremental_from=base)
    shutil.rmtree(base)
    report = verify_snapshot(inc)
    assert not report.clean and report.corrupt > 0
    target = {"app": StateDict(w=np.zeros((512, 128), np.float32),
                               b=np.zeros((256,), np.float32),
                               cfg={}, step=0)}
    with pytest.raises(Exception):
        Snapshot(inc).restore(target)


def test_slab_integrity_through_dedup(tmp_path):
    """Batched small arrays stage into slabs; member dedup must never
    hole a slab — unchanged members reference the base slab, the new
    slab is compacted, and the whole increment restores + scrubs."""
    st = StateDict(
        a=np.arange(64, dtype=np.float32),
        b=np.arange(64, 128, dtype=np.float32),
        c=np.arange(128, 192, dtype=np.float32),
    )
    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    Snapshot.take(base, {"app": st})
    Snapshot.take(inc, {"app": st}, incremental_from=base)
    # Whether or not members deduped, the increment restores bit-exact
    # and scrubs clean (slab integrity preserved).
    target = {"app": StateDict(a=np.zeros(64, np.float32),
                               b=np.zeros(64, np.float32),
                               c=np.zeros(64, np.float32))}
    Snapshot(inc).restore(target)
    for k in ("a", "b", "c"):
        assert np.array_equal(target["app"][k], st[k]), k
    assert verify_snapshot(inc).clean


def test_incremental_tile_grain(tmp_path):
    """Large blobs keep tile checksums through dedup; budget-tiled reads
    of a deduped entry verify against the base's bytes. The base take
    records 64-bit tile hashes (the knob documented for bases of
    planned incremental chains) — a tiled skip requires hash evidence
    on both sides."""
    arr = np.random.default_rng(5).standard_normal((4096, 64)).astype(np.float32)
    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    with override_tile_checksum_bytes(128 * 1024), override_batching_disabled(True):
        with override_record_dedup_hashes(True):
            Snapshot.take(base, {"app": StateDict(big=arr)})
        Snapshot.take(inc, {"app": StateDict(big=arr)}, incremental_from=base)
    assert _blob_files(inc) == []
    out = Snapshot(inc).read_object("0/app/big", memory_budget_bytes=256 * 1024)
    assert np.array_equal(out, arr)


def test_incremental_tiled_hashless_base_rewrites_once(tmp_path):
    """ADVICE r4: a tiled blob over a base WITHOUT recorded tile hashes
    must NOT skip on tile CRCs alone — the first increment rewrites
    (recording hashes), and the second increment dedups with 64-bit
    evidence."""
    arr = np.random.default_rng(6).standard_normal((4096, 64)).astype(np.float32)
    base = str(tmp_path / "s0")
    inc1, inc2 = str(tmp_path / "s1"), str(tmp_path / "s2")
    with override_tile_checksum_bytes(128 * 1024), override_batching_disabled(True):
        Snapshot.take(base, {"app": StateDict(big=arr)})
        assert (
            Snapshot(base).metadata.manifest["0/app/big"].tile_dedup_hashes
            is None
        )
        Snapshot.take(inc1, {"app": StateDict(big=arr)}, incremental_from=base)
        # Conservative rewrite: no 64-bit evidence to match against.
        assert _blob_files(inc1) != []
        Snapshot.take(inc2, {"app": StateDict(big=arr)}, incremental_from=inc1)
    assert _blob_files(inc2) == []
    out = Snapshot(inc2).read_object("0/app/big")
    assert np.array_equal(out, arr)


def test_incremental_requires_same_scheme(tmp_path):
    with pytest.raises(ValueError, match="scheme"):
        Snapshot.take(
            str(tmp_path / "s1"),
            {"app": StateDict(x=np.ones(4, np.float32))},
            incremental_from="gs://bkt/other",
        )


def test_incremental_from_missing_base_fails(tmp_path):
    with pytest.raises(RuntimeError, match="not a readable snapshot"):
        Snapshot.take(
            str(tmp_path / "s1"),
            {"app": StateDict(x=np.ones(4, np.float32))},
            incremental_from=str(tmp_path / "nope"),
        )


def test_sharded_subdivided_incremental(tmp_path):
    """Shards subdivided to the max-shard knob dedup per sub-shard box."""
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("x",))
    sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("x"))
    w = jax.device_put(jnp.arange(1024 * 8, dtype=jnp.float32).reshape(1024, 8), sh)
    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    with override_max_shard_size_bytes(8 * 1024):
        Snapshot.take(base, {"m": PytreeState({"w": w})})
        Snapshot.take(inc, {"m": PytreeState({"w": w})}, incremental_from=base)
    assert _blob_files(inc) == []
    assert verify_snapshot(inc).clean


def test_cli_info_reports_external_refs(tmp_path, capsys):
    from tpusnap.__main__ import main as cli_main

    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    with override_batching_disabled(True):
        Snapshot.take(base, {"app": _state()})
        Snapshot.take(inc, {"app": _state()}, incremental_from=base)
    assert cli_main(["info", inc]) == 0
    out = capsys.readouterr().out
    assert "external:" in out and "s0" in out
    assert cli_main(["info", base]) == 0
    assert "external:" not in capsys.readouterr().out


def _world_incremental_replicated(base, inc):
    import numpy as np

    from tpusnap import Snapshot, StateDict

    def state():
        return StateDict(
            w1=np.arange(512 * 64, dtype=np.float32).reshape(512, 64),
            w2=np.arange(512 * 64, dtype=np.float32).reshape(512, 64) * 2,
        )

    # Both arrays replicated; the write-load partitioner assigns them to
    # different ranks, so at least one writer is rank != 0.
    Snapshot.take(base, {"model": state()}, replicated=["**"])
    Snapshot.take(
        inc, {"model": state()}, replicated=["**"], incremental_from=base
    )
    # Second increment with one value changed: only that blob rewrites.
    st = state()
    st["w2"] = st["w2"] + 1.0
    Snapshot.take(
        inc + "_b", {"model": st}, replicated=["**"], incremental_from=inc
    )


def test_multirank_replicated_incremental(tmp_path):
    """A replicated entry deduped by its assigned writer rank (possibly
    rank != 0) must survive manifest consolidation: the committed
    manifest references the base blob, restores, and scrubs clean.
    (Consolidation must prefer the writer's rewritten copy over rank 0's
    never-staged one.)"""
    from tpusnap.test_utils import run_subprocess_world

    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    run_subprocess_world(
        _world_incremental_replicated,
        world_size=2,
        args=[base, inc],
        extra_env={"TPUSNAP_DISABLE_BATCHING": "1"},
    )
    assert _blob_files(inc) == [], _blob_files(inc)
    md = Snapshot(inc).metadata
    for p in ("0/model/w1", "0/model/w2"):
        assert md.manifest[p].location.startswith("../"), (
            p,
            md.manifest[p].location,
        )
        assert md.manifest[p].checksum is not None
    target = {"model": StateDict(
        w1=np.zeros((512, 64), np.float32), w2=np.zeros((512, 64), np.float32)
    )}
    Snapshot(inc).restore(target)
    expect = np.arange(512 * 64, dtype=np.float32).reshape(512, 64)
    assert np.array_equal(target["model"]["w1"], expect)
    assert np.array_equal(target["model"]["w2"], expect * 2)
    assert verify_snapshot(inc).clean

    # The chained increment rewrote only the changed replicated blob.
    inc_b = inc + "_b"
    files = _blob_files(inc_b)
    assert files == ["replicated/model/w2"], files
    assert verify_snapshot(inc_b).clean
    tgt2 = {"model": StateDict(
        w1=np.zeros((512, 64), np.float32), w2=np.zeros((512, 64), np.float32)
    )}
    Snapshot(inc_b).restore(tgt2)
    assert np.array_equal(tgt2["model"]["w2"], expect * 2 + 1.0)


def test_incremental_refuses_with_checksums_disabled(tmp_path):
    from tpusnap.knobs import override_checksum_disabled

    base = str(tmp_path / "s0")
    Snapshot.take(base, {"app": StateDict(x=np.ones(4, np.float32))})
    with override_checksum_disabled(True):
        with pytest.raises(ValueError, match="checksum"):
            Snapshot.take(
                str(tmp_path / "s1"),
                {"app": StateDict(x=np.ones(4, np.float32))},
                incremental_from=base,
            )


def test_cli_info_numeric_base_name(tmp_path, capsys):
    """Bases named by bare step number must display correctly."""
    from tpusnap.__main__ import main as cli_main

    base, inc = str(tmp_path / "1000"), str(tmp_path / "1100")
    with override_batching_disabled(True):
        Snapshot.take(base, {"app": _state()})
        Snapshot.take(inc, {"app": _state()}, incremental_from=base)
    assert cli_main(["info", inc]) == 0
    out = capsys.readouterr().out
    assert "../1000" in out, out


def test_materialize_makes_increment_self_contained(tmp_path):
    import shutil

    from tpusnap.__main__ import main as cli_main

    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    st = _state()
    with override_batching_disabled(True):
        Snapshot.take(base, {"app": st})
        st["b"] = st["b"] + 1.0
        Snapshot.take(inc, {"app": st}, incremental_from=base)
    assert _blob_files(inc) == ["0/app/b"]

    snap = Snapshot(inc)
    stats = snap.materialize()
    # cfg/step flatten to inline primitives; w is the one external blob
    assert stats["blobs_copied"] == 1
    assert stats["bytes_copied"] == 512 * 128 * 4
    # All blobs now live locally; no external references remain.
    md = snap.metadata
    from tpusnap.inspect import iter_blobs

    assert not any(
        b.location.startswith("../") for b in iter_blobs(md.manifest)
    )
    # The base can be deleted; the materialized snapshot stands alone.
    shutil.rmtree(base)
    assert verify_snapshot(inc).clean
    target = {"app": StateDict(w=np.zeros((512, 128), np.float32),
                               b=np.zeros((256,), np.float32),
                               cfg={}, step=0)}
    Snapshot(inc).restore(target)
    assert np.array_equal(target["app"]["w"], st["w"])
    assert np.array_equal(target["app"]["b"], st["b"])
    # Second materialize is a no-op.
    assert Snapshot(inc).materialize()["blobs_copied"] == 0


def test_materialize_preserves_slab_references(tmp_path):
    """An increment referencing members inside a base SLAB copies the
    slab once and keeps byte ranges valid."""
    import shutil

    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    big = np.random.default_rng(2).standard_normal((512, 128)).astype(np.float32)
    st = StateDict(big=big, a=np.arange(64, dtype=np.float32), b=np.ones(64, np.float32))
    # base: batching ON so a+b land in a slab; increment with batching
    # OFF so a+b become dedup-eligible and reference INTO the base slab.
    Snapshot.take(base, {"app": st})
    with override_batching_disabled(True):
        Snapshot.take(inc, {"app": st}, incremental_from=base)
    md = Snapshot(inc).metadata
    slab_refs = [
        e for e in md.manifest.values()
        if getattr(e, "location", "").startswith("../") and "batched/" in getattr(e, "location", "")
    ]
    if not slab_refs:
        pytest.skip("no slab references produced in this configuration")
    Snapshot(inc).materialize()
    shutil.rmtree(base)
    assert verify_snapshot(inc).clean
    out = Snapshot(inc).read_object("0/app/a")
    assert np.array_equal(out, np.arange(64, dtype=np.float32))


def test_cli_materialize(tmp_path, capsys):
    from tpusnap.__main__ import main as cli_main

    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    with override_batching_disabled(True):
        Snapshot.take(base, {"app": _state()})
        Snapshot.take(inc, {"app": _state()}, incremental_from=base)
    assert cli_main(["materialize", inc]) == 0
    out = capsys.readouterr().out
    assert "self-contained" in out
    assert cli_main(["info", inc]) == 0
    assert "external:" not in capsys.readouterr().out


def test_incremental_refuses_checksumless_base(tmp_path):
    """A base taken with checksums disabled can never dedup — refuse."""
    from tpusnap.knobs import override_checksum_disabled

    base = str(tmp_path / "s0")
    with override_checksum_disabled(True):
        Snapshot.take(base, {"app": StateDict(x=np.ones(64, np.float32))})
    with pytest.raises(ValueError, match="checksums"):
        Snapshot.take(
            str(tmp_path / "s1"),
            {"app": StateDict(x=np.ones(64, np.float32))},
            incremental_from=base,
        )


def test_materialize_refuses_corrupt_base(tmp_path):
    """Bit-rot in the base must surface DURING materialize (while the
    base still exists), and the manifest must stay base-referencing."""
    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    with override_batching_disabled(True):
        Snapshot.take(base, {"app": _state()})
        Snapshot.take(inc, {"app": _state()}, incremental_from=base)
    _flip = __import__("tests.test_inspect", fromlist=["_flip_byte"])._flip_byte
    _flip(base, "0/app/w")
    with pytest.raises(RuntimeError, match="BASE snapshot is corrupt"):
        Snapshot(inc).materialize()
    # Manifest untouched: still references the base.
    md = Snapshot(inc).metadata
    assert md.manifest["0/app/w"].location.startswith("../")


def _world_elastic_incremental(base_dir, inc_dir, phase):
    import numpy as np

    from tpusnap import Snapshot, StateDict, verify_snapshot
    from tpusnap.comm import get_communicator

    comm = get_communicator()
    shared = np.arange(4096, dtype=np.float32)
    if phase == "save":  # world 2: the base
        state = StateDict(
            shared=shared, own=np.full((64,), float(comm.rank), np.float32)
        )
        Snapshot.take(base_dir, {"m": state}, replicated=["m/shared"])
    else:  # world 3: incremental take on the world-2 base
        state = StateDict(
            shared=shared,  # unchanged -> dedups against the base
            own=np.full((64,), 10.0 + comm.rank, np.float32),  # changed
        )
        Snapshot.take(
            inc_dir, {"m": state}, replicated=["m/shared"],
            incremental_from=base_dir,
        )
        md = Snapshot(inc_dir).metadata
        assert md.world_size == 3
        # The replicated blob deduped against the base across the
        # world-size change; per-rank blobs (changed) were written.
        assert md.manifest["0/m/shared"].location.startswith("../")
        for r in range(3):
            assert not md.manifest[f"{r}/m/own"].location.startswith("../")
        if comm.rank == 0:
            assert verify_snapshot(inc_dir).clean
        dst = {"m": StateDict(
            shared=np.zeros(4096, np.float32), own=np.zeros(64, np.float32)
        )}
        Snapshot(inc_dir).restore(dst)
        np.testing.assert_array_equal(dst["m"]["shared"], shared)
        np.testing.assert_array_equal(
            dst["m"]["own"], np.full((64,), 10.0 + comm.rank, np.float32)
        )


def test_elastic_incremental_upscale(tmp_path):
    """Incremental take at world 3 against a world-2 base: the new rank's
    manifest view (replicated re-expansion) feeds dedup; unchanged
    replicated state references the base, changed per-rank state writes."""
    from tpusnap.test_utils import run_subprocess_world

    base, inc = str(tmp_path / "base"), str(tmp_path / "inc")
    with override_batching_disabled(True):
        run_subprocess_world(
            _world_elastic_incremental, world_size=2, args=[base, inc, "save"],
            extra_env={"TPUSNAP_DISABLE_BATCHING": "1"},
        )
        run_subprocess_world(
            _world_elastic_incremental, world_size=3, args=[base, inc, "load"],
            extra_env={"TPUSNAP_DISABLE_BATCHING": "1"},
        )


def test_materialized_snapshot_reshards_on_restore(tmp_path):
    """materialize rewrites shard locations; the overlap-read reshard
    path must work off the local copies (base deleted) into a different
    target sharding."""
    import shutil

    devs = np.array(jax.devices())
    mesh_a = jax.sharding.Mesh(devs.reshape(2, 4), ("x", "y"))
    mesh_b = jax.sharding.Mesh(devs.reshape(4, 2), ("x", "y"))
    spec = jax.sharding.PartitionSpec("x", "y")
    w = jax.device_put(
        jnp.arange(64 * 64, dtype=jnp.float32).reshape(64, 64),
        jax.sharding.NamedSharding(mesh_a, spec),
    )
    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    Snapshot.take(base, {"m": PytreeState({"w": w})})
    Snapshot.take(inc, {"m": PytreeState({"w": w})}, incremental_from=base)
    Snapshot(inc).materialize()
    shutil.rmtree(base)
    target = PytreeState(
        {
            "w": jax.device_put(
                jnp.zeros((64, 64), jnp.float32),
                jax.sharding.NamedSharding(mesh_b, spec),
            )
        }
    )
    Snapshot(inc).restore({"m": target})
    restored = target.tree["w"]
    assert restored.sharding.mesh.shape == {"x": 4, "y": 2}
    assert np.array_equal(np.asarray(restored), np.asarray(w))
    assert verify_snapshot(inc).clean


def test_async_incremental_mutation_isolation(tmp_path):
    """Async incremental take with a CHANGED leaf under the DEFAULT
    (COW) staging mode: live bytes stay aliased until the write drain,
    so training mutates only after ``wait_staged()`` — which freezes
    exactly the pre-mutation content (deduped leaves never clone and
    never write)."""
    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    frozen = np.random.default_rng(0).standard_normal((256, 64)).astype(np.float32)
    hot = np.arange(512, dtype=np.float32)
    frozen_orig = frozen.copy()
    with override_batching_disabled(True):
        Snapshot.take(base, {"app": StateDict(frozen=frozen, hot=hot)})
        hot2 = hot + 1.0
        state = StateDict(frozen=frozen, hot=hot2)
        pending = Snapshot.async_take(
            inc, {"app": state}, incremental_from=base
        )
        # Training continues AFTER the COW-aware rendezvous: under the
        # default TPUSNAP_ASYNC_COW staging the live bytes back the
        # in-flight writes, and wait_staged() is the safe-to-mutate
        # contract.
        assert pending.wait_staged()
        hot2[:] = -99.0
        frozen_view = state["frozen"]
        frozen_view[:] = -77.0
        snap = pending.wait()
    assert _blob_files(inc) == ["0/app/hot"]  # only the changed leaf wrote
    assert snap.verify().clean
    target = {"app": StateDict(frozen=np.zeros_like(frozen), hot=np.zeros(512, np.float32))}
    Snapshot(inc).restore(target)
    # hot: pre-mutation changed value (the drain completed before the
    # mutation).
    assert np.array_equal(target["app"]["hot"], hot + 1.0)
    # frozen: deduped against the base — the BASE's bytes, untouched by
    # the post-drain mutation of the live array.
    assert np.array_equal(target["app"]["frozen"], frozen_orig)


def test_async_incremental_mutation_isolation_cow_off(tmp_path):
    """The TPUSNAP_ASYNC_COW=0 escape hatch restores the defensive-clone
    contract: mutate IMMEDIATELY after control returns, before any write
    drains — the clone froze the content, so the take still commits the
    pre-mutation bytes."""
    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    frozen = np.random.default_rng(0).standard_normal((256, 64)).astype(np.float32)
    hot = np.arange(512, dtype=np.float32)
    frozen_orig = frozen.copy()
    with override_batching_disabled(True), override_async_cow(False):
        Snapshot.take(base, {"app": StateDict(frozen=frozen, hot=hot)})
        hot2 = hot + 1.0
        state = StateDict(frozen=frozen, hot=hot2)
        pending = Snapshot.async_take(
            inc, {"app": state}, incremental_from=base
        )
        # No rendezvous: the clone already froze the content.
        hot2[:] = -99.0
        state["frozen"][:] = -77.0
        snap = pending.wait()
    assert _blob_files(inc) == ["0/app/hot"]
    assert snap.verify().clean
    target = {"app": StateDict(frozen=np.zeros_like(frozen), hot=np.zeros(512, np.float32))}
    Snapshot(inc).restore(target)
    assert np.array_equal(target["app"]["hot"], hot + 1.0)
    assert np.array_equal(target["app"]["frozen"], frozen_orig)


# ---------------------------------------------------------------- round 4:
# tile-grain dedup, slab-member dedup, and >32-bit dedup evidence


def _total_blob_bytes(root: str) -> int:
    return sum(
        os.path.getsize(os.path.join(root, f)) for f in _blob_files(root)
    )


class TestTileGrainDedup:
    """Changing one row of a large array rewrites ~one checksum tile,
    not the whole blob: the incremental take re-chunks the array on the
    base's tile grid, unchanged tiles become byte-range references into
    the base blob, and every skip decision compares a 32-bit CRC AND a
    64-bit hash per tile."""

    SHAPE = (1024, 64)  # f32: 256 KiB; 4 KiB tiles -> 64 tiles of 16 rows

    def _arr(self):
        return (
            np.random.default_rng(7)
            .standard_normal(self.SHAPE)
            .astype(np.float32)
        )

    def _ctx(self):
        from contextlib import ExitStack

        from tpusnap.knobs import (
            override_record_dedup_hashes,
            override_tile_checksum_bytes,
        )

        stack = ExitStack()
        stack.enter_context(override_tile_checksum_bytes(4 * 1024))
        # Base takes record tile dedup hashes so the FIRST increment can
        # already dedup tile-grain.
        stack.enter_context(override_record_dedup_hashes(True))
        return stack

    def test_one_changed_row_writes_one_tile(self, tmp_path):
        arr = self._arr()
        base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
        with self._ctx():
            Snapshot.take(base, {"app": StateDict(big=arr)})
            base_entry = Snapshot(base).metadata.manifest["0/app/big"]
            assert base_entry.tile_dedup_hashes  # forced by the knob
            changed = arr.copy()
            changed[500, :] += 1.0  # one row -> one 16-row tile
            Snapshot.take(
                inc, {"app": StateDict(big=changed)}, incremental_from=base
            )
        # Only ~one tile (4 KiB) of new data, not the 256 KiB blob.
        written = _total_blob_bytes(inc)
        assert 0 < written <= 2 * 4 * 1024, written
        e = Snapshot(inc).metadata.manifest["0/app/big"]
        assert e.type == "ChunkedTensor"
        ext = [c for c in e.chunks if c.tensor.location.startswith("../")]
        assert len(ext) == len(e.chunks) - 1  # all but the changed tile
        # Restore, scrub, and read_object all resolve the mixed form.
        target = {"app": StateDict(big=np.zeros(self.SHAPE, np.float32))}
        Snapshot(inc).restore(target)
        assert np.array_equal(target["app"]["big"], changed)
        assert verify_snapshot(inc).clean
        out = Snapshot(inc).read_object("0/app/big")
        assert np.array_equal(out, changed)

    def test_chain_stays_tile_grain_and_collapses(self, tmp_path):
        """The 2nd increment dedups against the 1st's CHUNKED entry and
        references collapse to the oldest base that owns each tile."""
        arr = self._arr()
        s0, s1, s2 = (str(tmp_path / f"s{i}") for i in range(3))
        with self._ctx():
            Snapshot.take(s0, {"app": StateDict(big=arr)})
            c1 = arr.copy()
            c1[500, :] += 1.0
            Snapshot.take(s1, {"app": StateDict(big=c1)}, incremental_from=s0)
            c2 = c1.copy()
            c2[10, :] -= 2.0
            Snapshot.take(s2, {"app": StateDict(big=c2)}, incremental_from=s1)
        written = _total_blob_bytes(s2)
        assert 0 < written <= 2 * 4 * 1024, written
        e = Snapshot(s2).metadata.manifest["0/app/big"]
        locs = {c.tensor.location.split("/")[1] if c.tensor.location.startswith("..") else "local" for c in e.chunks}
        # Tiles reference s0 (unchanged since base), s1 (row 500), and
        # one local write (row 10) — chained refs collapsed, not s1-only.
        assert "s0" in locs and "s1" in locs and "local" in locs
        target = {"app": StateDict(big=np.zeros(self.SHAPE, np.float32))}
        Snapshot(s2).restore(target)
        assert np.array_equal(target["app"]["big"], c2)
        assert verify_snapshot(s2).clean

    def test_diff_decides_across_geometries(self, tmp_path):
        """diff(base, tile-grain increment): unchanged paths identical,
        the changed path provably changed — not undecidable — even
        though the increment stores a chunked geometry."""
        from tpusnap.inspect import diff_snapshots

        arr = self._arr()
        other = np.arange(32, dtype=np.int32)
        base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
        with self._ctx():
            Snapshot.take(base, {"app": StateDict(big=arr, other=other)})
            changed = arr.copy()
            changed[0, 0] += 1.0
            Snapshot.take(
                inc,
                {"app": StateDict(big=changed, other=other)},
                incremental_from=base,
            )
        d = diff_snapshots(base, inc)
        assert "0/app/big" in d.changed
        assert "0/app/other" in d.identical
        assert not d.unknown

    def test_materialize_tile_grain_increment(self, tmp_path):
        arr = self._arr()
        base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
        with self._ctx():
            Snapshot.take(base, {"app": StateDict(big=arr)})
            changed = arr.copy()
            changed[123, :] *= 3.0
            Snapshot.take(
                inc, {"app": StateDict(big=changed)}, incremental_from=base
            )
        from tpusnap.inspect import materialize_snapshot

        stats = materialize_snapshot(inc)
        assert stats["blobs_copied"] >= 1
        import shutil

        shutil.rmtree(base)
        target = {"app": StateDict(big=np.zeros(self.SHAPE, np.float32))}
        Snapshot(inc).restore(target)
        assert np.array_equal(target["app"]["big"], changed)
        assert verify_snapshot(inc).clean

    def test_tile_route_needs_prev_tile_hashes(self, tmp_path):
        """A base WITHOUT tile dedup hashes (plain take) cannot back
        tile-grain skips — the increment falls back to whole-blob
        dedup/rewrite and stays correct."""
        from tpusnap.knobs import override_tile_checksum_bytes

        arr = self._arr()
        base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
        with override_tile_checksum_bytes(4 * 1024):
            Snapshot.take(base, {"app": StateDict(big=arr)})  # no hashes
            assert (
                Snapshot(base).metadata.manifest["0/app/big"].tile_dedup_hashes
                is None
            )
            changed = arr.copy()
            changed[500, :] += 1.0
            Snapshot.take(
                inc, {"app": StateDict(big=changed)}, incremental_from=base
            )
        # Whole blob rewrote (safe fallback)...
        assert _total_blob_bytes(inc) >= arr.nbytes
        # ...and the rewrite recorded tile hashes (incremental take), so
        # the NEXT increment reaches tile grain.
        inc2 = str(tmp_path / "s2")
        with override_tile_checksum_bytes(4 * 1024):
            c2 = changed.copy()
            c2[1, :] = 0.0
            Snapshot.take(inc2, {"app": StateDict(big=c2)}, incremental_from=inc)
        assert 0 < _total_blob_bytes(inc2) <= 2 * 4 * 1024


class TestSlabMemberDedup:
    """Small slab-batched arrays dedup per member: unchanged members
    re-point at the base slab's byte ranges, the new slab holds only
    changed members (compacted), and a fully-unchanged slab writes
    nothing."""

    def _state(self, bump: float = 0.0):
        rng = np.random.default_rng(3)
        st = {
            f"p{i}": rng.standard_normal(256).astype(np.float32)
            for i in range(6)
        }
        if bump:
            st["p3"] = st["p3"] + bump
        return StateDict(**st)

    def test_one_changed_member_compacts_slab(self, tmp_path):
        base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
        Snapshot.take(base, {"app": self._state()})
        Snapshot.take(
            inc, {"app": self._state(bump=1.0)}, incremental_from=base
        )
        # New slab holds exactly the changed member's bytes.
        assert _total_blob_bytes(inc) == 256 * 4
        m = Snapshot(inc).metadata.manifest
        ext = [k for k in m if k.startswith("0/app/p") and m[k].location.startswith("../")]
        assert len(ext) == 5
        assert m["0/app/p3"].byte_range == [0, 1024]  # compacted offset
        target = {"app": self._state() }
        expect = self._state(bump=1.0)
        Snapshot(inc).restore(target)
        for k in expect:
            assert np.array_equal(target["app"][k], expect[k]), k
        assert verify_snapshot(inc).clean

    def test_unchanged_slab_writes_nothing(self, tmp_path):
        base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
        Snapshot.take(base, {"app": self._state()})
        Snapshot.take(inc, {"app": self._state()}, incremental_from=base)
        assert _blob_files(inc) == []
        assert verify_snapshot(inc).clean

    def test_device_batched_members_dedup(self, tmp_path):
        """jax.Array members take the device-packing path; dedup and
        compaction must work there too."""
        import jax.numpy as jnp

        def state(bump=0.0):
            vals = {
                f"p{i}": jnp.asarray(
                    np.arange(i * 100, i * 100 + 128, dtype=np.float32)
                )
                for i in range(4)
            }
            if bump:
                vals["p1"] = vals["p1"] + bump
            return StateDict(**vals)

        base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
        Snapshot.take(base, {"app": state()})
        Snapshot.take(inc, {"app": state(bump=2.0)}, incremental_from=base)
        assert _total_blob_bytes(inc) == 128 * 4
        target = {"app": state()}
        Snapshot(inc).restore(target)
        assert np.allclose(np.asarray(target["app"]["p1"]),
                           np.arange(100, 228, dtype=np.float32) + 2.0)
        assert verify_snapshot(inc).clean

    def test_member_without_base_hash_rewrites(self, tmp_path):
        """Strip the base members' dedup hashes (simulating an old-format
        base): a single matching 32-bit CRC is NOT enough evidence, so
        members conservatively rewrite."""
        import json

        base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
        Snapshot.take(base, {"app": self._state()})
        md_path = os.path.join(base, ".snapshot_metadata")
        md = json.loads(open(md_path).read())
        for entry in md["manifest"].values():
            entry.pop("dedup_hash", None)
        # Rewriting the file invalidates its self-checksum; per the
        # format spec a rewriter strips (or recomputes) the field.
        md.pop("self_checksum", None)
        with open(md_path, "w") as f:
            f.write(json.dumps(md))
        Snapshot.take(inc, {"app": self._state()}, incremental_from=base)
        assert _total_blob_bytes(inc) == 6 * 256 * 4  # full rewrite, safe
        assert verify_snapshot(inc).clean


def test_dedup_match_requires_64bit_evidence():
    """Unit pin of the ADVICE r3 fix: two tile-less entries agreeing on
    the 32-bit CRC but differing in (or missing) the 64-bit dedup hash
    must NOT dedup; tiled entries must agree on every tile CRC and, when
    present, every tile hash."""
    from tpusnap.io_preparers.array import dedup_entries_match
    from tpusnap.manifest import TensorEntry

    def te(**kw):
        base = dict(
            location="x", serializer="buffer_protocol", dtype="float32",
            shape=[4], replicated=False, checksum="crc32c:00000001",
        )
        base.update(kw)
        return TensorEntry(**base)

    a = te(dedup_hash="xxh64:00000000000000aa")
    assert dedup_entries_match(a, te(dedup_hash="xxh64:00000000000000aa"))
    # CRC collides, 64-bit hash differs -> changed blob detected.
    assert not dedup_entries_match(a, te(dedup_hash="xxh64:00000000000000bb"))
    # Either side missing the hash -> no dedup (old-format base).
    assert not dedup_entries_match(a, te())
    assert not dedup_entries_match(te(), te())
    # Tiled entries: tile CRCs alone are NOT enough (ADVICE r4: a
    # change confined to one tile would rest on a single 32-bit CRC) —
    # 64-bit tile hashes must match on both sides.
    t1 = te(tile_rows=2, tile_checksums=["crc32c:01", "crc32c:02"])
    t2 = te(tile_rows=2, tile_checksums=["crc32c:01", "crc32c:02"])
    assert not dedup_entries_match(t1, t2)
    t1.tile_dedup_hashes = ["xxh64:0a", "xxh64:0b"]
    t2.tile_dedup_hashes = ["xxh64:0a", "xxh64:0b"]
    assert dedup_entries_match(t1, t2)
    # One side missing its hashes -> conservative rewrite.
    t2.tile_dedup_hashes = None
    assert not dedup_entries_match(t1, t2)
    t2.tile_dedup_hashes = ["xxh64:0a", "xxh64:0c"]
    assert not dedup_entries_match(t1, t2)


def test_dedup_chain_depth_100(tmp_path):
    """VERDICT r4 #8: the production resume-loop pattern is a LONG chain
    of increments. Chains collapse to the oldest base
    (snapshot.py dedup resolution), so at depth 100: the manifest must
    not grow with depth, every increment writes only the changed leaf,
    and the tip restores bit-exact with all references resolving
    through ONE hop (no chain walk)."""
    frozen = np.arange(256 * 1024, dtype=np.float32).reshape(512, 512)
    hot = np.zeros(512, np.float32)
    base = str(tmp_path / "s000")
    with override_batching_disabled(True), override_record_dedup_hashes(True):
        Snapshot.take(base, {"app": StateDict(frozen=frozen, hot=hot)})
    meta_sizes = []
    prev = base
    with override_batching_disabled(True):
        for d in range(1, 101):
            hot = hot + 1.0
            path = str(tmp_path / f"s{d:03d}")
            Snapshot.take(
                path,
                {"app": StateDict(frozen=frozen, hot=hot)},
                incremental_from=prev,
            )
            meta_sizes.append(
                os.path.getsize(os.path.join(path, ".snapshot_metadata"))
            )
            # Only the changed leaf wrote (hot is small and tile-less).
            blobs = _blob_files(path)
            assert len(blobs) == 1, (d, blobs)
            prev = path

    # Manifest size is depth-INDEPENDENT (collapse to oldest base): the
    # deepest manifest is within a few % of the shallowest.
    assert max(meta_sizes) <= int(min(meta_sizes) * 1.05) + 64, (
        min(meta_sizes),
        max(meta_sizes),
    )
    # Every frozen reference in the tip points at the BASE snapshot
    # (one hop), not at increment 99.
    tip = Snapshot(prev)
    e = tip.metadata.manifest["0/app/frozen"]
    loc = getattr(e, "location", None) or e.chunks[0].tensor.location
    assert "s000" in loc, loc
    # Tip restores bit-exact and scrubs clean.
    target = {
        "app": StateDict(
            frozen=np.zeros_like(frozen), hot=np.zeros(512, np.float32)
        )
    }
    tip.restore(target)
    assert np.array_equal(target["app"]["frozen"], frozen)
    assert np.array_equal(target["app"]["hot"], np.full(512, 100.0, np.float32))
    assert verify_snapshot(prev).clean
