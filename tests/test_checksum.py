"""Per-blob CRC32C integrity checksums (beyond the reference, which has
no end-to-end integrity checking): recorded at stage time into the
manifest, verified on read; a flipped bit in storage must fail the
restore naming the corrupted blob.
"""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusnap import Snapshot, StateDict
from tpusnap._native import ChecksumError
from tpusnap.knobs import (
    override_checksum_disabled,
    override_max_chunk_size_bytes,
    override_slab_size_threshold_bytes,
)


def _corrupt_one_byte(snap_dir: str, name_fragment: str, offset: int = 100) -> str:
    """Flip one byte in the first blob file matching the fragment."""
    for f in sorted(glob.glob(f"{snap_dir}/**/*", recursive=True)):
        if os.path.isfile(f) and name_fragment in f and not f.endswith(".snapshot_metadata"):
            with open(f, "r+b") as fh:
                fh.seek(offset)
                b = fh.read(1)
                fh.seek(offset)
                fh.write(bytes([b[0] ^ 0xFF]))
            return f
    raise AssertionError(f"no blob matching {name_fragment!r} in {snap_dir}")


def test_checksums_recorded_in_manifest(tmp_path):
    arr = np.arange(4096, dtype=np.float32)
    # A set is not flattenable, so it persists as a pickled ObjectEntry.
    Snapshot.take(str(tmp_path / "s"), {"m": StateDict(w=arr, meta={1, 2, 3})})
    manifest = Snapshot(str(tmp_path / "s")).get_manifest()
    tensor_entry = manifest["0/m/w"]
    assert tensor_entry.checksum is not None
    algo, _, value = tensor_entry.checksum.partition(":")
    assert algo in ("crc32c", "zlib-crc32") and len(value) == 8
    obj_entry = manifest["0/m/meta"]
    assert obj_entry.checksum is not None


def test_corrupt_tensor_fails_restore_naming_path(tmp_path):
    arr = np.random.default_rng(0).standard_normal(100_000).astype(np.float32)
    Snapshot.take(str(tmp_path / "s"), {"m": StateDict(w=arr)})
    _corrupt_one_byte(str(tmp_path / "s"), "w")
    target = {"m": StateDict(w=np.zeros_like(arr))}
    with pytest.raises(ChecksumError, match="m/w"):
        Snapshot(str(tmp_path / "s")).restore(target)


def test_corrupt_object_fails_restore(tmp_path):
    # A set pickles as one ObjectEntry blob (dicts flatten into containers
    # whose string leaves are inlined primitives with no blob to corrupt).
    Snapshot.take(
        str(tmp_path / "s"), {"m": StateDict(cfg={"x" * 4000, "y"})}
    )
    _corrupt_one_byte(str(tmp_path / "s"), "cfg")
    target = {"m": StateDict(cfg=None)}
    with pytest.raises(ChecksumError, match="cfg"):
        Snapshot(str(tmp_path / "s")).restore(target)


def test_corrupt_slab_member_fails_read_object(tmp_path):
    """Batched (slab-resident) members carry member-grain checksums."""
    arrs = {f"w{i}": np.full(2048, float(i), dtype=np.float32) for i in range(4)}
    with override_slab_size_threshold_bytes(1 << 20):
        Snapshot.take(str(tmp_path / "s"), {"m": StateDict(**arrs)})
    snap = Snapshot(str(tmp_path / "s"))
    entry = snap.get_manifest()["0/m/w2"]
    assert entry.byte_range is not None, "state was not slab-batched"
    assert entry.checksum is not None
    # Corrupt one byte INSIDE w2's byte range of the slab.
    for f in glob.glob(f"{tmp_path}/s/batched/*"):
        with open(f, "r+b") as fh:
            fh.seek(entry.byte_range[0] + 8)
            b = fh.read(1)
            fh.seek(entry.byte_range[0] + 8)
            fh.write(bytes([b[0] ^ 0xFF]))
        break
    else:
        raise AssertionError("no slab file found")
    with pytest.raises(ChecksumError, match="w2"):
        snap.read_object("0/m/w2")
    # Untouched member still reads fine.
    out = snap.read_object("0/m/w1")
    assert np.array_equal(out, arrs["w1"])


def test_corrupt_chunk_fails_restore(tmp_path):
    arr = np.random.default_rng(1).standard_normal((64, 1024)).astype(np.float32)
    with override_max_chunk_size_bytes(64 * 1024):
        Snapshot.take(str(tmp_path / "s"), {"m": StateDict(w=arr)})
    manifest = Snapshot(str(tmp_path / "s")).get_manifest()
    entry = manifest["0/m/w"]
    assert entry.type == "ChunkedTensor" and len(entry.chunks) > 1
    assert all(c.tensor.checksum for c in entry.chunks)
    _corrupt_one_byte(str(tmp_path / "s"), entry.chunks[1].tensor.location.rsplit("/", 1)[-1])
    target = {"m": StateDict(w=np.zeros_like(arr))}
    with pytest.raises(ChecksumError):
        Snapshot(str(tmp_path / "s")).restore(target)


def test_corrupt_shard_fails_sharded_restore(tmp_path):
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]).reshape(4), ("dp",))
    sharding = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("dp"))
    arr = jax.device_put(jnp.arange(32768, dtype=jnp.float32), sharding)
    Snapshot.take(str(tmp_path / "s"), {"m": StateDict(w=arr)})
    manifest = Snapshot(str(tmp_path / "s")).get_manifest()
    entry = manifest["0/m/w"]
    assert all(s.tensor.checksum for s in entry.shards)
    _corrupt_one_byte(str(tmp_path / "s"), "w.8192")
    target = {"m": StateDict(w=jax.device_put(jnp.zeros(32768, jnp.float32), sharding))}
    with pytest.raises(ChecksumError, match="8192"):
        Snapshot(str(tmp_path / "s")).restore(target)


def test_checksum_knob_disables_both_sides(tmp_path):
    arr = np.arange(50_000, dtype=np.float32)
    with override_checksum_disabled(True):
        Snapshot.take(str(tmp_path / "s"), {"m": StateDict(w=arr)})
        manifest = Snapshot(str(tmp_path / "s")).get_manifest()
        assert manifest["0/m/w"].checksum is None
    # Snapshot taken WITH checksums, corrupted, read with verification off:
    Snapshot.take(str(tmp_path / "s2"), {"m": StateDict(w=arr)})
    _corrupt_one_byte(str(tmp_path / "s2"), "w")
    with override_checksum_disabled(True):
        target = {"m": StateDict(w=np.zeros_like(arr))}
        Snapshot(str(tmp_path / "s2")).restore(target)  # no raise
        assert not np.array_equal(target["m"]["w"], arr)


def test_budget_tiled_read_verifies_tiles(tmp_path):
    """Memory-budgeted partial reads verify against tile-grain checksums
    (combined per read range) — the huge-tensor-under-budget path must
    detect corruption, not restore it silently."""
    from tpusnap.knobs import _override_env

    # Shrink the checksum tile so a small test blob records many tiles.
    with _override_env("TPUSNAP_TILE_CHECKSUM_BYTES", str(64 * 1024)):
        arr = np.random.default_rng(2).integers(
            0, 2**16, (256, 4096), dtype=np.uint16
        )
        Snapshot.take(str(tmp_path / "s"), {"m": StateDict(w=arr)})
        snap = Snapshot(str(tmp_path / "s"))
        entry = snap.get_manifest()["0/m/w"]
        assert entry.tile_checksums and entry.tile_rows
        assert len(entry.tile_checksums) == -(-256 // entry.tile_rows)

        # Clean read under budget succeeds and round-trips.
        out = snap.read_object("0/m/w", memory_budget_bytes=256 * 1024)
        assert np.array_equal(out, arr)

        # Corrupt one byte deep inside the blob; a budget-tiled read must
        # fail loudly naming the rows.
        _corrupt_one_byte(str(tmp_path / "s"), "w", offset=arr.nbytes // 2)
        fresh = Snapshot(str(tmp_path / "s"))
        with pytest.raises(ChecksumError, match="rows"):
            fresh.read_object("0/m/w", memory_budget_bytes=256 * 1024)
        # The whole-blob (combined) checksum catches it on full reads too.
        with pytest.raises(ChecksumError, match="m/w"):
            fresh.read_object("0/m/w")
        # The kill-switch disables tile verification too (salvaging a
        # corrupt checkpoint must work through the budget path).
        with override_checksum_disabled(True):
            out = Snapshot(str(tmp_path / "s")).read_object(
                "0/m/w", memory_budget_bytes=256 * 1024
            )
            assert not np.array_equal(out, arr)


def test_tile_checksums_combine_to_whole(tmp_path):
    """The recorded whole-blob checksum equals the direct hash of the
    bytes even when derived by CRC combine from tile values."""
    from tpusnap import _native
    from tpusnap.knobs import _override_env

    with _override_env("TPUSNAP_TILE_CHECKSUM_BYTES", str(64 * 1024)):
        arr = np.random.default_rng(7).integers(
            0, 255, 300 * 1024, dtype=np.uint8
        ).reshape(300, 1024)
        Snapshot.take(str(tmp_path / "s"), {"m": StateDict(w=arr)})
    entry = Snapshot(str(tmp_path / "s")).get_manifest()["0/m/w"]
    assert entry.tile_checksums
    algo, _, value = entry.checksum.partition(":")
    assert int(value, 16) == (_native.crc32c(arr.tobytes()) & 0xFFFFFFFF)


def test_async_take_fused_clone_checksums_match_sync(tmp_path):
    """The async path records checksums inside the defensive-clone pass
    (_native.memcpy_crc_tiles); the values (incl. tile grain) must be
    byte-identical to the sync path's hash pass, and the snapshot must
    scrub clean."""
    import numpy as np

    from tpusnap import Snapshot, StateDict, verify_snapshot
    from tpusnap.knobs import (
        override_batching_disabled,
        override_tile_checksum_bytes,
    )

    rng = np.random.default_rng(7)
    state = {
        "big": rng.standard_normal((2048, 64)).astype(np.float32),
        "small": rng.standard_normal(32).astype(np.float32),
    }
    with override_tile_checksum_bytes(128 * 1024), override_batching_disabled(
        True
    ):
        sync_path = str(tmp_path / "sync")
        Snapshot.take(sync_path, {"app": StateDict(**state)})
        async_path = str(tmp_path / "async")
        Snapshot.async_take(async_path, {"app": StateDict(**state)}).wait()

    sm = Snapshot(sync_path).get_manifest()
    am = Snapshot(async_path).get_manifest()
    assert set(sm) == set(am)
    checked = 0
    for p, se in sm.items():
        ae = am[p]
        for field in ("checksum", "tile_rows", "tile_checksums"):
            if hasattr(se, field):
                assert getattr(se, field) == getattr(ae, field), (p, field)
                checked += 1
    assert checked > 0
    big_entry = am["0/app/big"]
    assert big_entry.tile_checksums and len(big_entry.tile_checksums) > 1
    assert verify_snapshot(async_path).clean


class TestXxh64Native:
    """The 64-bit dedup hash: native XXH64 against published vectors,
    fused tile passes against one-shot recomputation, and the fallback's
    distinct algorithm tag."""

    VECTORS = [  # (input, seed-0 XXH64) — from the xxHash reference
        (b"", 0xEF46DB3751D8E999),
        (b"a", 0xD24EC4F1A98C6E5B),
        (b"abc", 0x44BC2CF5AD770999),
    ]

    def test_known_vectors(self):
        from tpusnap import _native

        if not _native.available():
            import pytest

            pytest.skip("native helper unavailable")
        for data, expect in self.VECTORS:
            assert _native.xxh64(data) == expect, data

    def test_fused_tiles_match_one_shot(self):
        import numpy as np

        from tpusnap import _native

        buf = np.random.default_rng(0).integers(
            0, 255, 5_000_001, dtype=np.uint8
        )  # odd length: exercises sub-stripe tails
        tile = 1 << 20
        crcs, xxhs = _native.crc_xxh_tiles(buf, tile)
        dst = np.empty_like(buf)
        crcs2, xxhs2 = _native.memcpy_crc_xxh_tiles(dst, buf, tile)
        assert list(crcs) == list(crcs2) and list(xxhs) == list(xxhs2)
        assert np.array_equal(dst, buf)
        for i in range(len(xxhs)):
            sub = buf[i * tile : min((i + 1) * tile, buf.nbytes)]
            assert _native.crc32c(sub) == crcs[i]
            assert _native.xxh64(sub) == xxhs[i]

    def test_algorithm_tag_matches_build(self):
        from tpusnap import _native
        from tpusnap.knobs import _override_env

        s = _native.dedup_hash_string(b"hello")
        algo, _, val = s.partition(":")
        assert algo == _native.dedup_hash_algorithm()
        assert len(val) == 16 and int(val, 16) >= 0


def test_dedup_hashes_sync_async_parity(tmp_path):
    """Incremental-capable manifests must be byte-identical between the
    sync hash pass and the async fused clone+hash pass — including the
    new dedup_hash / tile_dedup_hashes fields."""
    import numpy as np

    from tpusnap import Snapshot, StateDict, verify_snapshot
    from tpusnap.knobs import (
        override_batching_disabled,
        override_record_dedup_hashes,
        override_tile_checksum_bytes,
    )

    rng = np.random.default_rng(11)
    state = {
        "big": rng.standard_normal((2048, 32)).astype(np.float32),
        "small": rng.standard_normal(64).astype(np.float32),
    }
    with override_batching_disabled(True), override_tile_checksum_bytes(
        16 * 1024
    ), override_record_dedup_hashes(True):
        sync_path = str(tmp_path / "sync")
        Snapshot.take(sync_path, {"app": StateDict(**state)})
        async_path = str(tmp_path / "async")
        Snapshot.async_take(async_path, {"app": StateDict(**state)}).wait()
    sm = Snapshot(sync_path).get_manifest()
    am = Snapshot(async_path).get_manifest()
    checked = 0
    for p, se in sm.items():
        ae = am[p]
        for field in ("checksum", "tile_rows", "tile_checksums",
                      "dedup_hash", "tile_dedup_hashes"):
            if hasattr(se, field):
                assert getattr(se, field) == getattr(ae, field), (p, field)
                checked += 1
    assert checked > 0
    assert sm["0/app/big"].tile_dedup_hashes
    assert sm["0/app/small"].dedup_hash
    assert verify_snapshot(async_path).clean
