"""Flight recorder (tpusnap.flight) + ``tpusnap timeline`` tests.

Unit level: ring bounding and eviction accounting, flush throttle and
atomicity, the JSONL reader, barrier-anchored skew estimation and the
post-mortem verdict on synthetic logs (pure math, no sleeps). System
level: a take persists the sidecar inside the snapshot AND the local
TPUSNAP_TELEMETRY_DIR copy, fsck treats it as a legitimate sidecar, the
knob disables the whole layer, an aborted take leaves its forensic
breadcrumb without locking the path, a SIGKILLed take's surviving
sidecar names the in-flight op and last phase, and the CLI honors the
exit contract (0 committed / 4 uncommitted post-mortem / 3 no data).
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from tpusnap import Snapshot, StateDict
from tpusnap import flight
from tpusnap.flight import (
    FlightRecorder,
    estimate_skew,
    load_flight_logs,
    merge_timeline,
    parse_flight_log,
    postmortem_verdict,
)
from tpusnap.io_types import FLIGHT_DIR
from tpusnap.knobs import (
    override_flight_enabled,
    override_flight_flush_interval_s,
    override_telemetry_dir,
)


def _state(seed=0, n=6):
    return {
        f"w{i}": np.random.default_rng(seed * 100 + i)
        .standard_normal((128, 128))
        .astype(np.float32)
        for i in range(n)
    }


# ------------------------------------------------------------- unit: ring


def test_ring_bounded_and_eviction_counted(tmp_path):
    rec = FlightRecorder(ring_size=8)
    for i in range(20):
        rec.record("ev", op=f"e{i}")
    rec._sidecar_dir = str(tmp_path / "flight")
    assert rec.maybe_flush(force=True)
    doc = parse_flight_log((tmp_path / "flight" / "rank_0.jsonl").read_text())
    assert doc["meta"]["events_total"] == 20
    assert doc["meta"]["dropped"] == 12
    assert [e["op"] for e in doc["events"]] == [f"e{i}" for i in range(12, 20)]


def test_disabled_recorder_records_nothing(tmp_path):
    with override_flight_enabled(False):
        rec = FlightRecorder(ring_size=8)
        rec.record("ev")
        assert rec.events_total == 0
        rec._sidecar_dir = str(tmp_path / "flight")
        assert not rec.maybe_flush(force=True)


def test_flush_throttle_and_force(tmp_path):
    with override_flight_flush_interval_s(3600.0):
        rec = FlightRecorder(ring_size=8)
        rec._flush_interval_s = 3600.0
        rec._sidecar_dir = str(tmp_path / "flight")
        rec.record("a")
        assert rec.maybe_flush()  # first flush always lands
        rec.record("b")
        assert not rec.maybe_flush()  # throttled
        assert rec.maybe_flush(force=True)
        assert rec.flushes == 2


def test_flush_is_atomic_and_reparsable(tmp_path):
    rec = FlightRecorder(ring_size=64)
    rec.record("x", op="y", detail_key=3)
    rec.set_context({"phase": "stage", "op": "storage_write"})
    rec._sidecar_dir = str(tmp_path / "flight")
    rec.maybe_flush(force=True)
    names = os.listdir(tmp_path / "flight")
    assert names == ["rank_0.jsonl"]  # no .tmp debris
    doc = parse_flight_log((tmp_path / "flight" / "rank_0.jsonl").read_text())
    assert doc["meta"]["context"]["phase"] == "stage"
    (ev,) = [e for e in doc["events"] if e["k"] == "x"]
    assert ev["op"] == "y" and ev["detail_key"] == 3
    # Wall mapping: anchors present and self-consistent.
    assert doc["meta"]["wall_anchor"] > 0
    assert doc["meta"]["mono_anchor"] <= ev["t"]


def test_parse_tolerates_garbage_lines():
    text = '{"k":"meta","rank":1}\nnot json\n[]\n{"t":1.0,"k":"ev"}\n'
    doc = parse_flight_log(text)
    assert doc["meta"]["rank"] == 1
    assert len(doc["events"]) == 1
    assert parse_flight_log("") is None


# ------------------------------------------------- unit: skew + timeline


def _mk_log(rank, wall_anchor, events, context=None, world_size=2):
    return {
        "meta": {
            "rank": rank,
            "wall_anchor": wall_anchor,
            "mono_anchor": 0.0,
            "world_size": world_size,
            "flush_mono": max((e["t"] for e in events), default=0.0),
            "context": context or {},
            "take_id": "deadbeef",
        },
        "events": events,
    }


def test_skew_estimated_from_shared_barrier_anchors():
    # Rank 1's wall clock runs 5 s ahead; both ranks saw two barrier
    # releases at the same true instants.
    logs = {
        0: _mk_log(0, 1000.0, [
            {"t": 1.0, "k": "barrier_exit", "op": "ns/b1"},
            {"t": 2.0, "k": "barrier_exit", "op": "ns/b2"},
            {"t": 2.5, "k": "op_begin", "op": "storage_write"},
        ]),
        1: _mk_log(1, 1005.0, [
            {"t": 1.0, "k": "barrier_exit", "op": "ns/b1"},
            {"t": 2.0, "k": "barrier_exit", "op": "ns/b2"},
            {"t": 1.5, "k": "op_begin", "op": "dtoh"},
        ]),
    }
    skew = estimate_skew(logs)
    assert skew[0]["anchors"] is None  # the reference rank
    assert skew[1]["anchors"] == 2
    assert skew[1]["offset_s"] == pytest.approx(-5.0)
    assert skew[1]["bound_s"] == pytest.approx(0.0)
    merged = merge_timeline(logs, skew)
    # After alignment rank 1's dtoh (true t=1.5) sorts between the two
    # barrier releases despite its +5 s wall clock.
    kinds = [(e["rank"], e["op"]) for e in merged]
    assert kinds.index((1, "dtoh")) < kinds.index((0, "ns/b2"))
    assert kinds.index((0, "ns/b1")) < kinds.index((1, "dtoh"))


def test_skew_bound_reflects_anchor_jitter():
    logs = {
        0: _mk_log(0, 1000.0, [
            {"t": 1.0, "k": "barrier_exit", "op": "b1"},
            {"t": 2.0, "k": "barrier_exit", "op": "b2"},
            {"t": 3.0, "k": "barrier_exit", "op": "b3"},
        ]),
        1: _mk_log(1, 1000.0, [
            {"t": 1.0, "k": "barrier_exit", "op": "b1"},
            {"t": 2.04, "k": "barrier_exit", "op": "b2"},
            {"t": 2.96, "k": "barrier_exit", "op": "b3"},
        ]),
    }
    skew = estimate_skew(logs)
    assert abs(skew[1]["offset_s"]) <= 0.04
    assert 0.03 <= skew[1]["bound_s"] <= 0.09


def test_skew_without_shared_anchors_is_zero_offset():
    logs = {
        0: _mk_log(0, 1000.0, [{"t": 1.0, "k": "phase", "op": "plan"}]),
        1: _mk_log(1, 1003.0, [{"t": 1.0, "k": "phase", "op": "plan"}]),
    }
    skew = estimate_skew(logs)
    assert skew[1] == {"offset_s": 0.0, "bound_s": None, "anchors": 0}


def test_postmortem_verdict_fields_and_missing_ranks():
    logs = {
        0: _mk_log(
            0,
            1000.0,
            [
                {"t": 1.0, "k": "op_begin", "op": "storage_write"},
                {"t": 1.2, "k": "stall", "op": "storage_write"},
            ],
            context={
                "phase": "stage",
                "op": "storage_write",
                "ops": ["storage_write", "dtoh"],
                "bytes_planned": 100,
                "bytes_written": 25,
                "bytes_staged": 50,
                "percent": 25.0,
            },
            world_size=3,
        )
    }
    v = postmortem_verdict(
        "/p", "torn", logs, journal_evidence={0: {"blobs_completed": 2,
                                                 "bytes_completed": 25}}
    )
    assert v["world_size"] == 3
    assert v["missing_ranks"] == [1, 2]
    r = v["ranks"][0]
    assert r["phase"] == "stage"
    assert r["inflight_op"] == "storage_write"
    assert r["bytes_written"] == 25 and r["bytes_planned"] == 100
    assert r["journal"]["blobs_completed"] == 2
    assert r["stall_episodes"] == 1
    assert v["stall_episodes"] == 1
    assert r["last_event"]["k"] == "stall"
    assert r["last_event"]["flush_age_s"] == pytest.approx(0.0)


# --------------------------------------------------------------- system


def _timeline(path, *extra):
    return subprocess.run(
        [sys.executable, "-m", "tpusnap", "timeline", path, *extra],
        capture_output=True,
        text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )


def test_take_persists_flight_sidecar_and_local_copy(tmp_path):
    path = str(tmp_path / "snap")
    tdir = str(tmp_path / "tele")
    with override_telemetry_dir(tdir):
        Snapshot.take(path, {"app": StateDict(**_state())})
        sidecar = os.path.join(path, FLIGHT_DIR, "rank_0.jsonl")
        assert os.path.exists(sidecar)
        doc = parse_flight_log(open(sidecar).read())
        kinds = {e["k"] for e in doc["events"]}
        # Span open/close, phase transitions, journal evidence and the
        # terminal event are all on the record.
        assert {"phase", "op_begin", "op_end", "take_end"} <= kinds
        assert doc["meta"]["context"]["state"] == "committed"
        # The local copy exists and holds the same take.
        copy_dir = flight.local_flight_dir(path)
        assert os.path.exists(os.path.join(copy_dir, "rank_0.jsonl"))
    # fsck: the sidecar is legitimate — committed, no orphans.
    from tpusnap.lifecycle import fsck_snapshot

    report = fsck_snapshot(path)
    assert report.state == "committed"
    assert not report.orphans


def test_flight_knob_off_leaves_no_sidecar(tmp_path):
    path = str(tmp_path / "snap")
    with override_flight_enabled(False):
        Snapshot.take(path, {"app": StateDict(**_state())})
    assert not os.path.exists(os.path.join(path, FLIGHT_DIR))


def test_timeline_cli_committed_exit0(tmp_path):
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"app": StateDict(**_state())})
    r = _timeline(path)
    assert r.returncode == 0, r.stderr
    assert "state:  committed" in r.stdout
    assert "op_begin" in r.stdout
    # --json is machine-parseable and carries the same events.
    rj = _timeline(path, "--json", "--last", "5")
    assert rj.returncode == 0
    doc = json.loads(rj.stdout)
    assert doc["state"] == "committed" and len(doc["events"]) == 5
    # --rank filters display (single-rank: everything stays).
    rr = _timeline(path, "--rank", "0", "--last", "3")
    assert rr.returncode == 0


def test_timeline_filters_stale_sidecars_from_previous_take(tmp_path):
    """A retake overwrites only the ranks it runs: sidecars left by a
    WIDER previous take to the same path must not merge into the
    current take's timeline (their recurring barrier anchor strings
    would also poison the skew estimate)."""
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"app": StateDict(**_state())})
    stale = (
        json.dumps(
            {
                "k": "meta",
                "v": 1,
                "rank": 3,
                "take_id": "00000000previous0000000000000000",
                "world_size": 4,
                "wall_anchor": 1.0,
                "mono_anchor": 0.0,
                "context": {"state": "running"},
            }
        )
        + "\n"
        + json.dumps({"t": 1.0, "k": "phase", "op": "plan"})
        + "\n"
    )
    with open(os.path.join(path, FLIGHT_DIR, "rank_3.jsonl"), "w") as f:
        f.write(stale)
    r = _timeline(path, "--json")
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    assert doc["ranks"] == [0], doc["ranks"]


def test_timeline_cli_no_flight_data_exit3(tmp_path):
    r = _timeline(str(tmp_path))
    assert r.returncode == 3
    assert "no flight data" in r.stderr


def test_aborted_take_leaves_breadcrumb_path_stays_reusable(
    tmp_path, monkeypatch
):
    import tpusnap.storage_plugins.fs as fs_mod
    from tpusnap.lifecycle import fsck_snapshot

    path = str(tmp_path / "snap")
    orig_write = fs_mod.FSStoragePlugin.write

    async def bad_write(self, write_io):
        raise RuntimeError("injected fatal write")

    monkeypatch.setattr(fs_mod.FSStoragePlugin, "write", bad_write)
    with pytest.raises(RuntimeError, match="injected fatal write"):
        Snapshot.take(path, {"app": StateDict(**_state())})
    monkeypatch.setattr(fs_mod.FSStoragePlugin, "write", orig_write)
    # The abort cleaned blobs + journal but left the black box: the
    # path classifies empty (reusable), and the breadcrumb names the
    # aborted state.
    report = fsck_snapshot(path)
    assert report.state == "empty", report.summary()
    sidecar = os.path.join(path, FLIGHT_DIR, "rank_0.jsonl")
    assert os.path.exists(sidecar)
    doc = parse_flight_log(open(sidecar).read())
    assert doc["meta"]["context"]["state"] == "aborted"
    assert any(e["k"] == "abort" for e in doc["events"])
    # timeline reports the post-mortem for the uncommitted path.
    r = _timeline(path)
    assert r.returncode == 4
    assert "POST-MORTEM" in r.stdout and "state=aborted" in r.stdout
    # Path stays reusable.
    Snapshot.take(path, {"app": StateDict(**_state())})
    assert fsck_snapshot(path).state == "committed"


_KILL_CHILD = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from tpusnap import Snapshot, StateDict

path = sys.argv[1]
os.environ["TPUSNAP_DISABLE_BATCHING"] = "1"
# Tight flush cadence (the loss bound under test) + slowed writes so the
# kill lands with storage_write provably in flight at the last flush.
os.environ["TPUSNAP_HEARTBEAT_INTERVAL_S"] = "0.05"
os.environ["TPUSNAP_FAULT_SPEC"] = "latency_ms=400,crash_after_op=write:4"
state = {
    f"w{i}": np.random.default_rng(i).standard_normal((128, 128))
    .astype(np.float32)
    for i in range(8)
}
Snapshot.take("chaos+fs://" + path, {"app": StateDict(**state)})
print("UNEXPECTED_COMPLETION", flush=True)
"""


@pytest.mark.soak
def test_sigkill_mid_take_timeline_names_inflight_op(tmp_path):
    path = str(tmp_path / "snap")
    r = subprocess.run(
        [sys.executable, "-c", _KILL_CHILD, path],
        capture_output=True,
        text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        timeout=150,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert r.returncode == -signal.SIGKILL, r.stdout[-2000:]
    t = _timeline(path, "--json")
    assert t.returncode == 4, (t.returncode, t.stderr)
    doc = json.loads(t.stdout)
    assert doc["state"] == "torn"
    verdict = doc["verdict"]
    r0 = verdict["ranks"]["0"]
    # The surviving sidecar names what rank 0 was doing when it died:
    # a completed phase and the op(s) in flight at the last flush.
    assert r0["phase"] is not None
    assert r0["inflight_op"] == "storage_write" or (
        r0["inflight_ops"] and "storage_write" in r0["inflight_ops"]
    ), r0
    assert r0["bytes_planned"] > 0
    assert r0["bytes_written"] > 0  # flushed context saw real progress
    # journal.d evidence channel is wired (the count itself races the
    # kill: record flushes are coalesced and draw the same injected
    # latency as the blob writes they witness).
    assert "journal" in r0, r0
    assert verdict["missing_ranks"] == []
    # analyze folds the same verdict on a torn path.
    a = subprocess.run(
        [sys.executable, "-m", "tpusnap", "analyze", path],
        capture_output=True,
        text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert a.returncode == 4
    assert "POST-MORTEM" in a.stdout and "storage_write" in a.stdout


def _world_flight_take(snap_dir):
    """2-rank take: both ranks' flight logs land, share barrier anchors,
    and the merged timeline covers both."""
    import numpy as np

    from tpusnap import Snapshot, StateDict
    from tpusnap.comm import get_communicator
    from tpusnap.flight import estimate_skew, load_flight_logs

    comm = get_communicator()
    state = {
        f"w{i}": np.full((2048,), float(i), np.float32) for i in range(4)
    }
    Snapshot.take(snap_dir, {"app": StateDict(**state)})
    comm.barrier()
    if comm.rank == 0:
        logs = load_flight_logs(snap_dir)
        assert sorted(logs) == [0, 1], sorted(logs)
        skew = estimate_skew(logs)
        assert skew[1]["anchors"] and skew[1]["anchors"] >= 1, skew
        assert skew[1]["bound_s"] is not None
        print(f"FLIGHT_OK anchors={skew[1]['anchors']}", flush=True)


@pytest.mark.distributed
def test_two_rank_flight_logs_share_barrier_anchors(tmp_path):
    from tpusnap.test_utils import run_subprocess_world

    outs = run_subprocess_world(
        _world_flight_take,
        world_size=2,
        args=[str(tmp_path / "snap")],
        timeout=150,
    )
    assert any("FLIGHT_OK" in o for o in outs), outs
