"""Property-based tests (hypothesis) for the pure data-model layers.

The reference's unit tests enumerate hand-picked hostile cases
(tests/test_flatten.py's %-and-/ keys, test_manifest.py's fixtures);
these generate them: arbitrary nested state round-trips through
flatten/inflate, arbitrary entries through the manifest serialization,
arbitrary floats through the bit-exact primitive encoding, and the
CRC-combine identity over arbitrary byte splits.
"""

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from tpusnap.flatten import flatten, inflate
from tpusnap.manifest import (
    PrimitiveEntry,
    SnapshotMetadata,
    TensorEntry,
    entry_from_dict,
    _entry_to_dict,
    is_container_entry,
)

SETTINGS = settings(max_examples=80, deadline=None)

# Keys exercise the %/escaping and the str/int dichotomy; values cover
# every primitive class plus nesting.
_keys = st.one_of(
    st.text(
        alphabet=st.sampled_from("ab%/_.0 é"), min_size=1, max_size=8
    ),
    st.integers(min_value=0, max_value=99),
)
_primitives = st.one_of(
    st.integers(min_value=-(2**62), max_value=2**62),
    st.floats(allow_nan=False),  # NaN breaks dict-equality comparison only
    st.booleans(),
    st.text(max_size=12),
    st.binary(max_size=12),
)
_leaves = _primitives


def _unique_str_int_keys(d: dict) -> bool:
    # flatten refuses colliding str(int_key) == str_key pairs; generated
    # dicts must not rely on them.
    return len({str(k) for k in d}) == len(d)


_state = st.recursive(
    _leaves,
    lambda children: st.one_of(
        st.lists(children, max_size=4),
        st.dictionaries(_keys, children, max_size=4).filter(
            _unique_str_int_keys
        ),
        st.tuples(children, children),
    ),
    max_leaves=12,
)


@SETTINGS
@given(st.dictionaries(_keys, _state, min_size=1, max_size=4).filter(_unique_str_int_keys))
def test_flatten_inflate_roundtrip(state):
    manifest, flattened = flatten(state, prefix="app")
    # Every flattened path must be addressable and escaping reversible.
    rebuilt = inflate(
        {p: e for p, e in manifest.items() if is_container_entry(e)},
        flattened,
        prefix="app",
    )
    assert _norm(rebuilt) == _norm(state)


def _norm(obj):
    """Tuples inflate as tuples, lists as lists; normalize int-keyed dict
    keys like flatten does (both 1 and "1" address the same child)."""
    if isinstance(obj, dict):
        return {str(k): _norm(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_norm(v) for v in obj)
    return obj


@SETTINGS
@given(st.floats())  # incl. nan/inf/-0.0/subnormals
def test_primitive_float_bit_exact(x):
    entry = PrimitiveEntry.from_object(x)
    d = _entry_to_dict(entry)
    back = entry_from_dict(d).get_value()
    assert isinstance(back, float)
    # Bit-exact, not just ==: compare the IEEE-754 payloads.
    import struct

    assert struct.pack("<d", back) == struct.pack("<d", x)


@SETTINGS
@given(
    st.integers(min_value=-(2**62), max_value=2**62)
    | st.booleans()
    | st.text(max_size=20)
    | st.binary(max_size=20)
)
def test_primitive_roundtrip(x):
    entry = PrimitiveEntry.from_object(x)
    back = entry_from_dict(_entry_to_dict(entry)).get_value()
    assert type(back) is type(x) and back == x


@SETTINGS
@given(
    st.lists(
        st.tuples(
            st.text(
                alphabet=st.characters(
                    blacklist_categories=("Cs",), min_codepoint=32
                ),
                min_size=1,
                max_size=16,
            ),
            st.sampled_from(["float32", "bfloat16", "int8", "uint16"]),
            st.lists(st.integers(0, 7), max_size=3),
        ),
        min_size=1,
        max_size=5,
        unique_by=lambda t: t[0],
    )
)
def test_metadata_yaml_roundtrip(specs):
    manifest = {
        f"0/{name}": TensorEntry(
            location=f"0/{name}",
            serializer="buffer_protocol",
            dtype=dtype,
            shape=shape,
            replicated=False,
            checksum="crc32c:00000000",
        )
        for name, dtype, shape in specs
    }
    md = SnapshotMetadata(version="0.1.0", world_size=1, manifest=manifest)
    back = SnapshotMetadata.from_yaml(md.to_yaml())
    assert back.to_dict() == md.to_dict()


@SETTINGS
@given(st.binary(max_size=4096), st.binary(max_size=4096))
def test_crc_combine_identity(a, b):
    from tpusnap import _native

    assert _native.crc_combine(
        _native.crc32c(a), _native.crc32c(b), len(b)
    ) == _native.crc32c(a + b)


@SETTINGS
@given(
    st.binary(min_size=0, max_size=2048),
    st.integers(min_value=1, max_value=512),
)
def test_memcpy_crc_tiles_matches_direct(data, tile):
    from tpusnap import _native

    src = np.frombuffer(data, dtype=np.uint8).copy()
    dst = np.zeros_like(src)
    crcs = _native.memcpy_crc_tiles(dst, src, tile)
    assert bytes(dst) == data
    n = len(data)
    t = min(tile, n) if n else tile
    if n:
        expect = [
            _native.crc32c(data[i : min(i + t, n)]) for i in range(0, n, t)
        ]
        assert crcs == expect
    # Folding the tiles reproduces the whole-buffer value.
    combined = crcs[0]
    for i, c in enumerate(crcs[1:], 1):
        ln = min((i + 1) * t, n) - i * t
        combined = _native.crc_combine(combined, c, ln)
    assert combined == _native.crc32c(data)


@settings(max_examples=12, deadline=None)
@given(
    n_leaves=st.integers(min_value=2, max_value=5),
    mutate_mask=st.lists(st.booleans(), min_size=5, max_size=5),
    seed=st.integers(min_value=0, max_value=2**31),
)
def test_incremental_writes_exactly_the_changed_leaves(
    n_leaves, mutate_mask, seed
):
    """Property: an incremental take writes precisely the blobs whose
    content changed — no over-writing (dedup missed) and no
    under-writing (stale data referenced)."""
    import os
    import shutil
    import tempfile

    from tpusnap import Snapshot, StateDict, verify_snapshot
    from tpusnap.knobs import override_batching_disabled

    rng = np.random.default_rng(seed)
    state = {
        f"p{i}": rng.standard_normal((32, 16)).astype(np.float32)
        for i in range(n_leaves)
    }
    changed = {
        f"p{i}" for i in range(n_leaves) if mutate_mask[i % len(mutate_mask)]
    }
    root = tempfile.mkdtemp(prefix="tpusnap_prop_inc_")
    try:
        with override_batching_disabled(True):
            Snapshot.take(root + "/base", {"a": StateDict(**state)})
            state2 = {
                k: (v + 1.0 if k in changed else v.copy())
                for k, v in state.items()
            }
            Snapshot.take(
                root + "/inc",
                {"a": StateDict(**state2)},
                incremental_from=root + "/base",
            )
        written = {
            os.path.relpath(os.path.join(d, f), root + "/inc")
            for d, _, fs in os.walk(root + "/inc")
            for f in fs
            if f != ".snapshot_metadata" and ".tpusnap" not in d.split(os.sep)
        }
        assert written == {f"0/a/{k}" for k in sorted(changed)}
        assert verify_snapshot(root + "/inc").clean
        target = {
            "a": StateDict(**{k: np.zeros_like(v) for k, v in state2.items()})
        }
        Snapshot(root + "/inc").restore(target)
        for k, v in state2.items():
            assert np.array_equal(target["a"][k], v), k
    finally:
        shutil.rmtree(root, ignore_errors=True)
