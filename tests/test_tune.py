"""`tpusnap tune` auto-tuner tests: planner determinism on synthetic
history, cell pinning, verdict-driven rules, explicit-env-wins
precedence in the tuned-plan overlay, the CLI exit-3 contract on
insufficient history, the applied-plan ``tuned`` stamp in the restore
history event, and fake-clock unit checks for the probe's read lane.
"""

import asyncio
import json
import os

import numpy as np
import pytest

from tpusnap import PytreeState, Snapshot
from tpusnap import compress, knobs, telemetry
from tpusnap.__main__ import main
from tpusnap.history import history_path, load_history
from tpusnap.knobs import (
    override_autotune,
    override_probe,
    override_telemetry_dir,
)
from tpusnap.tune import MIN_EVENTS, build_plan, select_events

GiB = 1 << 30
MiB = 1 << 20


def _events(n, kind="restore", plugin="FSStoragePlugin", world_size=1,
            bytes_=GiB, wall_s=2.0, **extra):
    return [
        {"kind": kind, "plugin": plugin, "world_size": world_size,
         "bytes": bytes_, "wall_s": wall_s, **extra}
        for _ in range(n)
    ]


# ------------------------------------------------------------ planner


def test_build_plan_deterministic(monkeypatch):
    """Same history + same ceilings → byte-identical plan (same
    plan_id, same knob values), call after call — the property that
    lets `history --check` group runs by the plan they ran under."""
    monkeypatch.delenv("TPUSNAP_PROBE_INTERVAL_BYTES", raising=False)
    events = _events(5)
    plans = [
        build_plan(events, "restore", ceilings={}, codec_gbps=0.0)
        for _ in range(2)
    ]
    for plan in plans:
        assert plan.ok
        assert plan.backend == "FSStoragePlugin"
        assert plan.world_size == 1
        assert plan.n_events == 5
    # 1 GiB median payload, 2 GiB default cadence: the probe-interval
    # rule fires (≥2x off) and proposes 1/8th of the payload.
    knob_envs = {k.env: k.value for k in plans[0].knobs}
    assert knob_envs == {"TPUSNAP_PROBE_INTERVAL_BYTES": str(GiB // 8)}
    assert plans[0].plan_id == plans[1].plan_id
    assert [k.to_json() for k in plans[0].knobs] == [
        k.to_json() for k in plans[1].knobs
    ]


def test_insufficient_history_not_ok():
    plan = build_plan(_events(MIN_EVENTS - 1), "restore", ceilings={},
                      codec_gbps=0.0)
    assert not plan.ok
    assert plan.plan_id is None
    assert plan.n_events == MIN_EVENTS - 1
    assert f"need {MIN_EVENTS}" in plan.reason
    assert "TPUSNAP_PROBE=1" in plan.reason


def test_cell_pins_to_newest_backend():
    """With no --backend, the cell pins to the NEWEST event's backend
    and drops other tiers — medians must never mix tiers."""
    events = _events(4, plugin="S3StoragePlugin") + _events(3)
    plan = build_plan(events, "restore", ceilings={}, codec_gbps=0.0)
    assert plan.backend == "FSStoragePlugin"
    assert plan.n_events == 3
    cell = select_events(events, "restore", backend="S3StoragePlugin")
    assert len(cell) == 4


def test_decode_verdict_flips_compression(monkeypatch):
    """analyze verdict 'decode' → the plan pins TPUSNAP_COMPRESS=off
    for this tier (the read pipe outruns the decompressor)."""
    monkeypatch.delenv("TPUSNAP_COMPRESS", raising=False)
    plan = build_plan(_events(3, bytes_=8 * GiB), "restore", ceilings={},
                      verdict="decode", codec_gbps=0.0)
    assert plan.ok
    by_env = {k.env: k for k in plan.knobs}
    assert by_env["TPUSNAP_COMPRESS"].value == "off"
    assert "decode" in by_env["TPUSNAP_COMPRESS"].rationale
    # And the verdict-free plan for the same cell proposes no flip.
    plain = build_plan(_events(3, bytes_=8 * GiB), "restore", ceilings={},
                       codec_gbps=0.0)
    assert "TPUSNAP_COMPRESS" not in {k.env for k in plain.knobs}
    assert plain.plan_id != plan.plan_id


# ------------------------------------------- explicit-env-wins overlay


def test_tuned_overlay_env_always_wins(monkeypatch):
    """apply_tuned_plan skips knobs the environment sets explicitly,
    and _env_get resolves env → overlay → default, so an operator's
    `export` beats the tuner per lookup."""
    monkeypatch.setenv("TPUSNAP_PROBE_INTERVAL_BYTES", "123")
    monkeypatch.delenv("TPUSNAP_STAGE_THREADS", raising=False)
    try:
        applied = knobs.apply_tuned_plan(
            "deadbeef0123",
            {"TPUSNAP_PROBE_INTERVAL_BYTES": "999",
             "TPUSNAP_STAGE_THREADS": "8"},
        )
        # Only the env-free knob landed; that subset is what the
        # history event's `tuned.knobs` stamp records.
        assert applied == {"TPUSNAP_STAGE_THREADS": "8"}
        assert knobs.tuned_plan() == {
            "plan_id": "deadbeef0123",
            "knobs": {"TPUSNAP_STAGE_THREADS": "8"},
        }
        assert knobs._env_get("TPUSNAP_PROBE_INTERVAL_BYTES") == "123"
        assert knobs._env_get("TPUSNAP_STAGE_THREADS") == "8"
        # The env value wins at the lookup layer; the knob's own 16 MiB
        # cadence floor still applies on top of whichever layer won.
        assert knobs.get_probe_interval_bytes() == 16 * MiB
    finally:
        knobs.clear_tuned_plan()
    assert knobs.tuned_plan() is None
    assert knobs._env_get("TPUSNAP_STAGE_THREADS") is None


def test_tuned_overlay_fully_shadowed_plan_is_not_a_plan(monkeypatch):
    """A plan whose every knob the env already sets applies nothing —
    tuned_plan() stays None so no bogus stamp rides the history."""
    monkeypatch.setenv("TPUSNAP_PROBE_INTERVAL_BYTES", "123")
    try:
        applied = knobs.apply_tuned_plan(
            "cafecafecafe", {"TPUSNAP_PROBE_INTERVAL_BYTES": "999"}
        )
        assert applied == {}
        assert knobs.tuned_plan() is None
    finally:
        knobs.clear_tuned_plan()


# ------------------------------------------------------------ CLI


def test_tune_cli_insufficient_history_exits_3(tmp_path, capsys):
    with override_telemetry_dir(str(tmp_path / "tele")):
        rc = main(["tune", "--check", "--kind", "restore"])
    assert rc == 3
    assert "no plan" in capsys.readouterr().out


def test_tune_cli_json_and_env_render(tmp_path, capsys):
    hist = tmp_path / "history.jsonl"
    with open(hist, "w") as f:
        for e in _events(3):
            f.write(json.dumps(e) + "\n")
    rc = main(["tune", "--file", str(hist), "--kind", "restore",
               "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"]
    assert doc["cell"] == {
        "backend": "FSStoragePlugin", "kind": "restore", "world_size": 1,
    }
    assert doc["plan_id"]
    planned = {k["env"]: k["value"] for k in doc["knobs"]}
    assert "TPUSNAP_PROBE_INTERVAL_BYTES" in planned
    rc = main(["tune", "--file", str(hist), "--kind", "restore", "--env"])
    assert rc == 0
    out = capsys.readouterr().out
    for env, value in planned.items():
        assert f"export {env}={value}" in out


# -------------------------------------------- autotune reconcile stamp


def test_autotune_stamps_plan_into_history_event(tmp_path, monkeypatch):
    """TPUSNAP_AUTOTUNE end to end: seed a cell with clones of a REAL
    restore event (real plugin label — no label guessing), rerun the
    restore under autotune, and the new history event carries the
    ``tuned: {plan_id, knobs}`` stamp matching the CLI's plan. The
    overlay is scoped to the restore: cleared by the time it returns."""
    monkeypatch.delenv("TPUSNAP_PROBE_INTERVAL_BYTES", raising=False)
    compress._reset_ceilings()
    snap = str(tmp_path / "snap")
    state = {"w": np.arange(65536, dtype=np.float32)}
    with override_telemetry_dir(str(tmp_path / "tele")):
        Snapshot.take(snap, {"m": PytreeState(state)})
        Snapshot(snap).restore(
            {"m": PytreeState({"w": np.zeros(65536, np.float32)})}
        )
        base = [e for e in load_history() if e.get("kind") == "restore"][-1]
        assert "tuned" not in base  # autotune was off
        with open(history_path(), "a") as f:
            for _ in range(3):
                f.write(json.dumps(dict(base, bytes=GiB, wall_s=2.0)) + "\n")
        expected = build_plan(
            load_history(), "restore",
            ceilings=compress.pipe_ceilings_snapshot(),
        )
        assert expected.ok and expected.knobs, expected.reason
        with override_autotune(True):
            Snapshot(snap).restore(
                {"m": PytreeState({"w": np.zeros(65536, np.float32)})}
            )
        assert knobs.tuned_plan() is None
        ev = [e for e in load_history() if e.get("kind") == "restore"][-1]
    assert ev.get("tuned"), ev
    assert ev["tuned"]["plan_id"] == expected.plan_id
    assert ev["tuned"]["knobs"] == {
        k.env: k.value for k in expected.knobs
    }
    assert "TPUSNAP_PROBE_INTERVAL_BYTES" in ev["tuned"]["knobs"]


def test_autotune_off_by_default(tmp_path):
    compress._reset_ceilings()
    snap = str(tmp_path / "snap")
    state = {"w": np.arange(4096, dtype=np.float32)}
    with override_telemetry_dir(str(tmp_path / "tele")):
        Snapshot.take(snap, {"m": PytreeState(state)})
        Snapshot(snap).restore(
            {"m": PytreeState({"w": np.zeros(4096, np.float32)})}
        )
        ev = [e for e in load_history() if e.get("kind") == "restore"][-1]
    assert "tuned" not in ev


# --------------------------------------------- probe read-lane units


def test_probe_read_lane_units_fake_clock(monkeypatch):
    """Deterministic-clock unit check: with the storage legs pinned to
    0.125 s each, the probe's read sample must come out at exactly
    nbytes / 0.125 / 1e9 GB/s — and feed the ceiling registry's READ
    lane (the write leg feeds the write lane), which is what prices
    restore_roofline_fraction and the slo cold-start fallback."""
    from tpusnap import scheduler as sched_mod
    from tpusnap.io_types import StoragePlugin
    from tpusnap.scheduler import _ProbeRunner

    class NullPlugin(StoragePlugin):
        async def write(self, write_io):
            pass

        async def read(self, read_io):
            pass

        async def delete(self, path):
            pass

    class FakeTime:
        def __init__(self, step):
            self.t, self.step = 0.0, step

        def monotonic(self):
            self.t += self.step
            return self.t

        def __getattr__(self, name):  # sleep etc. pass through
            import time as _real

            return getattr(_real, name)

    compress._reset_ceilings()
    try:
        with override_probe(True, interval_bytes=1 * MiB,
                            probe_bytes=8 * MiB):
            tele = telemetry.TakeTelemetry(rank=0, enabled=True)
            try:
                runner = _ProbeRunner(NullPlugin(), rank=0, tele=tele)
                monkeypatch.setattr(sched_mod, "time", FakeTime(0.125))
                runner.note_written(32 * MiB)  # past the 16 MiB floor
                assert runner.due
                asyncio.run(runner.run())
            finally:
                tele.finalize()
        assert runner.ran == 1
        assert not runner.due  # counter reset after the probe
        nbytes = runner.stream_bytes * _ProbeRunner._STREAMS
        assert nbytes == 8 * MiB
        want = round(nbytes / 0.125 / 1e9, 4)
        s = tele.summary()
        assert s["probe"]["probes"] == 1
        assert s["probe"]["read_gbps_p50"] == want
        assert s["probe"]["write_gbps_p50"] == want
        snap = compress.pipe_ceilings_snapshot()
        assert snap[(runner._label, "read")] == want
        assert snap[(runner._label, "write")] == want
    finally:
        compress._reset_ceilings()
