"""Retention (tpusnap/retention.py): keep newest N, materialize kept
increments before deleting their bases, never destroy readable data."""

import os
import time

import numpy as np
import pytest

from tpusnap import Snapshot, StateDict, verify_snapshot
from tpusnap.knobs import override_batching_disabled
from tpusnap.retention import apply_retention


def _chain(tmp_path, n=3):
    """s0 (full) <- s1 <- s2 incremental chain with a frozen blob and a
    changing step; returns (root, states)."""
    root = str(tmp_path)
    frozen = np.random.default_rng(0).standard_normal((512, 64)).astype(np.float32)
    prev = None
    hots = []
    with override_batching_disabled(True):
        for i in range(n):
            hot = np.full((64,), float(i), np.float32)
            hots.append(hot)
            path = os.path.join(root, f"s{i}")
            Snapshot.take(
                path,
                {"app": StateDict(frozen=frozen, hot=hot, step=i)},
                incremental_from=prev,
            )
            prev = path
    return root, frozen, hots


def test_keep_last_materializes_then_deletes(tmp_path):
    root, frozen, hots = _chain(tmp_path)
    plan = apply_retention(root, keep_last=1)
    assert plan.executed
    assert [os.path.basename(p) for p in plan.keep] == ["s2"]
    assert sorted(os.path.basename(p) for p in plan.delete) == ["s0", "s1"]
    assert [os.path.basename(p) for p in plan.materialize] == ["s2"]
    assert plan.bytes_copied >= frozen.nbytes
    assert sorted(os.listdir(root)) == ["s2"]
    # The survivor is self-contained: restores and scrubs clean.
    assert verify_snapshot(os.path.join(root, "s2")).clean
    tgt = {"app": StateDict(frozen=np.zeros_like(frozen),
                            hot=np.zeros(64, np.float32), step=-1)}
    Snapshot(os.path.join(root, "s2")).restore(tgt)
    assert tgt["app"]["step"] == 2
    assert np.array_equal(tgt["app"]["frozen"], frozen)
    assert np.array_equal(tgt["app"]["hot"], hots[2])


def test_dry_run_touches_nothing(tmp_path):
    root, _, _ = _chain(tmp_path)
    plan = apply_retention(root, keep_last=1, dry_run=True)
    assert not plan.executed
    assert sorted(os.path.basename(p) for p in plan.delete) == ["s0", "s1"]
    assert sorted(os.listdir(root)) == ["s0", "s1", "s2"]
    # Chain still intact and readable.
    assert verify_snapshot(os.path.join(root, "s2")).clean


def test_keep_two_materializes_both_dependents(tmp_path):
    """Chains collapse to the oldest base, so BOTH kept increments
    reference doomed s0 and both must be materialized."""
    root, frozen, hots = _chain(tmp_path)
    plan = apply_retention(root, keep_last=2)
    assert sorted(os.path.basename(p) for p in plan.materialize) == ["s1", "s2"]
    assert sorted(os.listdir(root)) == ["s1", "s2"]
    for name, hot in (("s1", hots[1]), ("s2", hots[2])):
        assert verify_snapshot(os.path.join(root, name)).clean
        tgt = {"app": StateDict(frozen=np.zeros_like(frozen),
                                hot=np.zeros(64, np.float32), step=-1)}
        Snapshot(os.path.join(root, name)).restore(tgt)
        assert np.array_equal(tgt["app"]["hot"], hot), name


def test_keep_all_is_noop(tmp_path):
    root, _, _ = _chain(tmp_path)
    plan = apply_retention(root, keep_last=10)
    assert plan.executed and not plan.delete and not plan.materialize
    assert sorted(os.listdir(root)) == ["s0", "s1", "s2"]


def test_refuses_object_store_roots():
    with pytest.raises(ValueError, match="local filesystem"):
        apply_retention("gs://bkt/snaps", keep_last=1, dry_run=True)


def test_ordering_survives_mtime_resets(tmp_path):
    """Ordering comes from metadata created_at, not file mtimes: a
    materialize (atomic metadata rewrite) or an rsync that resets mtimes
    must not flip which snapshots retention considers newest."""
    root, frozen, hots = _chain(tmp_path)
    # Adversarial mtimes: make s0 look newest and s2 oldest on disk.
    now = time.time()
    for i, bump in (("0", 100), ("1", 50), ("2", 0)):
        meta = os.path.join(root, f"s{i}", ".snapshot_metadata")
        os.utime(meta, (now + bump, now + bump))
    plan = apply_retention(root, keep_last=1)
    assert [os.path.basename(p) for p in plan.keep] == ["s2"]
    assert sorted(os.listdir(root)) == ["s2"]
    assert verify_snapshot(os.path.join(root, "s2")).clean


def test_cli_retain(tmp_path, capsys):
    from tpusnap.__main__ import main as cli_main

    root, _, _ = _chain(tmp_path)
    assert cli_main(["retain", root, "--keep", "1", "--dry-run"]) == 0
    out = capsys.readouterr().out
    assert "would delete" in out and "s0" in out
    assert sorted(os.listdir(root)) == ["s0", "s1", "s2"]
    assert cli_main(["retain", root, "--keep", "1"]) == 0
    out = capsys.readouterr().out
    assert "deleted" in out
    assert sorted(os.listdir(root)) == ["s2"]
