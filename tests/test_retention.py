"""Retention (tpusnap/retention.py): keep newest N, materialize kept
increments before deleting their bases, never destroy readable data."""

import os
import time

import numpy as np
import pytest

from tpusnap import Snapshot, StateDict, verify_snapshot
from tpusnap.knobs import override_batching_disabled
from tpusnap.retention import apply_retention


def _chain(tmp_path, n=3):
    """s0 (full) <- s1 <- s2 incremental chain with a frozen blob and a
    changing step; returns (root, states)."""
    root = str(tmp_path)
    frozen = np.random.default_rng(0).standard_normal((512, 64)).astype(np.float32)
    prev = None
    hots = []
    with override_batching_disabled(True):
        for i in range(n):
            hot = np.full((64,), float(i), np.float32)
            hots.append(hot)
            path = os.path.join(root, f"s{i}")
            Snapshot.take(
                path,
                {"app": StateDict(frozen=frozen, hot=hot, step=i)},
                incremental_from=prev,
            )
            prev = path
    return root, frozen, hots


def test_keep_last_materializes_then_deletes(tmp_path):
    root, frozen, hots = _chain(tmp_path)
    plan = apply_retention(root, keep_last=1)
    assert plan.executed
    assert [os.path.basename(p) for p in plan.keep] == ["s2"]
    assert sorted(os.path.basename(p) for p in plan.delete) == ["s0", "s1"]
    assert [os.path.basename(p) for p in plan.materialize] == ["s2"]
    assert plan.bytes_copied >= frozen.nbytes
    assert sorted(os.listdir(root)) == ["s2"]
    # The survivor is self-contained: restores and scrubs clean.
    assert verify_snapshot(os.path.join(root, "s2")).clean
    tgt = {"app": StateDict(frozen=np.zeros_like(frozen),
                            hot=np.zeros(64, np.float32), step=-1)}
    Snapshot(os.path.join(root, "s2")).restore(tgt)
    assert tgt["app"]["step"] == 2
    assert np.array_equal(tgt["app"]["frozen"], frozen)
    assert np.array_equal(tgt["app"]["hot"], hots[2])


def test_dry_run_touches_nothing(tmp_path):
    root, _, _ = _chain(tmp_path)
    plan = apply_retention(root, keep_last=1, dry_run=True)
    assert not plan.executed
    assert sorted(os.path.basename(p) for p in plan.delete) == ["s0", "s1"]
    assert sorted(os.listdir(root)) == ["s0", "s1", "s2"]
    # Chain still intact and readable.
    assert verify_snapshot(os.path.join(root, "s2")).clean


def test_keep_two_materializes_both_dependents(tmp_path):
    """Chains collapse to the oldest base, so BOTH kept increments
    reference doomed s0 and both must be materialized."""
    root, frozen, hots = _chain(tmp_path)
    plan = apply_retention(root, keep_last=2)
    assert sorted(os.path.basename(p) for p in plan.materialize) == ["s1", "s2"]
    assert sorted(os.listdir(root)) == ["s1", "s2"]
    for name, hot in (("s1", hots[1]), ("s2", hots[2])):
        assert verify_snapshot(os.path.join(root, name)).clean
        tgt = {"app": StateDict(frozen=np.zeros_like(frozen),
                                hot=np.zeros(64, np.float32), step=-1)}
        Snapshot(os.path.join(root, name)).restore(tgt)
        assert np.array_equal(tgt["app"]["hot"], hot), name


def test_keep_all_is_noop(tmp_path):
    root, _, _ = _chain(tmp_path)
    plan = apply_retention(root, keep_last=10)
    assert plan.executed and not plan.delete and not plan.materialize
    assert sorted(os.listdir(root)) == ["s0", "s1", "s2"]


def test_refuses_object_store_roots():
    with pytest.raises(ValueError, match="local filesystem"):
        apply_retention("gs://bkt/snaps", keep_last=1, dry_run=True)


def test_ordering_survives_mtime_resets(tmp_path):
    """Ordering comes from metadata created_at, not file mtimes: a
    materialize (atomic metadata rewrite) or an rsync that resets mtimes
    must not flip which snapshots retention considers newest."""
    root, frozen, hots = _chain(tmp_path)
    # Adversarial mtimes: make s0 look newest and s2 oldest on disk.
    now = time.time()
    for i, bump in (("0", 100), ("1", 50), ("2", 0)):
        meta = os.path.join(root, f"s{i}", ".snapshot_metadata")
        os.utime(meta, (now + bump, now + bump))
    plan = apply_retention(root, keep_last=1)
    assert [os.path.basename(p) for p in plan.keep] == ["s2"]
    assert sorted(os.listdir(root)) == ["s2"]
    assert verify_snapshot(os.path.join(root, "s2")).clean


def test_cli_retain(tmp_path, capsys):
    from tpusnap.__main__ import main as cli_main

    root, _, _ = _chain(tmp_path)
    assert cli_main(["retain", root, "--keep", "1", "--dry-run"]) == 0
    out = capsys.readouterr().out
    assert "would delete" in out and "s0" in out
    assert sorted(os.listdir(root)) == ["s0", "s1", "s2"]
    assert cli_main(["retain", root, "--keep", "1"]) == 0
    out = capsys.readouterr().out
    assert "deleted" in out
    assert sorted(os.listdir(root)) == ["s2"]


# --------------------------------------------------------------- round 4:
# crash/fault injection on the materialize-then-delete sequence — the
# module's headline claim ("a crash at any point leaves every kept
# snapshot readable") exercised, not just asserted.


def _restore_ok(path, frozen, hots, i):
    tgt = {"app": StateDict(frozen=np.zeros_like(frozen),
                            hot=np.zeros(64, np.float32), step=-1)}
    Snapshot(path).restore(tgt)
    assert tgt["app"]["step"] == i
    assert np.array_equal(tgt["app"]["frozen"], frozen)
    assert np.array_equal(tgt["app"]["hot"], hots[i])


class TestCrashMidLifecycle:
    def _multi_blob_chain(self, tmp_path):
        """A chain whose final increment references SEVERAL base blobs,
        so a fault can land between copies."""
        root = str(tmp_path)
        rng = np.random.default_rng(0)
        frozen = {
            f"f{i}": rng.standard_normal((256, 64)).astype(np.float32)
            for i in range(4)
        }
        prev = None
        hots = []
        with override_batching_disabled(True):
            for i in range(3):
                hot = np.full((64,), float(i), np.float32)
                hots.append(hot)
                path = os.path.join(root, f"s{i}")
                Snapshot.take(
                    path,
                    {"app": StateDict(hot=hot, step=i, **frozen)},
                    incremental_from=prev,
                )
                prev = path
        return root, frozen, hots

    def _restore_multi_ok(self, path, frozen, hots, i):
        tgt = {"app": StateDict(
            hot=np.zeros(64, np.float32), step=-1,
            **{k: np.zeros_like(v) for k, v in frozen.items()},
        )}
        Snapshot(path).restore(tgt)
        assert tgt["app"]["step"] == i
        assert np.array_equal(tgt["app"]["hot"], hots[i])
        for k, v in frozen.items():
            assert np.array_equal(tgt["app"][k], v), k

    def test_fault_mid_materialize_keeps_snapshot_readable(
        self, tmp_path, monkeypatch
    ):
        """Blob-copy writes fail partway through materialize: the
        manifest must NOT have been rewritten (metadata commit is last,
        atomic), the increment stays base-referencing and readable, and
        a re-run converges."""
        from tpusnap.storage_plugins.fs import FSStoragePlugin

        root, frozen, hots = self._multi_blob_chain(tmp_path)
        s2 = os.path.join(root, "s2")
        calls = {"n": 0}
        real_write = FSStoragePlugin.write

        async def faulty_write(self, write_io):
            calls["n"] += 1
            if calls["n"] >= 2:
                raise IOError("injected: storage died mid-materialize")
            return await real_write(self, write_io)

        monkeypatch.setattr(FSStoragePlugin, "write", faulty_write)
        from tpusnap.inspect import materialize_snapshot

        with pytest.raises(Exception, match="injected"):
            materialize_snapshot(s2)
        monkeypatch.setattr(FSStoragePlugin, "write", real_write)
        assert calls["n"] >= 2  # the fault landed between copies
        # Still an increment (metadata untouched), still fully readable.
        md = Snapshot(s2).metadata
        assert md.base_roots  # still references its bases
        self._restore_multi_ok(s2, frozen, hots, 2)
        assert verify_snapshot(s2).clean
        # Re-run converges to self-contained.
        stats = materialize_snapshot(s2)
        assert stats["blobs_copied"] >= 1
        assert Snapshot(s2).metadata.base_roots is None
        self._restore_multi_ok(s2, frozen, hots, 2)
        assert verify_snapshot(s2).clean

    def test_fault_mid_metadata_commit_keeps_snapshot_readable(
        self, tmp_path, monkeypatch
    ):
        """The atomic metadata rewrite itself fails: the OLD metadata
        must survive intact (temp+rename discipline) and a re-run
        converges."""
        from tpusnap.storage_plugins.fs import FSStoragePlugin

        root, frozen, hots = _chain(tmp_path)
        s2 = os.path.join(root, "s2")

        async def faulty_atomic(self, write_io, durable=False):
            raise IOError("injected: died in metadata commit")

        monkeypatch.setattr(FSStoragePlugin, "write_atomic", faulty_atomic)
        from tpusnap.inspect import materialize_snapshot

        with pytest.raises(Exception, match="injected"):
            materialize_snapshot(s2)
        monkeypatch.undo()
        _restore_ok(s2, frozen, hots, 2)
        assert verify_snapshot(s2).clean
        materialize_snapshot(s2)
        _restore_ok(s2, frozen, hots, 2)

    def test_crash_mid_delete_keeps_survivors_readable(
        self, tmp_path, monkeypatch
    ):
        """The delete phase dies halfway (first rmtree succeeds, second
        raises — the moral equivalent of a kill between unlinks): every
        KEPT snapshot is already self-contained and readable, and a
        re-run of retention converges."""
        import shutil

        root, frozen, hots = _chain(tmp_path)
        real_rmtree = shutil.rmtree
        calls = {"n": 0}

        def faulty_rmtree(path, *a, **kw):
            calls["n"] += 1
            if calls["n"] >= 2:
                raise OSError("injected: crash mid-delete")
            return real_rmtree(path, *a, **kw)

        monkeypatch.setattr("tpusnap.retention.shutil.rmtree", faulty_rmtree)
        with pytest.raises(OSError, match="injected"):
            apply_retention(root, keep_last=1)
        monkeypatch.undo()
        # The kept snapshot was materialized BEFORE any deletion: it is
        # readable even though its bases are half-gone.
        s2 = os.path.join(root, "s2")
        _restore_ok(s2, frozen, hots, 2)
        assert verify_snapshot(s2).clean
        # Re-run converges (idempotent on the debris).
        plan = apply_retention(root, keep_last=1)
        assert plan.executed
        assert sorted(os.listdir(root)) == ["s2"]
        _restore_ok(s2, frozen, hots, 2)

    def test_sigkill_mid_materialize(self, tmp_path):
        """The hard version of the claim: SIGKILL (no cleanup, no
        exception handling) mid-materialize leaves the increment
        readable and a fresh-process re-run converges."""
        import signal
        import subprocess
        import sys
        import time

        root, frozen, hots = _chain(tmp_path)
        s2 = os.path.join(root, "s2")
        child_src = (
            "import os, sys, time\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "import tpusnap.storage_plugins.fs as fsmod\n"
            "real = fsmod.FSStoragePlugin.write\n"
            "async def slow(self, wio):\n"
            "    await real(self, wio)\n"
            "    print('COPIED', flush=True)\n"
            "    time.sleep(30)\n"  # hold mid-copy so the kill lands here
            "fsmod.FSStoragePlugin.write = slow\n"
            "from tpusnap.inspect import materialize_snapshot\n"
            "print('READY', flush=True)\n"
            "materialize_snapshot(sys.argv[1])\n"
        )
        proc = subprocess.Popen(
            [sys.executable, "-c", child_src, s2],
            stdout=subprocess.PIPE,
            text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        try:
            deadline = time.monotonic() + 120
            saw_copy = False
            for line in proc.stdout:
                if "COPIED" in line:
                    saw_copy = True
                    break
                if time.monotonic() > deadline:
                    break
            assert saw_copy, "child never copied a blob"
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
        # Metadata untouched -> still an increment, still readable.
        assert Snapshot(s2).metadata.base_roots
        _restore_ok(s2, frozen, hots, 2)
        assert verify_snapshot(s2).clean
        from tpusnap.inspect import materialize_snapshot

        stats = materialize_snapshot(s2)
        assert stats["blobs_copied"] >= 1
        assert Snapshot(s2).metadata.base_roots is None
        _restore_ok(s2, frozen, hots, 2)
