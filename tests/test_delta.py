"""Continuous delta checkpointing (``tpusnap.delta``): DeltaStream
micro-commits, chain resolution, compaction, retention pinning.

Covers: an unchanged model streams ~zero payload bytes per micro-commit
(dual-hash skip asserted via the stream's byte accounting AND the
member's on-disk payload files); restore of a delta head replays base +
committed chain bit-identically (flat lookups at any depth); cadence
free-running and step-gated capture (step-gated heads land EXACTLY on a
mark_step boundary state); chain compaction via materialize bounds the
chain and retires superseded members; resolve_chain names head / torn
tail / debris; retention never reclaims a member a kept head references
(transitive pinning); the SLO tracker is anchored by micro-commits.
SIGKILL crash windows live in test_crash_matrix.py.
"""

import os

import numpy as np
import pytest

from tpusnap import (
    DeltaStream,
    Snapshot,
    StateDict,
    resolve_chain,
    verify_snapshot,
)
from tpusnap.delta import delta_fields, delta_payload_bytes, member_name
from tpusnap.inspect import load_snapshot_metadata


def _payload_files(root: str):
    """PAYLOAD files under a snapshot dir (excluding metadata and the
    .tpusnap sidecars)."""
    out = []
    for dirpath, _, files in os.walk(root):
        if ".tpusnap" in dirpath.split(os.sep):
            continue
        for f in files:
            if f != ".snapshot_metadata":
                out.append(os.path.relpath(os.path.join(dirpath, f), root))
    return sorted(out)


def _state(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "app": StateDict(
            w=rng.standard_normal((256, 64)).astype(np.float32),
            b=rng.standard_normal((128,)).astype(np.float32),
        )
    }


def test_stream_commit_restore_bit_identical(tmp_path):
    root = str(tmp_path / "stream")
    state = _state()
    with Snapshot.stream(root, state, cadence_s=3600) as s:
        assert s.seq == 0
        assert s.head.endswith(member_name(0))
        state["app"]["w"][0, :] = 42.0
        snap = s.commit_now()
        assert s.seq == 1
        # The committed member is a real snapshot: verifies clean and
        # carries its chain fields.
        assert verify_snapshot(snap.path).clean
        d = delta_fields(snap.metadata)
        assert d is not None and d["seq"] == 1
        assert d["parent"] == member_name(0)
        state["app"]["b"][:] = -1.0
        s.commit_now()
        expected_w = state["app"]["w"].copy()
        expected_b = state["app"]["b"].copy()
    # close() ran a final (unchanged) commit; head replays the chain.
    rep = resolve_chain(root)
    assert rep.head is not None
    target = {
        "app": StateDict(
            w=np.zeros((256, 64), np.float32), b=np.zeros(128, np.float32)
        )
    }
    Snapshot(rep.head_path).restore(target)
    assert np.array_equal(target["app"]["w"], expected_w)
    assert np.array_equal(target["app"]["b"], expected_b)
    # Intermediate members restore too (any member is a snapshot).
    mid = os.path.join(root, member_name(1))
    out = Snapshot(mid).read_object("0/app/w")
    assert np.array_equal(out[0], np.full(64, 42.0, np.float32))


def test_unchanged_model_streams_zero_payload_bytes(tmp_path):
    from tpusnap import telemetry

    root = str(tmp_path / "stream")
    state = _state(1)
    s = Snapshot.stream(root, state, cadence_s=3600)
    commits_before = telemetry.counter_value("delta.commits")
    snap = s.commit_now()
    # Dual-hash skip: nothing changed since the base — the member holds
    # NO payload files and the stream accounts zero bytes written.
    assert s.stats["last_commit_bytes"] == 0
    assert _payload_files(snap.path) == []
    assert delta_payload_bytes(snap.metadata) == 0
    assert telemetry.counter_value("delta.commits") == commits_before + 1
    # ... and still restores the full state through the base references.
    assert verify_snapshot(snap.path).clean
    target = {
        "app": StateDict(
            w=np.zeros((256, 64), np.float32), b=np.zeros(128, np.float32)
        )
    }
    Snapshot(snap.path).restore(target)
    assert np.array_equal(target["app"]["w"], state["app"]["w"])
    # A changed leaf rewrites only itself (b is slab-batched, so the
    # new slab holds just the one changed member: b's 512 bytes).
    state["app"]["b"][0] = 123.0
    snap2 = s.commit_now()
    files = _payload_files(snap2.path)
    assert len(files) == 1, files
    assert s.stats["last_commit_bytes"] == state["app"]["b"].nbytes
    s.close(final_commit=False)


def test_cadence_free_running_commits(tmp_path):
    import time

    root = str(tmp_path / "stream")
    state = _state(2)
    s = Snapshot.stream(root, state, cadence_s=0.3)
    t0 = time.monotonic()
    while time.monotonic() - t0 < 1.4:
        state["app"]["w"][0, 0] += 1.0
        time.sleep(0.02)
    s.close(final_commit=False)
    s.raise_if_failed()
    # ~4 intervals elapsed; allow generous slack for slow CI hosts.
    assert s.stats["commits"] >= 2, s.stats
    assert s.seq >= 2


def test_mark_step_gated_capture_lands_on_step_boundaries(tmp_path):
    """With mark_step gating, every committed increment must equal a
    state AS OF some step boundary — never a mid-mutation mixture."""
    import time

    root = str(tmp_path / "stream")
    state = _state(3)
    boundary_states = []

    def snapshot_boundary():
        boundary_states.append(
            (state["app"]["w"].copy(), state["app"]["b"].copy())
        )

    snapshot_boundary()  # the base capture in __init__ sees this state
    s = Snapshot.stream(root, state, cadence_s=0.25)
    t0 = time.monotonic()
    while time.monotonic() - t0 < 1.3:
        # "training step": in-place mutation between boundaries.
        state["app"]["w"] += 1.0
        state["app"]["b"] -= 0.5
        snapshot_boundary()
        s.mark_step(bytes_changed=state["app"]["w"].nbytes)
        time.sleep(0.02)
    s.close(final_commit=False)
    s.raise_if_failed()
    assert s.stats["commits"] >= 2, s.stats
    assert s.stats["steps_marked"] > 0
    rep = resolve_chain(root)
    target = {
        "app": StateDict(
            w=np.zeros((256, 64), np.float32), b=np.zeros(128, np.float32)
        )
    }
    Snapshot(rep.head_path).restore(target)
    matches = [
        i
        for i, (w, b) in enumerate(boundary_states)
        if np.array_equal(target["app"]["w"], w)
        and np.array_equal(target["app"]["b"], b)
    ]
    assert matches, "head is not any step-boundary state (torn capture)"


def test_compaction_bounds_chain_and_retires_members(tmp_path):
    root = str(tmp_path / "stream")
    state = _state(4)
    s = Snapshot.stream(root, state, cadence_s=3600, max_chain=2)
    for i in range(5):
        state["app"]["w"][i, :] = float(i)
        s.commit_now()
    expected = state["app"]["w"].copy()
    s.close(final_commit=False)
    assert s.stats["compactions"] >= 1, s.stats
    assert len(s.chain) <= 2, s.chain
    rep = resolve_chain(root)
    # Superseded members were retired from disk (local fs).
    on_disk = {m.name for m in rep.members}
    assert set(s.chain) <= on_disk
    assert len(on_disk) <= 3, on_disk  # chain + at most the fresh head
    # The compacted base is self-contained and the head restores.
    target = {
        "app": StateDict(
            w=np.zeros((256, 64), np.float32), b=np.zeros(128, np.float32)
        )
    }
    Snapshot(rep.head_path).restore(target)
    assert np.array_equal(target["app"]["w"], expected)
    assert verify_snapshot(rep.head_path).clean


def test_resolve_chain_names_torn_tail_and_debris(tmp_path):
    import json

    root = str(tmp_path / "stream")
    state = _state(5)
    s = Snapshot.stream(root, state, cadence_s=3600)
    state["app"]["w"][0, 0] = 9.0
    s.commit_now()
    s.close(final_commit=False)
    # Manufacture a torn tail: journal marker with stream fields, no
    # metadata — exactly what a SIGKILLed micro-commit leaves.
    torn = tmp_path / "stream" / member_name(2) / ".tpusnap"
    torn.mkdir(parents=True)
    (torn / "journal").write_text(
        json.dumps(
            {
                "take_id": "deadbeef",
                "world_size": 1,
                "started_at": 0.0,
                "incremental_from": "../" + member_name(1),
                "stream": {
                    "stream": s.stream_id,
                    "seq": 2,
                    "parent": member_name(1),
                },
            }
        )
    )
    # ... and a debris dir (half-retired compaction leftover).
    junk = tmp_path / "stream" / "delta-000090"
    junk.mkdir()
    (junk / "leftover.blob").write_bytes(b"x" * 32)
    rep = resolve_chain(root)
    assert rep.torn_tail == member_name(2)
    assert rep.head == member_name(1)  # recovery ignores the torn tail
    assert "delta-000090" in rep.debris
    # fsck of the torn member classifies it and names the delta state.
    from tpusnap.lifecycle import fsck_snapshot

    fr = fsck_snapshot(str(tmp_path / "stream" / member_name(2)))
    assert fr.state == "torn"
    assert fr.delta and fr.delta["seq"] == 2
    assert "torn delta micro-commit seq 2" in fr.summary()
    # Root-level fsck exits 4 on the torn tail; info renders the chain.
    from tpusnap.__main__ import main

    assert main(["fsck", root]) == 4
    assert main(["info", root]) == 0


def test_retention_pins_chain_of_kept_head(tmp_path):
    """`retain --keep 1` on a stream root: the kept head references
    earlier members (unchanged blobs dedup into them) — retention must
    materialize it BEFORE deleting them, never leaving a dangling
    chain."""
    from tpusnap.retention import _referenced_bases, apply_retention

    root = str(tmp_path / "stream")
    state = _state(6)
    s = Snapshot.stream(root, state, cadence_s=3600, max_chain=100)
    # b never changes -> every increment references the base's b blob.
    state["app"]["w"][0, :] = 1.0
    s.commit_now()
    state["app"]["w"][1, :] = 2.0
    s.commit_now()
    expected_w = state["app"]["w"].copy()
    expected_b = state["app"]["b"].copy()
    s.close(final_commit=False)
    head = os.path.join(root, member_name(2))
    bases = _referenced_bases(head)
    assert any(member_name(0) in b for b in bases), bases
    plan = apply_retention(root, keep_last=1)
    assert plan.keep == [os.path.abspath(head)]
    assert plan.materialize == [os.path.abspath(head)], (
        "kept head referencing doomed chain members must be materialized"
    )
    assert not os.path.exists(os.path.join(root, member_name(0)))
    target = {
        "app": StateDict(
            w=np.zeros((256, 64), np.float32), b=np.zeros(128, np.float32)
        )
    }
    Snapshot(head).restore(target)
    assert np.array_equal(target["app"]["w"], expected_w)
    assert np.array_equal(target["app"]["b"], expected_b)
    assert verify_snapshot(head).clean


def test_referenced_bases_walks_transitively(tmp_path):
    """Defense in depth: a hand-built NON-collapsed chain (C→B→A where
    C's metadata only names B) must still pin A through the transitive
    walk."""
    from tpusnap.retention import _referenced_bases

    a, b, c = (str(tmp_path / n) for n in ("a", "b", "c"))
    st = _state(7)
    Snapshot.take(a, st)
    st["app"]["w"][0, 0] += 1  # w rewrites in b; bias still refs a
    Snapshot.take(b, st, incremental_from=a)
    # Nothing changes: c references b's w AND (collapsed) a's bias.
    Snapshot.take(c, st, incremental_from=b)
    direct = _referenced_bases(c)
    assert os.path.abspath(a) in direct and os.path.abspath(b) in direct
    # b itself references only a; the transitive walk from c reaches a
    # even through b (defense in depth for non-collapsed chains).
    assert _referenced_bases(b) == [os.path.abspath(a)]


def test_stream_anchors_slo_tracker(tmp_path):
    from tpusnap import slo

    slo.reset_tracker()
    root = str(tmp_path / "stream")
    state = _state(8)
    s = Snapshot.stream(root, state, cadence_s=0.5)
    st = slo.tracker().snapshot_state()
    assert st["stream_cadence_s"] == 0.5
    # The base commit anchored the RPO clock seconds ago, not minutes.
    assert st["rpo_s"] < 60.0
    state["app"]["w"][0, 0] = 7.0
    s.commit_now()
    st = slo.tracker().snapshot_state()
    assert st["last_commit_take_id"], st
    assert st["commit_interval_s"] is not None
    s.close(final_commit=False)
    st = slo.tracker().snapshot_state()
    assert st["stream_cadence_s"] is None


def test_stream_multiprocess_needs_coordination(tmp_path):
    """A multi-process stream runs its elastic control plane over the
    jax.distributed coordination KV — opening one without the service
    must fail loudly, not wedge."""
    from tpusnap.comm import Communicator

    class FakeMulti(Communicator):
        @property
        def world_size(self):
            return 2

    with pytest.raises(RuntimeError, match="jax.distributed"):
        Snapshot.stream(
            str(tmp_path / "s"), _state(), comm=FakeMulti()
        )


def test_stream_resumes_committed_chain(tmp_path):
    """Reopening a stream root RESUMES the committed chain across
    process lifetimes: the new stream adopts the head's stream id and
    sequence, takes no new base, and its first micro-commit extends
    the existing chain."""
    root = str(tmp_path / "stream")
    state = _state(11)
    s = Snapshot.stream(root, state, cadence_s=3600)
    sid = s.stream_id
    state["app"]["w"][0, 0] = 1.0
    s.commit_now()
    s.close(final_commit=False)

    s2 = Snapshot.stream(root, state, cadence_s=3600)
    try:
        assert s2.stream_id == sid
        assert s2.seq == 1  # adopted, not reset
        state["app"]["w"][0, 1] = 2.0
        snap = s2.commit_now()
        assert s2.seq == 2
        # No second base: the resumed commit extends the old chain.
        assert not os.path.isdir(
            os.path.join(root, "base-000001")
        )
        restored = _state()
        snap.restore(restored)
        np.testing.assert_array_equal(
            restored["app"]["w"], state["app"]["w"]
        )
        rep = resolve_chain(root)
        assert rep.head == member_name(2)
        assert member_name(0) in rep.chain
        assert verify_snapshot(rep.head_path).clean
    finally:
        s2.close(final_commit=False)


def test_stream_refuses_foreign_root(tmp_path):
    """A root holding committed NON-stream snapshots still refuses: a
    fresh base under foreign snapshot dirs would silently change what
    the directory means."""
    root = str(tmp_path / "root")
    Snapshot.take(os.path.join(root, "plain"), _state(11))
    with pytest.raises(ValueError, match="non-stream"):
        Snapshot.stream(root, _state(11), cadence_s=3600)


def test_stream_rejects_nonpositive_cadence(tmp_path):
    with pytest.raises(ValueError, match="cadence_s"):
        Snapshot.stream(str(tmp_path / "s"), _state(12), cadence_s=0)
    with pytest.raises(ValueError, match="cadence_s"):
        Snapshot.stream(str(tmp_path / "s"), _state(12), cadence_s=-1.5)


def test_failed_stream_clears_slo_cadence(tmp_path):
    """A stream stopped by a FAILED micro-commit must clear the SLO
    cadence gauge — a dashboard must not read 'delta stream active'
    while the stream is dead and exposure grows."""
    import shutil

    from tpusnap import slo

    slo.reset_tracker()
    root = str(tmp_path / "stream")
    state = _state(13)
    s = Snapshot.stream(root, state, cadence_s=3600)
    assert slo.tracker().snapshot_state()["stream_cadence_s"] == 3600
    # Sabotage the chain: the next increment's dedup base is gone.
    shutil.rmtree(os.path.join(root, member_name(0)))
    state["app"]["w"][0, 0] = 1.0
    with pytest.raises(Exception):
        s.commit_now()
    # commit_now propagates to the caller and keeps the stream open;
    # a WORKER/mark_step failure stops the stream and must clear the
    # gauge — simulate via the failure path directly.
    s._fail(RuntimeError("boom"), where="test")
    assert slo.tracker().snapshot_state()["stream_cadence_s"] is None
    with pytest.raises(RuntimeError, match="recovery point"):
        s.raise_if_failed()
    s.close(final_commit=False)  # idempotent on a failed stream


def test_commit_after_close_raises(tmp_path):
    s = Snapshot.stream(str(tmp_path / "s"), _state(9), cadence_s=3600)
    s.close(final_commit=False)
    with pytest.raises(RuntimeError, match="closed"):
        s.commit_now()
    # close is idempotent.
    assert s.close() is not None


def test_chain_lookups_stay_flat(tmp_path):
    """Writer-side collapse: every external location of a deep head
    points DIRECTLY at the member holding the bytes (one '../' hop) —
    lookups never chase intermediate members."""
    root = str(tmp_path / "stream")
    state = _state(10)
    s = Snapshot.stream(root, state, cadence_s=3600, max_chain=100)
    for i in range(4):
        state["app"]["w"][i, :] = float(i + 1)
        s.commit_now()
    s.close(final_commit=False)
    md = load_snapshot_metadata(os.path.join(root, member_name(4)))
    from tpusnap.inspect import iter_blobs
    from tpusnap.manifest_ops import external_reference_depth

    # The chain-resolution invariant: at any chain depth, every lookup
    # is ONE parent hop ("../<member>/<path>"), never a chase through
    # intermediates.
    assert external_reference_depth(md.manifest) <= 1
    for blob in iter_blobs(md.manifest):
        if blob.location.startswith("../"):
            member = blob.location.split("/")[1]
            assert os.path.isdir(os.path.join(root, member)), blob.location
