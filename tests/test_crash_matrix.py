"""Randomized crash-matrix soak for the two-phase commit protocol.

``test_crash.py`` kills one take mid-write; this matrix SIGKILLs a
take inside each distinct phase of the commit protocol, across seeded
jitter within each window, and asserts the ONE invariant the protocol
promises after every kill (reference invariant: metadata written last,
snapshot invisible until then — torchsnapshot snapshot.py commit
ordering):

    .snapshot_metadata exists  ⟺  the snapshot restores bit-exact
                                   (and scrubs clean)

Windows:
- ``staging``      — inside the staging pass (blob files partial);
- ``residual_io``  — after async_take returned, residual storage I/O
                     still draining in the background thread;
- ``metadata``     — inside the metadata writer, after a PARTIAL
                     temp-file write has been flushed to disk (the
                     temp+rename atomicity window);
- ``durable``      — TPUSNAP_DURABLE_COMMIT=1, inside the pre-barrier
                     durable flush of created dirents.
- ``journal``      — inside the take-journal write, before any blob
                     write (the lifecycle layer's own commit point).

Every window additionally asserts the LIFECYCLE classification
(``tpusnap.lifecycle.fsck_snapshot``): a committed directory fscks as
``committed``; an uncommitted one as ``torn`` (journal present) or
``empty`` — never misclassified as committed. Further down:
SIGKILL-mid-GC, salvage-resume of a torn take (≥50% byte reuse asserted
via the salvaged-bytes counter), and SIGKILL mid-materialize /
mid-retention.

Each (window, seed) run jitters the kill delay within the window, so
kills land at varied instants — including occasionally AFTER the
window completes, which exercises the other side of the ⟺ (metadata
present must imply a perfect restore). The child builds a
deterministic state from the seed so the parent can verify
bit-exactness independently.
"""

import os
import random
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from tpusnap import Snapshot, StateDict, verify_snapshot

_N_ARRAYS = 12
_ARR_SHAPE = (256, 256)  # ~256 KB each -> ~3 MB state, many blobs


def _expected_state(seed: int):
    return {
        f"w{i}": np.random.default_rng(seed * 1000 + i)
        .standard_normal(_ARR_SHAPE)
        .astype(np.float32)
        for i in range(_N_ARRAYS)
    }


_CHILD = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

window, path, seed = sys.argv[1], sys.argv[2], int(sys.argv[3])

_WINDOW_SLEEP = 1.2

def mark_and_linger():
    # The parent SIGKILLs at a seeded delay within this sleep; if the
    # jitter overshoots, execution proceeds and the take COMPLETES —
    # exercising the "metadata present => restores bit-exact" side.
    print("MARK", flush=True)
    time.sleep(_WINDOW_SLEEP)

import tpusnap.snapshot as snap_mod
import tpusnap.storage_plugins.fs as fs_mod
from tpusnap import Snapshot, StateDict

if window == "staging":
    from tpusnap.io_preparers import array as arr_mod
    orig_stage = arr_mod.ArrayBufferStager._stage_blocking
    fired = [False]
    def hooked(self):
        if not fired[0]:
            fired[0] = True
            mark_and_linger()
        return orig_stage(self)
    arr_mod.ArrayBufferStager._stage_blocking = hooked
elif window == "residual_io":
    # Slow every write so plenty of residual I/O is pending when
    # async_take returns.
    orig_write = fs_mod.FSStoragePlugin.write
    async def slow_write(self, write_io):
        import asyncio
        await asyncio.sleep(0.08)
        await orig_write(self, write_io)
    fs_mod.FSStoragePlugin.write = slow_write
elif window == "metadata":
    orig_meta = snap_mod._write_metadata
    def hooked_meta(storage, metadata, event_loop):
        # A partial, FLUSHED temp write first: the crash window the
        # temp+rename protocol exists for.
        tmp = os.path.join(path, ".snapshot_metadata.crashtmp")
        with open(tmp, "wb") as f:
            f.write(b"{" + b"x" * 100)
            f.flush()
            os.fsync(f.fileno())
        mark_and_linger()
        os.unlink(tmp)
        return orig_meta(storage, metadata, event_loop)
    snap_mod._write_metadata = hooked_meta
elif window == "durable":
    os.environ["TPUSNAP_DURABLE_COMMIT"] = "1"
    # Hook the async method, not the sync shim: the retry middleware
    # wrapper delegates flush_created_dirs() directly.
    orig_flush = fs_mod.FSStoragePlugin.flush_created_dirs
    async def hooked_flush(self):
        mark_and_linger()
        return await orig_flush(self)
    fs_mod.FSStoragePlugin.flush_created_dirs = hooked_flush
elif window == "journal":
    import tpusnap.lifecycle as lc_mod
    orig_journal = lc_mod.write_journal
    def hooked_journal(storage, event_loop, journal):
        mark_and_linger()
        return orig_journal(storage, event_loop, journal)
    lc_mod.write_journal = hooked_journal
else:
    raise SystemExit(f"unknown window {window}")

state = {
    f"w{i}": np.random.default_rng(seed * 1000 + i)
    .standard_normal((256, 256))
    .astype(np.float32)
    for i in range(12)
}
os.environ["TPUSNAP_DISABLE_BATCHING"] = "1"
# Tight heartbeat cadence: the flight recorder's flush rides the pump,
# so this bounds the black-box loss window the parent's timeline
# assertions depend on.
os.environ["TPUSNAP_HEARTBEAT_INTERVAL_S"] = "0.05"

if window == "residual_io":
    pending = Snapshot.async_take(path, {"app": StateDict(**state)})
    mark_and_linger()
    pending.wait()
else:
    Snapshot.take(path, {"app": StateDict(**state)})
print("DONE", flush=True)
"""


def _timeline_json(path: str):
    """In-process ``tpusnap timeline --json`` (spawning a fresh
    interpreter per matrix window would pay a jax import each)."""
    import contextlib
    import io
    import json

    from tpusnap.__main__ import main

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf), contextlib.redirect_stderr(
        io.StringIO()
    ):
        rc = main(["timeline", path, "--json"])
    out = buf.getvalue().strip()
    return rc, (json.loads(out) if out else None)


def _assert_timeline_postmortem(path, window, seed, kill_jitter_s) -> None:
    """Every SIGKILL window: the surviving flight sidecar must let
    ``tpusnap timeline`` name what the killed rank was doing.

    The journal window kills BEFORE the heartbeat pump (and with it the
    flight flusher) starts, so it legitimately has no flight data —
    that exercises the exit-3 leg of the contract instead."""
    rc, doc = _timeline_json(path)
    if window == "journal":
        assert rc in (3, 4), (window, seed, rc)
        return
    assert rc == 4, (window, seed, rc, doc)
    verdict = (doc or {}).get("verdict") or {}
    r0 = (verdict.get("ranks") or {}).get("0")
    assert r0 is not None, (window, seed, doc)
    # The last completed phase is always on record (the pump's first
    # flush lands before any kill window opens).
    assert r0.get("phase") is not None, (window, seed, r0)
    assert r0.get("last_event") is not None, (window, seed, r0)
    if window == "staging" and kill_jitter_s >= 0.15:
        # The kill landed ≥3 flush intervals into the staging sleep, so
        # the last flushed context must name the wedged op and the
        # planned byte denominator.
        assert r0.get("inflight_op") is not None, (window, seed, r0)
        assert (r0.get("bytes_planned") or 0) > 0, (window, seed, r0)


def _run_window(tmp_path, window: str, seed: int, extra_env=None) -> None:
    import select

    path = str(tmp_path / "snap")
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(extra_env or {}))
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD, window, path, str(seed)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        start_new_session=True,
    )
    try:
        # Wait for the child to enter the window — via select, so a
        # wedged-silent child hits the deadline instead of blocking
        # readline() forever (the pipe-wedge class _subproc.py exists
        # for).
        buf = ""
        deadline = time.monotonic() + 120
        marked = eof = False
        while time.monotonic() < deadline and not marked and not eof:
            ready, _, _ = select.select([proc.stdout], [], [], 0.5)
            if not ready:
                continue
            chunk = os.read(proc.stdout.fileno(), 4096).decode(
                "utf-8", errors="replace"
            )
            if chunk == "":
                eof = True
                break
            buf += chunk
            marked = "MARK" in buf
        if not marked:
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait(timeout=60)
            pytest.fail(
                f"child never reached window {window!r} "
                f"(eof={eof}): {buf[-2000:]}"
            )
        # Seeded jitter: kills land at varied instants inside (and
        # occasionally after) the window.
        kill_jitter_s = random.Random(seed).uniform(0.0, 1.5)
        time.sleep(kill_jitter_s)
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            proc.wait()

    from tpusnap.lifecycle import fsck_snapshot

    meta_path = os.path.join(path, ".snapshot_metadata")
    if os.path.exists(meta_path):
        # Committed ⟹ must be a complete, bit-exact, clean snapshot.
        expected = _expected_state(seed)
        target = {
            "app": StateDict(
                **{k: np.zeros(_ARR_SHAPE, np.float32) for k in expected}
            )
        }
        Snapshot(path).restore(target)
        for k, v in expected.items():
            assert np.array_equal(target["app"][k], v), (window, seed, k)
        assert verify_snapshot(path).clean, (window, seed)
        report = fsck_snapshot(path)
        assert report.state == "committed", (window, seed, report.summary())
        assert not report.missing_referenced, (window, seed)
    else:
        # Not committed ⟹ invisible.
        with pytest.raises(RuntimeError, match="not a snapshot"):
            Snapshot(path).metadata
        # ... and the lifecycle layer classifies the debris: a journal
        # marker makes it torn; pre-journal kills leave empty/foreign.
        report = fsck_snapshot(path)
        if os.path.exists(os.path.join(path, ".tpusnap/journal")):
            assert report.state == "torn", (window, seed, report.summary())
            # The black box survived the SIGKILL: `tpusnap timeline`
            # reconstructs what the killed rank was doing from the
            # flushed flight sidecar.
            _assert_timeline_postmortem(path, window, seed, kill_jitter_s)
        else:
            assert report.state in ("empty", "foreign"), (
                window,
                seed,
                report.summary(),
            )


_WINDOWS = ["staging", "residual_io", "metadata", "durable", "journal"]


@pytest.mark.soak
@pytest.mark.parametrize("window", _WINDOWS)
@pytest.mark.parametrize("seed", range(3))
def test_crash_matrix(tmp_path, window, seed):
    """Fast seeds: run in tier-1 so every commit window stays covered."""
    _run_window(tmp_path, window, seed)


@pytest.mark.soak
@pytest.mark.parametrize("window", ["metadata", "staging"])
def test_crash_matrix_pure_python(tmp_path, window):
    """The pure-Python fallback path (TPUSNAP_DISABLE_NATIVE=1) must keep
    the same crash guarantees — fallback writes have different syscall
    patterns and checksum algorithms, and the metadata self-checksum must
    verify under the fallback CRC too. Fast subset, runs in tier-1."""
    _run_window(tmp_path, window, 0, extra_env={"TPUSNAP_DISABLE_NATIVE": "1"})


@pytest.mark.soak
@pytest.mark.slow
@pytest.mark.parametrize("window", _WINDOWS)
@pytest.mark.parametrize("seed", range(3, 20))
def test_crash_matrix_seed_sweep(tmp_path, window, seed):
    """Wider jitter sweep of the same windows (excluded from tier-1)."""
    _run_window(tmp_path, window, seed)


# --------------------------------------------------- lifecycle windows


def _take_to_completion_or_kill(script: str, args, timeout=150, env=None):
    """Run a child snippet; return (returncode, output)."""
    full_env = dict(os.environ, JAX_PLATFORMS="cpu", **(env or {}))
    proc = subprocess.run(
        [sys.executable, "-c", script, *args],
        env=full_env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        timeout=timeout,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    return proc.returncode, proc.stdout


_SALVAGE_CHILD = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from tpusnap import Snapshot, StateDict

path, seed, crash_at = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
os.environ["TPUSNAP_DISABLE_BATCHING"] = "1"
state = {
    f"w{i}": np.random.default_rng(seed * 1000 + i)
    .standard_normal((256, 256))
    .astype(np.float32)
    for i in range(12)
}
# Deterministic SIGKILL after the Nth successful blob write — the
# chaos layer's registered crash point, no monkeypatching.
Snapshot.take(
    "chaos+fs://" + path,
    {"app": StateDict(**state)},
    storage_options={"fault_plan": {"seed": seed, "crash_after_op": ("write", crash_at)}},
)
print("UNEXPECTED_COMPLETION", flush=True)
"""


@pytest.mark.soak
@pytest.mark.chaos
@pytest.mark.parametrize("seed,crash_at", [(0, 8), (1, 10)])
def test_salvage_resume_of_torn_take(tmp_path, seed, crash_at):
    """SIGKILL a take after N blob writes, then retake the same path with
    the same state: fsck classifies the debris as torn, the retake reuses
    ≥50% of the torn take's intact bytes (asserted via the
    salvaged-bytes counter in the committed rollup), the result restores
    bit-exact and scrubs clean, and a sibling committed snapshot is
    untouched throughout."""
    from tpusnap.knobs import override_batching_disabled
    from tpusnap.lifecycle import fsck_snapshot

    path = str(tmp_path / "snap")
    sibling = str(tmp_path / "sibling")
    expected = _expected_state(seed)
    with override_batching_disabled(True):
        Snapshot.take(sibling, {"app": StateDict(**expected)})

        rc, out = _take_to_completion_or_kill(
            _SALVAGE_CHILD, [path, str(seed), str(crash_at)]
        )
        assert rc == -signal.SIGKILL, (rc, out[-2000:])

        report = fsck_snapshot(path)
        assert report.state == "torn", report.summary()
        # Record flushes coalesce under concurrent writes, so the count
        # can trail the kill point by a few — but never collapse.
        assert report.salvage_records >= crash_at // 2, report.summary()
        assert report.salvage_bytes_present > 0

        # Salvage-retake in this process so the counters are observable
        # both live and in the committed rollup.
        import tpusnap.telemetry as telemetry

        before = telemetry.counter_value("salvage.bytes_salvaged")
        Snapshot.take(path, {"app": StateDict(**expected)})
        salvaged = telemetry.counter_value("salvage.bytes_salvaged") - before
        assert salvaged >= 0.5 * report.salvage_bytes_present, (
            salvaged,
            report.salvage_bytes_present,
        )
        rollup = (Snapshot(path).metadata.extras or {}).get("telemetry", {})
        assert rollup.get("counters", {}).get("salvage.bytes_salvaged", 0) == salvaged

    assert fsck_snapshot(path).state == "committed"
    target = {
        "app": StateDict(**{k: np.zeros_like(v) for k, v in expected.items()})
    }
    Snapshot(path).restore(target)
    for k, v in expected.items():
        assert np.array_equal(target["app"][k], v), k
    assert verify_snapshot(path).clean
    # The sibling committed snapshot was never touched.
    assert fsck_snapshot(sibling).state == "committed"
    assert verify_snapshot(sibling).clean


_PIPELINED_DRAIN_CHILD = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from tpusnap import Snapshot, StateDict

path, seed, crash_at = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
os.environ["TPUSNAP_DISABLE_BATCHING"] = "1"
# Tight staging window: async_take returns control after ~one blob and
# the deterministic SIGKILL (after the crash_at-th successful blob
# write) lands inside the BACKGROUND drain of the residual windows.
os.environ["TPUSNAP_ASYNC_STAGE_WINDOW_BYTES"] = str(1 << 19)
state = {
    f"w{i}": np.random.default_rng(seed * 1000 + i)
    .standard_normal((256, 256))
    .astype(np.float32)
    for i in range(12)
}
pending = Snapshot.async_take(
    "chaos+fs://" + path,
    {"app": StateDict(**state)},
    storage_options={"fault_plan": {"seed": seed, "crash_after_op": ("write", crash_at)}},
)
print("RETURNED", flush=True)
pending.wait()
print("UNEXPECTED_COMPLETION", flush=True)
"""


@pytest.mark.chaos
@pytest.mark.pipelined
def test_sigkill_in_pipelined_async_drain_is_torn_and_salvageable(tmp_path):
    """SIGKILL inside the background drain of a PIPELINED async take
    (control already returned to "training", residual windows still
    staging/writing): fsck classifies the debris as torn, and a retake
    salvage-resumes the windows the drain had already written instead
    of rewriting them from byte zero."""
    from tpusnap.knobs import override_batching_disabled
    from tpusnap.lifecycle import fsck_snapshot

    seed, crash_at = 3, 6
    path = str(tmp_path / "snap")
    expected = _expected_state(seed)

    rc, out = _take_to_completion_or_kill(
        _PIPELINED_DRAIN_CHILD, [path, str(seed), str(crash_at)]
    )
    assert rc == -signal.SIGKILL, (rc, out[-2000:])
    # The kill landed AFTER control returned (the pipelined contract)
    # and before the commit.
    assert "RETURNED" in out, out[-2000:]
    assert "UNEXPECTED_COMPLETION" not in out, out[-2000:]

    report = fsck_snapshot(path)
    assert report.state == "torn", report.summary()
    assert report.salvage_records >= crash_at // 2, report.summary()
    assert report.salvage_bytes_present > 0

    import tpusnap.telemetry as telemetry

    before = telemetry.counter_value("salvage.bytes_salvaged")
    with override_batching_disabled(True):
        Snapshot.take(path, {"app": StateDict(**expected)})
    salvaged = telemetry.counter_value("salvage.bytes_salvaged") - before
    # The already-written windows were reused, not rewritten.
    assert salvaged >= 0.5 * report.salvage_bytes_present, (
        salvaged,
        report.salvage_bytes_present,
    )
    assert fsck_snapshot(path).state == "committed"
    target = {
        "app": StateDict(**{k: np.zeros_like(v) for k, v in expected.items()})
    }
    Snapshot(path).restore(target)
    for k, v in expected.items():
        assert np.array_equal(target["app"][k], v), k
    assert verify_snapshot(path).clean


_GC_CHILD = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")

path = sys.argv[1]
import tpusnap.storage_plugins.fs as fs_mod

orig_delete = fs_mod.FSStoragePlugin.delete
calls = [0]
async def slow_delete(self, p):
    calls[0] += 1
    if calls[0] == 2:
        print("MARK", flush=True)
        time.sleep(1.2)
    await orig_delete(self, p)
fs_mod.FSStoragePlugin.delete = slow_delete

from tpusnap.lifecycle import gc_snapshot
gc_snapshot(path, dry_run=False)
print("DONE", flush=True)
"""


@pytest.mark.soak
def test_crash_mid_gc(tmp_path):
    """SIGKILL inside gc's delete loop: the snapshot stays committed and
    bit-exact, already-deleted orphans stay gone, and a second gc
    reclaims exactly the survivors."""
    import select

    from tpusnap.lifecycle import fsck_snapshot, gc_snapshot

    path = str(tmp_path / "snap")
    expected = _expected_state(0)
    Snapshot.take(path, {"app": StateDict(**expected)})
    orphans = {f"orphan_{i}.blob": 1000 + i for i in range(5)}
    for name, size in orphans.items():
        with open(os.path.join(path, name), "wb") as f:
            f.write(b"x" * size)
    report = fsck_snapshot(path)
    assert set(report.orphans) == set(orphans)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", _GC_CHILD, path],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        start_new_session=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    try:
        buf = ""
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and "MARK" not in buf:
            ready, _, _ = select.select([proc.stdout], [], [], 0.5)
            if not ready:
                continue
            chunk = os.read(proc.stdout.fileno(), 4096).decode(
                "utf-8", errors="replace"
            )
            if chunk == "":
                break
            buf += chunk
        assert "MARK" in buf, buf[-2000:]
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait()

    # Mid-GC crash: committed and clean, some orphans possibly gone.
    report = fsck_snapshot(path)
    assert report.state == "committed", report.summary()
    assert not report.missing_referenced
    remaining = set(report.orphans)
    assert remaining <= set(orphans)
    assert verify_snapshot(path).clean
    # Second gc reclaims exactly the survivors.
    g = gc_snapshot(path, dry_run=False)
    assert set(g.reclaimed) == remaining and not g.errors
    assert not fsck_snapshot(path).orphans
    target = {
        "app": StateDict(**{k: np.zeros_like(v) for k, v in expected.items()})
    }
    Snapshot(path).restore(target)
    for k, v in expected.items():
        assert np.array_equal(target["app"][k], v), k


_MATERIALIZE_CHILD = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")

path = sys.argv[1]
import tpusnap.storage_plugins.fs as fs_mod

orig_write = fs_mod.FSStoragePlugin.write
fired = [False]
async def slow_write(self, write_io):
    if not fired[0]:
        fired[0] = True
        print("MARK", flush=True)
        time.sleep(1.2)
    await orig_write(self, write_io)
fs_mod.FSStoragePlugin.write = slow_write

from tpusnap.inspect import materialize_snapshot
materialize_snapshot(path)
print("DONE", flush=True)
"""

_RETAIN_CHILD = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")

root = sys.argv[1]
import tpusnap.retention as ret_mod

orig_rmtree = ret_mod.shutil.rmtree
def slow_rmtree(p, *a, **k):
    print("MARK", flush=True)
    time.sleep(1.2)
    return orig_rmtree(p, *a, **k)
ret_mod.shutil.rmtree = slow_rmtree

from tpusnap.retention import apply_retention
apply_retention(root, 2)
print("DONE", flush=True)
"""


def _run_marked_child(script, args, timeout=120):
    """Start a child, wait for MARK, SIGKILL at a short delay."""
    import select

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", script, *args],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        start_new_session=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    try:
        buf = ""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and "MARK" not in buf:
            ready, _, _ = select.select([proc.stdout], [], [], 0.5)
            if not ready:
                continue
            chunk = os.read(proc.stdout.fileno(), 4096).decode(
                "utf-8", errors="replace"
            )
            if chunk == "":
                break
            buf += chunk
        assert "MARK" in buf, buf[-2000:]
        time.sleep(0.3)
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait(timeout=60)
        return buf
    finally:
        if proc.poll() is None:
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait()


def _restorable(path, expected):
    target = {
        "app": StateDict(**{k: np.zeros_like(v) for k, v in expected.items()})
    }
    Snapshot(path).restore(target)
    for k, v in expected.items():
        assert np.array_equal(target["app"][k], v), k


@pytest.mark.soak
def test_crash_mid_materialize(tmp_path):
    """SIGKILL inside materialize's blob-copy phase: the increment stays
    committed and still references its (intact) base; the half-copied
    blobs are fsck-visible orphans gc can reclaim; a retried materialize
    completes and cuts the base references."""
    from tpusnap.lifecycle import fsck_snapshot, gc_snapshot

    base = str(tmp_path / "base")
    inc = str(tmp_path / "inc")
    state = _expected_state(1)
    Snapshot.take(base, {"app": StateDict(**state)})
    changed = dict(state, w0=state["w0"] + 1.0)
    Snapshot.take(inc, {"app": StateDict(**changed)}, incremental_from=base)
    assert Snapshot(inc).metadata.base_roots, "increment must reference base"

    _run_marked_child(_MATERIALIZE_CHILD, [inc])

    # Mid-copy crash: both snapshots still committed; the increment
    # still references the base (manifest not rewritten) and restores.
    for p in (base, inc):
        report = fsck_snapshot(p)
        assert report.state == "committed", (p, report.summary())
        assert not report.missing_referenced
    assert Snapshot(inc).metadata.base_roots, "references must survive the crash"
    _restorable(inc, changed)
    # Partially copied blobs are unreferenced orphans; reclaim them.
    gc_snapshot(inc, dry_run=False)
    # Retry completes.
    from tpusnap.inspect import materialize_snapshot

    stats = materialize_snapshot(inc)
    assert stats["blobs_copied"] > 0
    assert Snapshot(inc).metadata.base_roots is None
    _restorable(inc, changed)
    assert verify_snapshot(inc).clean
    assert not fsck_snapshot(inc).missing_referenced


@pytest.mark.soak
def test_crash_mid_retention(tmp_path):
    """SIGKILL between retention's materialize phase and its deletions:
    no kept increment may ever reference a deleted base. After the
    crash every kept snapshot restores; a re-run converges."""
    from tpusnap.lifecycle import fsck_snapshot
    from tpusnap.retention import apply_retention

    root = tmp_path / "snaps"
    root.mkdir()
    s1, s2, s3 = (str(root / f"s{i}") for i in (1, 2, 3))
    state = _expected_state(2)
    Snapshot.take(s1, {"app": StateDict(**state)})
    changed = dict(state, w1=state["w1"] * 2.0)
    Snapshot.take(s2, {"app": StateDict(**changed)}, incremental_from=s1)
    state3 = dict(state, w2=state["w2"] - 3.0)
    Snapshot.take(s3, {"app": StateDict(**state3)})

    _run_marked_child(_RETAIN_CHILD, [str(root)])

    # Whatever the crash point: every surviving committed snapshot must
    # restore — in particular s2, whose base s1 was doomed. Retention
    # materializes BEFORE deleting, so s2 is either still base-backed
    # (s1 present) or already self-contained.
    assert os.path.exists(os.path.join(s2, ".snapshot_metadata"))
    report = fsck_snapshot(s2)
    assert report.state == "committed"
    assert not report.missing_referenced, report.summary()
    if Snapshot(s2).metadata.base_roots:
        assert os.path.exists(os.path.join(s1, ".snapshot_metadata")), (
            "kept increment references a deleted base"
        )
    _restorable(s2, changed)
    _restorable(s3, state3)
    # Re-run converges: 2 snapshots kept, everything restorable.
    apply_retention(str(root), 2)
    assert sorted(os.listdir(root)) == ["s2", "s3"]
    _restorable(s2, changed)
    _restorable(s3, state3)
    assert verify_snapshot(s2).clean and verify_snapshot(s3).clean


# ---------------------------------------------------------------- abort


def _world_abort_mid_take(snap_dir):
    """Rank 1's storage write raises a FATAL error mid-take; rank 0 must
    exit with TakeAbortedError in seconds (not the barrier timeout), no
    ``.snapshot_metadata`` may exist, and the SAME path must be usable
    for a subsequent take."""
    import time as _time

    import numpy as np

    import tpusnap.storage_plugins.fs as fs_mod
    from tpusnap import Snapshot, StateDict, TakeAbortedError, verify_snapshot
    from tpusnap.comm import get_communicator

    comm = get_communicator()
    state = {f"w{i}": np.full((2048,), float(i), np.float32) for i in range(6)}
    orig_write = fs_mod.FSStoragePlugin.write
    if comm.rank == 1:

        async def bad_write(self, write_io):
            raise RuntimeError("injected fatal write")

        fs_mod.FSStoragePlugin.write = bad_write
    t0 = _time.monotonic()
    try:
        Snapshot.take(snap_dir, {"app": StateDict(**state)})
        raise AssertionError("take should have failed")
    except TakeAbortedError:
        dt = _time.monotonic() - t0
        assert comm.rank == 0, "only the peer should see TakeAbortedError"
        assert dt < 30, f"abort propagation took {dt:.1f}s"
        print(f"ABORT_OK {dt:.2f}", flush=True)
    except RuntimeError as e:
        assert comm.rank == 1 and "injected fatal write" in str(e), e
    assert not os.path.exists(os.path.join(snap_dir, ".snapshot_metadata"))
    # The failing rank best-effort deleted its staged blobs; the path is
    # immediately reusable.
    fs_mod.FSStoragePlugin.write = orig_write
    Snapshot.take(snap_dir, {"app": StateDict(**state)})
    if comm.rank == 0:
        assert verify_snapshot(snap_dir).clean
        target = {
            "app": StateDict(
                **{k: np.zeros_like(v) for k, v in state.items()}
            )
        }
        Snapshot(snap_dir).restore(target)
        for k, v in state.items():
            assert np.array_equal(target["app"][k], v), k
        print("REUSE_OK", flush=True)


@pytest.mark.soak
@pytest.mark.distributed
def test_abort_propagates_across_ranks(tmp_path):
    from tpusnap.test_utils import run_subprocess_world

    outs = run_subprocess_world(
        _world_abort_mid_take,
        world_size=2,
        args=[str(tmp_path / "snap")],
        timeout=150,
    )
    assert any("ABORT_OK" in o for o in outs), outs
    assert any("REUSE_OK" in o for o in outs), outs


# ------------------------------------------------ delta-stream windows


_DELTA_CHILD = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["TPUSNAP_DISABLE_BATCHING"] = "1"
os.environ["TPUSNAP_HEARTBEAT_INTERVAL_S"] = "0.05"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

window, root, seed = sys.argv[1], sys.argv[2], int(sys.argv[3])

_WINDOW_SLEEP = 1.2

def mark_and_linger():
    print("MARK", flush=True)
    time.sleep(_WINDOW_SLEEP)

import tpusnap.storage_plugins.fs as fs_mod
import tpusnap.inspect as inspect_mod
from tpusnap import Snapshot, StateDict

if window == "delta_micro":
    # SIGKILL inside a micro-commit's storage write, after >= 1 delta
    # already committed (so recovery lands on a delta, not the base).
    orig_write = fs_mod.FSStoragePlugin.write
    fired = [False]
    async def hooked(self, write_io):
        root_s = getattr(self, "root", "")
        if (
            not fired[0]
            and "delta-0000" in root_s
            and not root_s.endswith("delta-000001")
            and not write_io.path.startswith(".tpusnap")
        ):
            fired[0] = True
            mark_and_linger()
        await orig_write(self, write_io)
    fs_mod.FSStoragePlugin.write = hooked
elif window == "delta_compact":
    orig_mat = inspect_mod.materialize_snapshot
    def hooked_mat(*a, **kw):
        mark_and_linger()
        return orig_mat(*a, **kw)
    inspect_mod.materialize_snapshot = hooked_mat
elif window != "delta_between":
    raise SystemExit(f"unknown window {window}")

# Self-describing deterministic state: pattern(seed) + step. The
# parent recomputes the expected arrays for ANY committed step k and
# asserts the replayed restore is bit-identical.
pattern = (
    np.random.default_rng(seed).standard_normal((256, 256)).astype(np.float32)
)
state = {"app": StateDict(w=pattern.copy(), step=0)}
max_chain = 2 if window == "delta_compact" else 100
stream = Snapshot.stream(root, state, cadence_s=3600, max_chain=max_chain)
for k in range(1, 8):
    state["app"]["w"] = pattern + np.float32(k)
    state["app"]["step"] = k
    stream.commit_now()
    print(f"COMMIT {stream.seq} {k}", flush=True)
    if window == "delta_between" and k == 3:
        mark_and_linger()
print("DONE", flush=True)
stream.close(final_commit=False)
"""


def _run_delta_window(tmp_path, window: str, seed: int) -> None:
    """SIGKILL a delta stream inside ``window``; assert the chain's
    crash contract: fsck classifies every member, `timeline` names the
    in-flight delta state of a torn tail, and replaying base +
    committed chain restores BIT-IDENTICALLY to the last committed
    micro-commit's reference state (never older than one commit)."""
    import re
    import select

    root = str(tmp_path / "stream")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", _DELTA_CHILD, window, root, str(seed)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        start_new_session=True,
    )
    try:
        buf = ""
        deadline = time.monotonic() + 120
        marked = eof = False
        while time.monotonic() < deadline and not marked and not eof:
            ready, _, _ = select.select([proc.stdout], [], [], 0.5)
            if not ready:
                continue
            chunk = os.read(proc.stdout.fileno(), 4096).decode(
                "utf-8", errors="replace"
            )
            if chunk == "":
                eof = True
                break
            buf += chunk
            marked = "MARK" in buf
        if not marked:
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait(timeout=60)
            pytest.fail(
                f"child never reached window {window!r} (eof={eof}): "
                f"{buf[-2000:]}"
            )
        kill_jitter_s = random.Random(seed).uniform(0.0, 0.8)
        time.sleep(kill_jitter_s)
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            proc.wait()

    from tpusnap import resolve_chain
    from tpusnap.lifecycle import fsck_snapshot

    # The child printed "COMMIT <seq> <step>" after each completed
    # commit; recovery must land at least there.
    committed_steps = [
        int(m.group(2)) for m in re.finditer(r"COMMIT (\d+) (\d+)", buf)
    ]
    last_printed_step = committed_steps[-1] if committed_steps else 0

    rep = resolve_chain(root)
    assert rep.head is not None, (window, seed, buf[-500:], rep.summary())
    head_path = rep.head_path

    # 1. Replay restore is bit-identical to the last committed
    # micro-commit's reference state (self-describing: step rides the
    # snapshot, so an unprinted trailing commit verifies too).
    pattern = (
        np.random.default_rng(seed)
        .standard_normal((256, 256))
        .astype(np.float32)
    )
    target = {
        "app": StateDict(w=np.zeros((256, 256), np.float32), step=-1)
    }
    Snapshot(head_path).restore(target)
    k = target["app"]["step"]
    assert k >= last_printed_step, (
        f"recovery lost a committed micro-commit: restored step {k} < "
        f"last printed {last_printed_step}"
    )
    expected = pattern + np.float32(k) if k > 0 else pattern
    assert np.array_equal(target["app"]["w"], expected), (window, seed, k)
    assert verify_snapshot(head_path).clean, (window, seed)

    # 2. fsck classification of every member + the torn tail contract.
    head_report = fsck_snapshot(head_path)
    assert head_report.state == "committed", head_report.summary()
    assert head_report.delta is not None, head_report.summary()
    if rep.torn_tail:
        torn_path = os.path.join(root, rep.torn_tail)
        torn_report = fsck_snapshot(torn_path)
        assert torn_report.state == "torn", torn_report.summary()
        assert torn_report.delta is not None, (
            "torn tail lost its chain membership",
            torn_report.summary(),
        )
        assert "torn delta micro-commit" in torn_report.summary()
        # 3. `timeline` names the in-flight delta state (exit 4 =
        # torn-path post-mortem; 3 = killed before the first flight
        # flush, the documented no-data leg).
        rc, doc = _timeline_json(torn_path)
        assert rc in (3, 4), (window, seed, rc)
        if rc == 4:
            assert (doc or {}).get("delta"), doc
            assert doc["delta"].get("seq") is not None, doc
    # Root-level fsck honors the chain exit contract.
    from tpusnap.__main__ import main as _main

    import contextlib
    import io

    with contextlib.redirect_stdout(io.StringIO()):
        rc = _main(["fsck", root])
    assert rc == (4 if rep.torn_tail else 0), (window, seed, rc)


@pytest.mark.soak
@pytest.mark.parametrize(
    "window", ["delta_micro", "delta_between", "delta_compact"]
)
@pytest.mark.parametrize("seed", range(2))
def test_delta_crash_matrix(tmp_path, window, seed):
    """SIGKILL inside a micro-commit, between micro-commits, and
    mid-chain-compaction (tier-1 fast seeds)."""
    _run_delta_window(tmp_path, window, seed)


@pytest.mark.soak
@pytest.mark.slow
@pytest.mark.parametrize(
    "window", ["delta_micro", "delta_between", "delta_compact"]
)
@pytest.mark.parametrize("seed", range(2, 10))
def test_delta_crash_matrix_seed_sweep(tmp_path, window, seed):
    _run_delta_window(tmp_path, window, seed)
