"""Randomized crash-matrix soak for the two-phase commit protocol.

``test_crash.py`` kills one take mid-write; this matrix SIGKILLs a
take inside each distinct phase of the commit protocol, across seeded
jitter within each window, and asserts the ONE invariant the protocol
promises after every kill (reference invariant: metadata written last,
snapshot invisible until then — torchsnapshot snapshot.py commit
ordering):

    .snapshot_metadata exists  ⟺  the snapshot restores bit-exact
                                   (and scrubs clean)

Windows:
- ``staging``      — inside the staging pass (blob files partial);
- ``residual_io``  — after async_take returned, residual storage I/O
                     still draining in the background thread;
- ``metadata``     — inside the metadata writer, after a PARTIAL
                     temp-file write has been flushed to disk (the
                     temp+rename atomicity window);
- ``durable``      — TPUSNAP_DURABLE_COMMIT=1, inside the pre-barrier
                     durable flush of created dirents.

Each (window, seed) run jitters the kill delay within the window, so
kills land at varied instants — including occasionally AFTER the
window completes, which exercises the other side of the ⟺ (metadata
present must imply a perfect restore). The child builds a
deterministic state from the seed so the parent can verify
bit-exactness independently.
"""

import os
import random
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from tpusnap import Snapshot, StateDict, verify_snapshot

_N_ARRAYS = 12
_ARR_SHAPE = (256, 256)  # ~256 KB each -> ~3 MB state, many blobs


def _expected_state(seed: int):
    return {
        f"w{i}": np.random.default_rng(seed * 1000 + i)
        .standard_normal(_ARR_SHAPE)
        .astype(np.float32)
        for i in range(_N_ARRAYS)
    }


_CHILD = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

window, path, seed = sys.argv[1], sys.argv[2], int(sys.argv[3])

_WINDOW_SLEEP = 1.2

def mark_and_linger():
    # The parent SIGKILLs at a seeded delay within this sleep; if the
    # jitter overshoots, execution proceeds and the take COMPLETES —
    # exercising the "metadata present => restores bit-exact" side.
    print("MARK", flush=True)
    time.sleep(_WINDOW_SLEEP)

import tpusnap.snapshot as snap_mod
import tpusnap.storage_plugins.fs as fs_mod
from tpusnap import Snapshot, StateDict

if window == "staging":
    from tpusnap.io_preparers import array as arr_mod
    orig_stage = arr_mod.ArrayBufferStager._stage_blocking
    fired = [False]
    def hooked(self):
        if not fired[0]:
            fired[0] = True
            mark_and_linger()
        return orig_stage(self)
    arr_mod.ArrayBufferStager._stage_blocking = hooked
elif window == "residual_io":
    # Slow every write so plenty of residual I/O is pending when
    # async_take returns.
    orig_write = fs_mod.FSStoragePlugin.write
    async def slow_write(self, write_io):
        import asyncio
        await asyncio.sleep(0.08)
        await orig_write(self, write_io)
    fs_mod.FSStoragePlugin.write = slow_write
elif window == "metadata":
    orig_meta = snap_mod._write_metadata
    def hooked_meta(storage, metadata, event_loop):
        # A partial, FLUSHED temp write first: the crash window the
        # temp+rename protocol exists for.
        tmp = os.path.join(path, ".snapshot_metadata.crashtmp")
        with open(tmp, "wb") as f:
            f.write(b"{" + b"x" * 100)
            f.flush()
            os.fsync(f.fileno())
        mark_and_linger()
        os.unlink(tmp)
        return orig_meta(storage, metadata, event_loop)
    snap_mod._write_metadata = hooked_meta
elif window == "durable":
    os.environ["TPUSNAP_DURABLE_COMMIT"] = "1"
    # Hook the async method, not the sync shim: the retry middleware
    # wrapper delegates flush_created_dirs() directly.
    orig_flush = fs_mod.FSStoragePlugin.flush_created_dirs
    async def hooked_flush(self):
        mark_and_linger()
        return await orig_flush(self)
    fs_mod.FSStoragePlugin.flush_created_dirs = hooked_flush
else:
    raise SystemExit(f"unknown window {window}")

state = {
    f"w{i}": np.random.default_rng(seed * 1000 + i)
    .standard_normal((256, 256))
    .astype(np.float32)
    for i in range(12)
}
os.environ["TPUSNAP_DISABLE_BATCHING"] = "1"

if window == "residual_io":
    pending = Snapshot.async_take(path, {"app": StateDict(**state)})
    mark_and_linger()
    pending.wait()
else:
    Snapshot.take(path, {"app": StateDict(**state)})
print("DONE", flush=True)
"""


def _run_window(tmp_path, window: str, seed: int) -> None:
    import select

    path = str(tmp_path / "snap")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD, window, path, str(seed)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        start_new_session=True,
    )
    try:
        # Wait for the child to enter the window — via select, so a
        # wedged-silent child hits the deadline instead of blocking
        # readline() forever (the pipe-wedge class _subproc.py exists
        # for).
        buf = ""
        deadline = time.monotonic() + 120
        marked = eof = False
        while time.monotonic() < deadline and not marked and not eof:
            ready, _, _ = select.select([proc.stdout], [], [], 0.5)
            if not ready:
                continue
            chunk = os.read(proc.stdout.fileno(), 4096).decode(
                "utf-8", errors="replace"
            )
            if chunk == "":
                eof = True
                break
            buf += chunk
            marked = "MARK" in buf
        if not marked:
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait(timeout=60)
            pytest.fail(
                f"child never reached window {window!r} "
                f"(eof={eof}): {buf[-2000:]}"
            )
        # Seeded jitter: kills land at varied instants inside (and
        # occasionally after) the window.
        time.sleep(random.Random(seed).uniform(0.0, 1.5))
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            proc.wait()

    meta_path = os.path.join(path, ".snapshot_metadata")
    if os.path.exists(meta_path):
        # Committed ⟹ must be a complete, bit-exact, clean snapshot.
        expected = _expected_state(seed)
        target = {
            "app": StateDict(
                **{k: np.zeros(_ARR_SHAPE, np.float32) for k in expected}
            )
        }
        Snapshot(path).restore(target)
        for k, v in expected.items():
            assert np.array_equal(target["app"][k], v), (window, seed, k)
        assert verify_snapshot(path).clean, (window, seed)
    else:
        # Not committed ⟹ invisible.
        with pytest.raises(RuntimeError, match="not a snapshot"):
            Snapshot(path).metadata


@pytest.mark.soak
@pytest.mark.parametrize("window", ["staging", "residual_io", "metadata", "durable"])
@pytest.mark.parametrize("seed", range(3))
def test_crash_matrix(tmp_path, window, seed):
    """Fast seeds: run in tier-1 so every commit window stays covered."""
    _run_window(tmp_path, window, seed)


@pytest.mark.soak
@pytest.mark.slow
@pytest.mark.parametrize("window", ["staging", "residual_io", "metadata", "durable"])
@pytest.mark.parametrize("seed", range(3, 20))
def test_crash_matrix_seed_sweep(tmp_path, window, seed):
    """Wider jitter sweep of the same windows (excluded from tier-1)."""
    _run_window(tmp_path, window, seed)


# ---------------------------------------------------------------- abort


def _world_abort_mid_take(snap_dir):
    """Rank 1's storage write raises a FATAL error mid-take; rank 0 must
    exit with TakeAbortedError in seconds (not the barrier timeout), no
    ``.snapshot_metadata`` may exist, and the SAME path must be usable
    for a subsequent take."""
    import time as _time

    import numpy as np

    import tpusnap.storage_plugins.fs as fs_mod
    from tpusnap import Snapshot, StateDict, TakeAbortedError, verify_snapshot
    from tpusnap.comm import get_communicator

    comm = get_communicator()
    state = {f"w{i}": np.full((2048,), float(i), np.float32) for i in range(6)}
    orig_write = fs_mod.FSStoragePlugin.write
    if comm.rank == 1:

        async def bad_write(self, write_io):
            raise RuntimeError("injected fatal write")

        fs_mod.FSStoragePlugin.write = bad_write
    t0 = _time.monotonic()
    try:
        Snapshot.take(snap_dir, {"app": StateDict(**state)})
        raise AssertionError("take should have failed")
    except TakeAbortedError:
        dt = _time.monotonic() - t0
        assert comm.rank == 0, "only the peer should see TakeAbortedError"
        assert dt < 30, f"abort propagation took {dt:.1f}s"
        print(f"ABORT_OK {dt:.2f}", flush=True)
    except RuntimeError as e:
        assert comm.rank == 1 and "injected fatal write" in str(e), e
    assert not os.path.exists(os.path.join(snap_dir, ".snapshot_metadata"))
    # The failing rank best-effort deleted its staged blobs; the path is
    # immediately reusable.
    fs_mod.FSStoragePlugin.write = orig_write
    Snapshot.take(snap_dir, {"app": StateDict(**state)})
    if comm.rank == 0:
        assert verify_snapshot(snap_dir).clean
        target = {
            "app": StateDict(
                **{k: np.zeros_like(v) for k, v in state.items()}
            )
        }
        Snapshot(snap_dir).restore(target)
        for k, v in state.items():
            assert np.array_equal(target["app"][k], v), k
        print("REUSE_OK", flush=True)


@pytest.mark.soak
@pytest.mark.distributed
def test_abort_propagates_across_ranks(tmp_path):
    from tpusnap.test_utils import run_subprocess_world

    outs = run_subprocess_world(
        _world_abort_mid_take,
        world_size=2,
        args=[str(tmp_path / "snap")],
        timeout=150,
    )
    assert any("ABORT_OK" in o for o in outs), outs
    assert any("REUSE_OK" in o for o in outs), outs
