"""Memory-budgeted (tiled) reads — the reference's signature
memory-bounded load (io_preparers/tensor.py:126-179, validated by
benchmarks/load_tensor/main.py:24-61): ``read_object`` of an array far
larger than the budget must stream byte-ranged tiles, keeping peak RSS
near the budget instead of materializing a second full copy.
"""

import numpy as np
import pytest

from tpusnap import Snapshot, StateDict
from tpusnap.knobs import override_slab_size_threshold_bytes
from tpusnap.rss_profiler import measure_rss_deltas

MB = 1024 * 1024


@pytest.fixture()
def big_snapshot(tmp_path):
    rng = np.random.default_rng(3)
    arr = rng.integers(0, 2**16, (192, 256 * 1024), dtype=np.uint16)  # 96 MiB
    Snapshot.take(str(tmp_path / "snap"), {"s": StateDict(w=arr)})
    return str(tmp_path / "snap"), arr


def test_tiled_read_correct_and_budgeted(big_snapshot):
    path, arr = big_snapshot
    budget = 8 * MB
    rss_deltas = []
    with measure_rss_deltas(rss_deltas):
        out = Snapshot(path).read_object(
            "0/s/w", memory_budget_bytes=budget
        )
    assert np.array_equal(out, arr)
    # Prove the tiled path ran: one 96 MiB tensor under an 8 MiB budget
    # must split into byte-ranged tile reads, not one dense read. Tiles
    # align UP to the 16 MiB checksum-tile boundary (integrity over
    # budget), so the floor is nbytes / 16 MiB reads.
    from tpusnap.scheduler import LAST_EXECUTION_STATS

    assert LAST_EXECUTION_STATS["read"]["reqs"] >= arr.nbytes // (16 * MB)
    # Peak transient RSS beyond the (unavoidable) full-size destination
    # must stay near the effective tile size: destination + in-flight
    # tiles (the scheduler admits <= budget of tiles plus one always-
    # allowed over-budget item; tiles here are one 16 MiB checksum tile).
    # The bound still catches the failure mode (a second full 96 MiB
    # copy).
    # Headroom covers allocator noise from earlier tests in the process
    # (retained free lists make the RSS delta start from a shifted
    # baseline); the guarded failure mode — a second full-size copy —
    # would show >= 2x arr.nbytes (192 MiB), far above this bound.
    peak = max(rss_deltas, default=0)
    assert peak < arr.nbytes + 8 * budget, (
        f"peak RSS delta {peak / MB:.0f} MiB exceeds destination+8x budget"
    )


def test_tiled_read_in_place_target(big_snapshot):
    path, arr = big_snapshot
    target = np.zeros_like(arr)
    out = Snapshot(path).read_object(
        "0/s/w", obj_out=target, memory_budget_bytes=8 * MB
    )
    assert out is target
    assert np.array_equal(target, arr)


def test_tiled_read_of_slab_resident_entry(tmp_path):
    """Byte-ranged source: a batched (slab-resident) tensor read with a
    budget smaller than the tensor must tile WITHIN the slab's byte range."""
    rng = np.random.default_rng(4)
    arrs = {
        f"w{i}": rng.integers(0, 2**16, (64, 64 * 1024), dtype=np.uint16)
        for i in range(3)
    }  # 8 MiB each — small enough to batch under a shrunken threshold
    with override_slab_size_threshold_bytes(64 * MB):
        Snapshot.take(str(tmp_path / "snap"), {"s": StateDict(**arrs)})
    snap = Snapshot(str(tmp_path / "snap"))
    manifest = snap.get_manifest()
    entry = manifest["0/s/w1"]
    assert entry.byte_range is not None, "arrays were not slab-batched"
    out = snap.read_object("0/s/w1", memory_budget_bytes=1 * MB)
    assert np.array_equal(out, arrs["w1"])


def test_unbudgeted_read_object_unchanged(big_snapshot):
    path, arr = big_snapshot
    out = Snapshot(path).read_object("0/s/w")
    assert np.array_equal(out, arr)
