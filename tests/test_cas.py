"""Content-addressed cross-run blob store (tpusnap/cas.py).

Covers the acceptance criteria end to end:

- two jobs taking identical content through the store pay ~1× storage
  (snapshots hold refs, the store holds one blob per unique content),
  and every snapshot restores bit-exact through its refs;
- the intent/ref/grace state machine under a fake clock: fresh intents
  protect keys, stale intents and orphans sweep only past the grace
  window, ref'd blobs never sweep, the gc lock lease refuses a live
  concurrent sweeper and is stolen once expired;
- a real 2-process hammer (this process publishing, a subprocess gc
  sweeping in a tight loop with a sub-second grace window) over ≥100
  iterations with ZERO lost blobs;
- SIGKILL at every CAS chaos window (mid-publish, mid-ref-write,
  mid-gc-sweep, mid-store-drain) leaves a state fsck names, gc
  converges, and never a restore-breaking dangling ref;
- CLI exit contracts: ``fsck --store`` (0 clean / 4 dangling / 3 not a
  store), snapshot ``fsck`` exit 4 on a dangling ref, ``gc --store``
  dry-run default;
- ``gc --evict-local`` interplay: refs are excluded from eviction and
  eviction is REFUSED unless the store's journal proves every ref'd
  blob remote.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from tpusnap import Snapshot, StateDict, knobs
from tpusnap import cas
from tpusnap.cas import (
    BLOBS_DIR,
    GC_LOCK_PATH,
    INTENTS_DIR,
    ROOTS_DIR,
    blob_key,
    blob_path,
    fsck_store,
    gc_store,
    read_refs_dir,
)
from tpusnap.io_types import CAS_REFS_DIR
from tpusnap.lifecycle import dual_hash_evidence, fsck_snapshot, gc_snapshot
from tpusnap.storage_plugin import url_to_storage_plugin

pytestmark = pytest.mark.cas

_SHAPE = (96, 96)
_N = 4


def _state(seed: int = 0):
    return {
        "m": StateDict(
            **{
                f"w{i}": np.random.default_rng(seed * 100 + i)
                .standard_normal(_SHAPE)
                .astype(np.float32)
                for i in range(_N)
            }
        )
    }


def _zeros():
    return {
        "m": StateDict(
            **{f"w{i}": np.zeros(_SHAPE, np.float32) for i in range(_N)}
        )
    }


def _assert_eq(a, b):
    for k in a["m"]:
        assert np.array_equal(np.asarray(a["m"][k]), np.asarray(b["m"][k])), k


@pytest.fixture(autouse=True)
def _isolated_cas_env(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUSNAP_TELEMETRY_DIR", str(tmp_path / "tele"))
    monkeypatch.setenv("TPUSNAP_HISTORY", "0")
    # Payload blobs must reach the CAS write path individually (slab
    # objects are uuid-named per take and deliberately never dedup).
    monkeypatch.setenv("TPUSNAP_DISABLE_BATCHING", "1")
    yield


# ------------------------------------------------- store construction


def _mk_store(root: str) -> str:
    for d in (BLOBS_DIR, INTENTS_DIR, ROOTS_DIR):
        os.makedirs(os.path.join(root, d), exist_ok=True)
    return root


def _put_blob(store: str, data: bytes) -> str:
    key = blob_key(dual_hash_evidence(data))
    with open(os.path.join(store, blob_path(key)), "wb") as f:
        f.write(data)
    return key


def _put_ref_snapshot(store: str, snap_dir: str, loc: str, data: bytes):
    """Hand-build a snapshot dir holding one ref, rooted in the store."""
    triple = dual_hash_evidence(data)
    refs_dir = os.path.join(snap_dir, CAS_REFS_DIR)
    os.makedirs(refs_dir, exist_ok=True)
    with open(os.path.join(refs_dir, "rank_0.json"), "w") as f:
        json.dump(
            {"version": 1, "store": store, "refs": {loc: list(triple)}}, f
        )
    digest = cas._root_digest(os.path.abspath(snap_dir))
    with open(os.path.join(store, ROOTS_DIR, digest), "w") as f:
        json.dump({"dir": os.path.abspath(snap_dir), "ts": time.time()}, f)
    return blob_key(triple)


def _backdate(path: str, seconds: float) -> None:
    t = time.time() - seconds
    os.utime(path, (t, t))


# ---------------------------------------------- intent/ref/grace matrix


def test_blob_key_from_triple():
    nbytes, crc, xxh = dual_hash_evidence(b"payload bytes")
    key = blob_key((nbytes, crc, xxh))
    assert key == f"{crc.split(':')[1]}-{xxh.split(':')[1]}"
    assert blob_path(key) == f"blobs/{key}"


def test_orphan_sweeps_only_past_grace(tmp_path):
    store = _mk_store(str(tmp_path / "store"))
    key = _put_blob(store, b"orphan content")
    # Young orphan: inside the grace window, protected.
    rep = gc_store(store, dry_run=False, grace_s=60.0)
    assert not rep.reclaimed and rep.kept_young == 1
    assert os.path.exists(os.path.join(store, blob_path(key)))
    # Aged past grace: swept.
    _backdate(os.path.join(store, blob_path(key)), 120.0)
    rep = gc_store(store, dry_run=False, grace_s=60.0)
    assert blob_path(key) in rep.reclaimed
    assert not os.path.exists(os.path.join(store, blob_path(key)))


def test_fresh_intent_protects_unrooted_blob(tmp_path):
    store = _mk_store(str(tmp_path / "store"))
    key = _put_blob(store, b"mid-publish content")
    _backdate(os.path.join(store, blob_path(key)), 999.0)
    intent = os.path.join(store, INTENTS_DIR, f"{key}__555-abc")
    with open(intent, "w") as f:
        json.dump({"ts": time.time(), "job": "j1"}, f)
    # Fresh intent: the publisher is inside the publish-to-ref window —
    # the blob must survive even though it is old and unreferenced.
    rep = gc_store(store, dry_run=False, grace_s=60.0)
    assert blob_path(key) not in rep.reclaimed
    assert os.path.exists(os.path.join(store, blob_path(key)))
    # Stale intent: protection lapses; both intent and blob sweep.
    _backdate(intent, 120.0)
    rep = gc_store(store, dry_run=False, grace_s=60.0)
    assert blob_path(key) in rep.reclaimed
    assert f"{INTENTS_DIR}/{key}__555-abc" in rep.reclaimed


def test_refd_blob_never_sweeps_and_root_goes_stale(tmp_path):
    store = _mk_store(str(tmp_path / "store"))
    data = b"shared content" * 64
    snap = str(tmp_path / "snapA")
    key = _put_ref_snapshot(store, snap, "0/w", data)
    _put_blob(store, data)
    _backdate(os.path.join(store, blob_path(key)), 9999.0)
    rep = gc_store(store, dry_run=False, grace_s=60.0)
    assert blob_path(key) not in rep.reclaimed and rep.marked == 1
    # Snapshot dir deleted -> the root is stale; past grace the root
    # record sweeps, and with it the blob's liveness.
    import shutil

    shutil.rmtree(snap)
    for name in os.listdir(os.path.join(store, ROOTS_DIR)):
        _backdate(os.path.join(store, ROOTS_DIR, name), 120.0)
    rep = gc_store(store, dry_run=False, grace_s=60.0)
    assert any(p.startswith(ROOTS_DIR + "/") for p in rep.reclaimed)
    assert blob_path(key) in rep.reclaimed


def test_gc_lease_refuses_live_steals_expired(tmp_path, monkeypatch):
    store = _mk_store(str(tmp_path / "store"))
    now = 1_000_000.0
    monkeypatch.setattr(cas, "_wall", lambda: now)
    with open(os.path.join(store, GC_LOCK_PATH), "w") as f:
        json.dump({"owner": "other-host:1", "expires_at": now + 30.0}, f)
    with pytest.raises(RuntimeError, match="lease"):
        gc_store(store, dry_run=False, grace_s=60.0)
    # Dry-run never takes the lease, so it is never refused.
    gc_store(store, dry_run=True, grace_s=60.0)
    # Fake clock past expiry: the abandoned lease is stolen.
    monkeypatch.setattr(cas, "_wall", lambda: now + 60.0)
    rep = gc_store(store, dry_run=False, grace_s=60.0)
    assert not rep.errors


def test_torn_publish_named_and_swept(tmp_path):
    store = _mk_store(str(tmp_path / "store"))
    torn = os.path.join(store, BLOBS_DIR, "deadbeef-0123456789abcdef.tmp.42")
    with open(torn, "wb") as f:
        f.write(b"half a blob")
    rep = fsck_store(store)
    assert rep.state == "store"
    assert rep.torn_publishes == [
        f"{BLOBS_DIR}/deadbeef-0123456789abcdef.tmp.42"
    ]
    _backdate(torn, 120.0)
    g = gc_store(store, dry_run=False, grace_s=60.0)
    assert f"{BLOBS_DIR}/deadbeef-0123456789abcdef.tmp.42" in g.reclaimed


def test_refcount_cache_divergence_detected_and_rederived(tmp_path):
    store = _mk_store(str(tmp_path / "store"))
    data = b"counted content"
    key = _put_ref_snapshot(store, str(tmp_path / "snap"), "0/w", data)
    _put_blob(store, data)
    with open(os.path.join(store, cas.REFCOUNTS_PATH), "w") as f:
        json.dump({key: 7, "bogus-key": 1}, f)
    rep = fsck_store(store)
    assert key in rep.refcount_divergence
    assert "bogus-key" in rep.refcount_divergence
    # gc rewrites the advisory cache from fresh marks.
    gc_store(store, dry_run=False, grace_s=60.0)
    with open(os.path.join(store, cas.REFCOUNTS_PATH)) as f:
        assert json.load(f) == {key: 1}
    assert not fsck_store(store).refcount_divergence


def test_dangling_ref_is_the_exit4_state(tmp_path):
    from tpusnap.__main__ import main as cli_main

    store = _mk_store(str(tmp_path / "store"))
    snap = str(tmp_path / "snap")
    _put_ref_snapshot(store, snap, "0/w", b"vanished content")
    # The ref's blob was never published (or was lost): DANGLING.
    rep = fsck_store(store)
    assert rep.dangling and rep.dangling[0]["location"] == "0/w"
    assert cli_main(["fsck", "--store", store]) == 4
    assert cli_main(["fsck", "--store", str(tmp_path / "nope")]) == 3


# --------------------------------------------------- two-job e2e dedup


def test_two_jobs_share_one_base_storage(tmp_path):
    store = str(tmp_path / "store")
    s = _state(7)
    with knobs.override_cas(store):
        Snapshot.take(str(tmp_path / "jobA"), s)
        Snapshot.take(str(tmp_path / "jobB"), s)
        out = _zeros()
        Snapshot(str(tmp_path / "jobB")).restore(out)
        _assert_eq(out, s)
        rep = fsck_store(store)
        assert rep.state == "store" and not rep.dangling
        # ~1x aggregate: one blob per unique tensor, each refcount 2.
        assert len(rep.blobs) == _N
        assert sorted(rep.referenced.values()) == [2] * _N
        for job in ("jobA", "jobB"):
            fa = fsck_snapshot(str(tmp_path / job))
            assert fa.state == "committed"
            assert fa.cas_refs == _N and not fa.cas_dangling
            # No private payload copies on disk.
            payload = [
                f
                for d, _, fs in os.walk(str(tmp_path / job))
                if CAS_REFS_DIR.split("/")[0] not in d
                for f in fs
                if f != ".snapshot_metadata"
            ]
            assert not payload, payload
        # gc converges to a no-op on the healthy store.
        g = gc_store(store, dry_run=False, grace_s=0.0)
        assert not g.reclaimed and g.marked == _N


def test_deleting_one_job_keeps_shared_blobs(tmp_path):
    import shutil

    store = str(tmp_path / "store")
    s = _state(3)
    with knobs.override_cas(store):
        Snapshot.take(str(tmp_path / "jobA"), s)
        Snapshot.take(str(tmp_path / "jobB"), s)
        shutil.rmtree(str(tmp_path / "jobA"))
        for name in os.listdir(os.path.join(store, ROOTS_DIR)):
            _backdate(os.path.join(store, ROOTS_DIR, name), 120.0)
        gc_store(store, dry_run=False, grace_s=60.0)
        # jobB's refs keep every blob alive.
        fb = fsck_snapshot(str(tmp_path / "jobB"))
        assert not fb.cas_dangling
        out = _zeros()
        Snapshot(str(tmp_path / "jobB")).restore(out)
        _assert_eq(out, s)
        # Now the last root goes too: blobs become orphans and sweep.
        shutil.rmtree(str(tmp_path / "jobB"))
        for name in os.listdir(os.path.join(store, ROOTS_DIR)):
            _backdate(os.path.join(store, ROOTS_DIR, name), 120.0)
        for name in os.listdir(os.path.join(store, BLOBS_DIR)):
            _backdate(os.path.join(store, BLOBS_DIR, name), 120.0)
        rep = gc_store(store, dry_run=False, grace_s=60.0)
        assert len([p for p in rep.reclaimed if p.startswith("blobs/")]) == _N


def test_snapshot_gc_prunes_stale_refs(tmp_path):
    store = str(tmp_path / "store")
    with knobs.override_cas(store):
        path = str(tmp_path / "snap")
        Snapshot.take(path, _state(1))
        # Retake under DIFFERENT tensor names: the old locations vanish
        # from the manifest but their refs linger in the rank record.
        rng = np.random.default_rng(2)
        Snapshot.take(
            path,
            {
                "m": StateDict(
                    v=rng.standard_normal(_SHAPE).astype(np.float32)
                )
            },
        )
        refs, _ = read_refs_dir(path)
        assert len(refs) == _N + 1  # stale w0..w3 + live v
        gc_snapshot(path, dry_run=False)
        refs_after, _ = read_refs_dir(path)
        from tpusnap.lifecycle import _referenced_locations

        md = fsck_snapshot(path).metadata
        assert set(refs_after) <= _referenced_locations(md)
        assert len(refs_after) == 1
        assert not fsck_snapshot(path).cas_dangling


# ------------------------------------------------------ chaos windows


_CHAOS_TAKE = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from tpusnap import Snapshot, StateDict

path, seed = sys.argv[1], int(sys.argv[2])
state = {
    "m": StateDict(**{
        f"w{i}": np.random.default_rng(seed * 100 + i)
        .standard_normal((96, 96)).astype(np.float32)
        for i in range(4)
    })
}
print("READY", flush=True)
Snapshot.take(path, state)
print("DONE", flush=True)
"""


def _run_chaos_child(
    path: str, seed: int, env_extra: dict, timeout: float = 120.0
):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        TPUSNAP_DISABLE_BATCHING="1",
        TPUSNAP_HISTORY="0",
        **env_extra,
    )
    proc = subprocess.run(
        [sys.executable, "-c", _CHAOS_TAKE, path, str(seed)],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    return proc


@pytest.mark.chaos
@pytest.mark.parametrize(
    "fault,window",
    [
        ("crash_after_op=write_atomic:2", "mid-publish"),
        ("crash_after_op=cas_ref:1", "mid-ref-write"),
    ],
)
def test_sigkill_chaos_windows_converge(tmp_path, fault, window):
    """SIGKILL inside a CAS window: fsck names the resulting state, a
    second job still commits the same content, gc converges, and no
    committed snapshot ever holds a dangling ref."""
    store = str(tmp_path / "store")
    if window == "mid-publish":
        # Chaos on the STORE plugin: the child dies right after a store
        # write (intent or blob publish) — before its ref lands.
        env = {
            "TPUSNAP_CAS_DIR": f"chaos+fs://{store}",
            "TPUSNAP_FAULT_SPEC": fault,
        }
        snap_url = str(tmp_path / "jobA")
    else:
        # Chaos on the SNAPSHOT plugin: the child dies right after its
        # first ref-record flush (the cas_ref chaos kind).
        env = {"TPUSNAP_CAS_DIR": store}
        snap_url = f"chaos+fs://{tmp_path / 'jobA'}"
        env["TPUSNAP_FAULT_SPEC"] = fault
    proc = _run_chaos_child(snap_url, 5, env)
    assert proc.returncode == -signal.SIGKILL, (
        proc.returncode,
        proc.stdout,
        proc.stderr,
    )
    assert "DONE" not in proc.stdout

    # fsck names the state on both sides; nothing is "corrupt".
    srep = fsck_store(store)
    assert srep.state == "store"
    assert not srep.dangling  # a never-committed take cannot dangle
    frep = fsck_snapshot(str(tmp_path / "jobA"))
    assert frep.state in ("torn", "empty", "committed")

    # A concurrent/second job taking the SAME content converges: it
    # adopts published blobs (or republishes) and commits cleanly.
    proc2 = _run_chaos_child(
        str(tmp_path / "jobB"), 5, {"TPUSNAP_CAS_DIR": store}
    )
    assert proc2.returncode == 0, proc2.stderr
    fb = fsck_snapshot(str(tmp_path / "jobB"))
    assert fb.state == "committed" and not fb.cas_dangling

    # gc converges: heal the torn job dir, sweep store debris; the
    # committed job's refs all still resolve and it restores bit-exact.
    if frep.state == "torn":
        gc_snapshot(str(tmp_path / "jobA"), dry_run=False, reclaim_torn=True)
    for sub in (BLOBS_DIR, INTENTS_DIR, ROOTS_DIR):
        d = os.path.join(store, sub)
        for name in os.listdir(d) if os.path.isdir(d) else []:
            _backdate(os.path.join(d, name), 120.0)
    g = gc_store(store, dry_run=False, grace_s=60.0)
    assert not g.errors
    fb = fsck_snapshot(str(tmp_path / "jobB"))
    assert not fb.cas_dangling, fb.cas_dangling
    with knobs.override_cas(store):
        out = _zeros()
        Snapshot(str(tmp_path / "jobB")).restore(out)
        _assert_eq(out, _state(5))


@pytest.mark.chaos
def test_sigkill_mid_gc_sweep_converges(tmp_path, monkeypatch):
    """A gc SIGKILLed mid-sweep (chaos ``delete`` kill on the store
    plugin) leaves a state fsck names; a re-run gc converges and live
    refs are untouched."""
    store = _mk_store(str(tmp_path / "store"))
    data = b"live content" * 32
    key_live = _put_ref_snapshot(store, str(tmp_path / "snap"), "0/w", data)
    _put_blob(store, data)
    orphans = [_put_blob(store, b"orphan-%d" % i * 40) for i in range(6)]
    for name in os.listdir(os.path.join(store, BLOBS_DIR)):
        _backdate(os.path.join(store, BLOBS_DIR, name), 600.0)

    child = (
        "import sys\n"
        "from tpusnap.cas import gc_store\n"
        "gc_store(sys.argv[1], dry_run=False, grace_s=60.0)\n"
    )
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        TPUSNAP_FAULT_SPEC="crash_after_op=delete:2",
    )
    proc = subprocess.run(
        [sys.executable, "-c", child, f"chaos+fs://{store}"],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == -signal.SIGKILL, (proc.returncode, proc.stderr)

    # Mid-sweep state: some orphans gone, some left; the lease may be
    # stranded. fsck still names everything and the live blob is safe.
    rep = fsck_store(store)
    assert rep.state == "store" and not rep.dangling
    assert key_live in rep.referenced
    # Re-run converges: past its TTL the dead sweeper's lease is STOLEN
    # (fake-forward the clock rather than sleeping out the 60 s default).
    monkeypatch.setattr(cas, "_wall", lambda: time.time() + 120.0)
    g = gc_store(store, dry_run=False, grace_s=60.0, lease_ttl_s=0.0)
    assert not g.errors
    rep = fsck_store(store)
    assert not rep.orphans and not rep.dangling
    assert os.path.exists(os.path.join(store, blob_path(key_live)))
    for k in orphans:
        assert not os.path.exists(os.path.join(store, blob_path(k)))


@pytest.mark.chaos
def test_sigkill_mid_store_drain_resumes(tmp_path):
    """A store drain SIGKILLed mid-upload re-runs to convergence, with
    the already-journaled blobs skipped via hash evidence."""
    store = _mk_store(str(tmp_path / "store"))
    keys = [
        _put_blob(store, b"drain-me-%d" % i * 512) for i in range(6)
    ]
    remote = str(tmp_path / "mirror")
    child = (
        "import sys\n"
        "from tpusnap.cas import drain_store\n"
        "r = drain_store(sys.argv[1], remote_url=sys.argv[2])\n"
        "print(r.summary())\n"
    )
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        TPUSNAP_FAULT_SPEC="crash_after_op=write_atomic:2",
    )
    proc = subprocess.run(
        [sys.executable, "-c", child, store, f"chaos+fs://{remote}"],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == -signal.SIGKILL, (proc.returncode, proc.stderr)
    journal = cas.read_store_journal(store)
    assert journal is not None and 0 < len(journal["blobs"]) < len(keys)

    rep = cas.drain_store(store, remote_url=f"fs://{remote}")
    assert rep.state == "durable", rep.summary()
    assert rep.skipped >= 1  # journaled evidence licensed skips
    proven, _ = cas.store_remote_evidence(store, set(keys))
    assert proven == set(keys)
    for k in keys:
        assert os.path.exists(os.path.join(remote, blob_path(k)))


# ------------------------------------------------- 2-process hammer


def test_publisher_vs_gc_hammer_zero_lost_blobs(tmp_path):
    """One process publishing through the full CAS plugin protocol, a
    REAL second process gc-sweeping in a tight loop with a sub-second
    grace window: ≥100 publishes, zero lost blobs (every committed ref
    resolves, every location reads back bit-exact)."""
    store = str(tmp_path / "store")
    snap = str(tmp_path / "snap")
    beacon = str(tmp_path / "sweeps")
    gc_child = (
        "import sys, time\n"
        "from tpusnap.cas import gc_store\n"
        "store, beacon = sys.argv[1], sys.argv[2]\n"
        "end = time.monotonic() + 120\n"
        "sweeps = 0\n"
        "while time.monotonic() < end:\n"
        "    try:\n"
        "        gc_store(store, dry_run=False, grace_s=0.5,\n"
        "                 lease_ttl_s=5.0, owner='hammer-gc')\n"
        "        sweeps += 1\n"
        "        with open(beacon, 'w') as f:\n"
        "            f.write(str(sweeps))\n"
        "    except RuntimeError:\n"
        "        pass\n"
        "    time.sleep(0.002)\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    sweeper = subprocess.Popen(
        [sys.executable, "-c", gc_child, store, beacon], env=env
    )

    def _sweeps() -> int:
        try:
            with open(beacon) as f:
                return int(f.read() or 0)
        except (OSError, ValueError):
            return 0

    try:
        import asyncio

        from tpusnap.io_types import ReadIO, WriteIO

        with knobs.override_cas(store):
            plugin = url_to_storage_plugin(snap)
        contents = {}
        iterations = 120
        # The race only exists once the sweeper is ALIVE: wait for its
        # first completed gc pass before publishing anything.
        deadline = time.monotonic() + 60
        while _sweeps() == 0:
            assert sweeper.poll() is None, "gc sweeper died before start"
            assert time.monotonic() < deadline, "gc sweeper never swept"
            time.sleep(0.01)
        sweeps_at_start = _sweeps()

        async def hammer():
            for i in range(iterations):
                # A rotating content pool: repeats exercise the ADOPT
                # path against blobs the sweeper is racing to age out;
                # overwritten locations feed it a steady orphan diet.
                data = (b"hammer-%d|" % (i % 9)) * 257
                loc = f"0/blob_{i % 24}"
                await plugin.write(WriteIO(path=loc, buf=data))
                contents[loc] = data
                # Zero-lost-blobs invariant, checked at full race
                # pressure: the ref just flushed MUST resolve.
                read_io = ReadIO(path=loc)
                await plugin.read(read_io)
                assert read_io.buf.getvalue() == data, (
                    f"iteration {i}: lost blob under gc race ({loc})"
                )
                # Pace the publisher across the sweeper's cadence so a
                # fast machine cannot finish before gc ever interleaves.
                if i % 24 == 23:
                    target = sweeps_at_start + (i // 24) + 1
                    pace = time.monotonic() + 10
                    while _sweeps() < target and time.monotonic() < pace:
                        await asyncio.sleep(0.005)
            await plugin.close()

        asyncio.run(hammer())
        assert _sweeps() > sweeps_at_start, "gc never ran during the hammer"
    finally:
        sweeper.terminate()
        sweeper.wait(timeout=30)

    # Post-hammer: the final refs all resolve through a FRESH plugin
    # (nothing cached), and a final gc converges with zero dangling.
    # A sweeper terminated mid-sweep leaves its 5s lease live; wait it
    # out instead of racing the steal window.
    gc_deadline = time.monotonic() + 15
    while True:
        try:
            gc_store(store, dry_run=False, grace_s=0.5, lease_ttl_s=0.0)
            break
        except RuntimeError:
            assert time.monotonic() < gc_deadline, (
                "terminated sweeper's gc lease never expired"
            )
            time.sleep(0.25)
    refs, _ = read_refs_dir(snap)
    assert len(refs) == 24
    import asyncio

    from tpusnap.io_types import ReadIO

    with knobs.override_cas(store):
        fresh = url_to_storage_plugin(snap)

    async def verify():
        for loc, data in contents.items():
            read_io = ReadIO(path=loc)
            await fresh.read(read_io)
            assert read_io.buf.getvalue() == data, f"lost blob at {loc}"
        await fresh.close()

    asyncio.run(verify())


# ------------------------------------------ evict-local interplay


def test_evict_local_refuses_without_store_remote_evidence(tmp_path):
    """A tiered snapshot whose payload is CAS refs must not evict on
    its OWN durable marker: the store's journal has to prove every
    ref'd blob remote first."""
    store = str(tmp_path / "store")
    cache = str(tmp_path / "cache")
    remote_root = str(tmp_path / "remote")
    url = f"tier+local={cache}+remote=fs://{remote_root}/snap"
    s = _state(11)
    with knobs.override_cas(store):
        Snapshot.take(url, s)
        from tpusnap.tiering import drain_snapshot

        # The store has no remote mirror yet: the drain must refuse the
        # durable marker (shared blobs have no durable copy elsewhere).
        rep = drain_snapshot(url, deadline_s=30.0)
        assert rep.state != "durable", rep.summary()
        assert rep.cas_refs == _N
        with pytest.raises(RuntimeError):
            gc_snapshot(url, dry_run=False, evict_local=True)

        # Give the store a remote; drain store-level, then the snapshot
        # drain converges and eviction is licensed — but ref'd
        # locations are EXCLUDED from the delete set (deleting a ref
        # would drop the liveness root other jobs may rely on).
        store_remote = str(tmp_path / "store_mirror")
        with open(os.path.join(store, cas.CONFIG_PATH), "w") as f:
            json.dump({"remote": f"fs://{store_remote}"}, f)
        rep = drain_snapshot(url, deadline_s=60.0)
        assert rep.state == "durable", rep.summary()
        assert rep.cas_blobs_uploaded == _N
        local_dir = os.path.join(cache, os.path.abspath(remote_root)[1:], "snap")
        from tpusnap.tiering import parse_tier_url

        local_dir = parse_tier_url(url).local_dir
        monkey_retention = dict(os.environ)
        os.environ["TPUSNAP_TIER_LOCAL_RETENTION_S"] = "0"
        try:
            g = gc_snapshot(url, dry_run=False, evict_local=True)
        finally:
            os.environ.clear()
            os.environ.update(monkey_retention)
        assert not g.errors
        refs, _ = read_refs_dir(local_dir)
        assert len(refs) == _N  # refs survived eviction
        out = _zeros()
        Snapshot(url).restore(out)
        _assert_eq(out, s)


# ------------------------------------------------- CLI exit contracts


def test_cli_gc_store_dry_run_default(tmp_path):
    from tpusnap.__main__ import main as cli_main

    store = _mk_store(str(tmp_path / "store"))
    key = _put_blob(store, b"reclaim me")
    _backdate(os.path.join(store, blob_path(key)), 9999.0)
    assert cli_main(["gc", "--store", store]) == 0
    assert os.path.exists(os.path.join(store, blob_path(key)))  # dry-run
    assert cli_main(["gc", "--store", store, "--force"]) == 0
    assert not os.path.exists(os.path.join(store, blob_path(key)))


def test_cli_info_prints_cas_summary(tmp_path, capsys):
    from tpusnap.__main__ import main as cli_main

    store = str(tmp_path / "store")
    with knobs.override_cas(store):
        Snapshot.take(str(tmp_path / "snap"), _state(0))
        assert cli_main(["info", str(tmp_path / "snap")]) == 0
    out = capsys.readouterr().out
    assert "cas:" in out and "ref(s) into" in out
    assert "deduplicated" in out
